"""mcpack2pb — mcpack v2 codec + protobuf bridge.

Analog of reference src/mcpack2pb/ (parser.cpp/serializer.cpp +
generator.cpp protoc plugin): mcpack is Baidu's binary JSON; the
reference generates per-message converters at protoc time, this module
converts at runtime through message descriptors (same approach as
json2pb).

DESIGN DEVIATION (deliberate): the reference's protoc plugin
(generator.cpp:1346,1424) exists because C++ needs codegen for
reflection-speed conversion; Python message descriptors already carry
full reflection, so a runtime walk is the idiomatic binding with
identical wire behavior. Wire compatibility with compack/mcpack v2
producers is pinned by hand-built byte corpora in
tests/test_mcpack_trackme.py (test_mcpack_conformance_corpus).

Wire facts (field_type.h, parser.cpp:27-81):

  head:  fixed (2B: type,name_size) when type&0x0F != 0 — value size is
         type&0x0F; short (3B: type|0x80,name_size,value_size u8) for
         strings<=254 / binary<=255; long (6B: type,name_size,
         value_size u32le) otherwise.
  names: C strings, name_size includes the terminating 0.
  OBJECT/ARRAY (0x10/0x20): long head; value = u32le item_count + items.
  ISOARRAY (0x30): long head; value = u8 item_type + packed values.
  STRING (0x50): value includes trailing 0.  BINARY (0x60): raw bytes.
  ints 0x11/12/14/18, uints 0x21/22/24/28, BOOL 0x31, FLOAT 0x44,
  DOUBLE 0x48, NULL 0x61 (one 0 byte).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

F_OBJECT, F_ARRAY, F_ISOARRAY = 0x10, 0x20, 0x30
F_STRING, F_BINARY = 0x50, 0x60
F_INT8, F_INT16, F_INT32, F_INT64 = 0x11, 0x12, 0x14, 0x18
F_UINT8, F_UINT16, F_UINT32, F_UINT64 = 0x21, 0x22, 0x24, 0x28
F_BOOL, F_FLOAT, F_DOUBLE, F_NULL = 0x31, 0x44, 0x48, 0x61
_SHORT_MASK = 0x80
_FIXED_MASK = 0x0F

_FIXED_FMT = {
    F_INT8: "<b", F_INT16: "<h", F_INT32: "<i", F_INT64: "<q",
    F_UINT8: "<B", F_UINT16: "<H", F_UINT32: "<I", F_UINT64: "<Q",
    F_FLOAT: "<f", F_DOUBLE: "<d",
}


# ---------------------------------------------------------------------------
# encode: python value -> mcpack field bytes
# ---------------------------------------------------------------------------
def _head(ftype: int, name: bytes, value_size: int) -> bytes:
    if ftype & _FIXED_MASK:
        return struct.pack("<BB", ftype, len(name)) + name
    if ftype in (F_STRING, F_BINARY) and value_size <= (254 if ftype == F_STRING else 255):
        return struct.pack("<BBB", ftype | _SHORT_MASK, len(name), value_size) + name
    return struct.pack("<BBI", ftype, len(name), value_size) + name


def _name_bytes(name: Optional[str]) -> bytes:
    if not name:
        return b"\x00"
    return name.encode() + b"\x00"


def _int_type(v: int) -> Tuple[int, bytes]:
    for t in (F_INT8, F_INT16, F_INT32, F_INT64):
        try:
            return t, struct.pack(_FIXED_FMT[t], v)
        except struct.error:
            continue
    return F_UINT64, struct.pack("<Q", v)


def encode_field(name: Optional[str], v) -> bytes:
    nb = _name_bytes(name)
    if isinstance(v, bool):
        return _head(F_BOOL, nb, 1) + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        t, raw = _int_type(v)
        return _head(t, nb, len(raw)) + raw
    if isinstance(v, float):
        return _head(F_DOUBLE, nb, 8) + struct.pack("<d", v)
    if isinstance(v, str):
        raw = v.encode() + b"\x00"
        return _head(F_STRING, nb, len(raw)) + raw
    if isinstance(v, bytes):
        return _head(F_BINARY, nb, len(v)) + v
    if v is None:
        return _head(F_NULL, nb, 1) + b"\x00"
    if isinstance(v, dict):
        items = b"".join(encode_field(k, item) for k, item in v.items())
        value = struct.pack("<I", len(v)) + items
        return _head(F_OBJECT, nb, len(value)) + value
    if isinstance(v, (list, tuple)):
        items = b"".join(encode_field(None, item) for item in v)
        value = struct.pack("<I", len(v)) + items
        return _head(F_ARRAY, nb, len(value)) + value
    raise TypeError(f"mcpack: unsupported type {type(v)}")


def dumps(doc: Dict) -> bytes:
    """Serialize a dict as the root mcpack OBJECT."""
    return encode_field(None, doc)


# ---------------------------------------------------------------------------
# decode: mcpack field bytes -> python value
# ---------------------------------------------------------------------------
def _decode_field(data: bytes, pos: int) -> Tuple[str, object, int]:
    """→ (name, value, next_pos)."""
    first = data[pos]
    if first & _FIXED_MASK:
        ftype = first
        name_size = data[pos + 1]
        vstart = pos + 2 + name_size
        vsize = ftype & _FIXED_MASK
    elif first & _SHORT_MASK:
        ftype = first & ~_SHORT_MASK
        name_size = data[pos + 1]
        vsize = data[pos + 2]
        vstart = pos + 3 + name_size
    else:
        ftype = first
        name_size = data[pos + 1]
        (vsize,) = struct.unpack_from("<I", data, pos + 2)
        vstart = pos + 6 + name_size
    name = data[vstart - name_size : vstart - 1].decode("utf-8", "replace") if name_size else ""
    end = vstart + vsize
    if end > len(data):
        raise ValueError("mcpack field truncated")
    raw = data[vstart:end]
    if ftype in _FIXED_FMT:
        value = struct.unpack(_FIXED_FMT[ftype], raw)[0]
    elif ftype == F_BOOL:
        value = raw[0] != 0
    elif ftype == F_NULL:
        value = None
    elif ftype == F_STRING:
        value = raw[:-1].decode("utf-8", "replace")
    elif ftype == F_BINARY:
        value = raw
    elif ftype in (F_OBJECT, F_ARRAY):
        (count,) = struct.unpack_from("<I", raw, 0)
        cur = 4
        if ftype == F_OBJECT:
            obj: Dict = {}
            for _ in range(count):
                k, v, nxt = _decode_field(raw, cur)
                obj[k] = v
                cur = nxt
            value = obj
        else:
            arr = []
            for _ in range(count):
                _, v, nxt = _decode_field(raw, cur)
                arr.append(v)
                cur = nxt
            value = arr
    elif ftype == F_ISOARRAY:
        item_type = raw[0]
        fmt = _FIXED_FMT.get(item_type)
        if fmt is None:
            raise ValueError(f"mcpack: bad isoarray item type 0x{item_type:02x}")
        isz = item_type & _FIXED_MASK
        value = [
            struct.unpack_from(fmt, raw, 1 + i * isz)[0]
            for i in range((len(raw) - 1) // isz)
        ]
    else:
        raise ValueError(f"mcpack: unknown field type 0x{ftype:02x}")
    return name, value, end


def loads(data: bytes) -> Dict:
    name, value, _ = _decode_field(data, 0)
    if not isinstance(value, dict):
        raise ValueError("mcpack root is not an object")
    return value


# ---------------------------------------------------------------------------
# protobuf bridge (the mcpack2pb purpose: pb messages as the front-end)
# ---------------------------------------------------------------------------
def proto_to_mcpack(msg) -> bytes:
    """Serialize a protobuf message as mcpack (field names = keys)."""
    return dumps(_msg_to_dict(msg))


def _msg_to_dict(msg) -> Dict:
    out = {}
    for field, value in msg.ListFields():
        if field.is_repeated:
            if field.type == field.TYPE_MESSAGE:
                out[field.name] = [_msg_to_dict(v) for v in value]
            else:
                out[field.name] = list(value)
        elif field.type == field.TYPE_MESSAGE:
            out[field.name] = _msg_to_dict(value)
        else:
            out[field.name] = value
    return out


def mcpack_to_proto(data: bytes, msg) -> Tuple[bool, str]:
    """Parse mcpack bytes into a protobuf message. → (ok, error)."""
    try:
        doc = loads(data)
    except (ValueError, IndexError, struct.error) as e:
        return False, f"bad mcpack: {e}"
    try:
        _dict_to_msg(doc, msg)
    except (TypeError, ValueError, AttributeError) as e:
        return False, f"mcpack does not fit message: {e}"
    return True, ""


def _dict_to_msg(doc: Dict, msg):
    for field in msg.DESCRIPTOR.fields:
        if field.name not in doc:
            continue
        v = doc[field.name]
        if field.is_repeated:
            target = getattr(msg, field.name)
            for item in v:
                if field.type == field.TYPE_MESSAGE:
                    _dict_to_msg(item, target.add())
                else:
                    target.append(_coerce(field, item))
        elif field.type == field.TYPE_MESSAGE:
            _dict_to_msg(v, getattr(msg, field.name))
        else:
            setattr(msg, field.name, _coerce(field, v))


def _coerce(field, v):
    if field.type == field.TYPE_STRING and isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if field.type == field.TYPE_BYTES and isinstance(v, str):
        return v.encode()
    if field.cpp_type in (field.CPPTYPE_INT32, field.CPPTYPE_INT64,
                          field.CPPTYPE_UINT32, field.CPPTYPE_UINT64):
        return int(v)
    if field.cpp_type in (field.CPPTYPE_FLOAT, field.CPPTYPE_DOUBLE):
        return float(v)
    if field.cpp_type == field.CPPTYPE_BOOL:
        return bool(v)
    return v
