"""Serialization adjuncts (reference src/json2pb/ + mcpack2pb/)."""

from incubator_brpc_tpu.serialization.json2pb import (  # noqa: F401
    json_to_proto,
    proto_to_json,
)
