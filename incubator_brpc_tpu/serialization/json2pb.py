"""JSON ↔ protobuf conversion with the reference's per-call options.

Analog of reference src/json2pb/ (json_to_pb.{h,cpp}, pb_to_json.{h,cpp},
~1,740 LoC of rapidjson streaming): a descriptor-walking converter whose
option structs mirror Json2PbOptions / Pb2JsonOptions field for field —

- ``bytes_to_base64`` / ``base64_to_bytes``: bytes fields as base64
  strings (the default) or raw latin-1 strings (the baidu-std wire's
  historical mode, pb_to_json.h:52-55 / json_to_pb.h:32-35).
- ``enum_option``: enums by name or by number (pb_to_json.h:37-39).
- ``enable_protobuf_map``: proto3 maps as JSON objects, or as the
  underlying repeated {key,value} entry list (pb_to_json.h:47-50).
- ``jsonify_empty_array``, ``always_print_primitive_fields``,
  ``pretty_json`` (pb_to_json.h:57-66).
- ``single_repeated_to_array`` / ``array_to_single_repeated``: a
  message whose only field is repeated converts to/from a bare JSON
  array (pb_to_json.h:68-70, json_to_pb.h:37-39).
- ``allow_remaining_bytes_after_parsing`` + parsed offset
  (json_to_pb.h:41-58).
- ``allow_unknown_fields``: tolerate or reject unknown JSON keys.

Error surface matches JsonToProtoMessage: (ok, error_string) tuples,
never exceptions.  64-bit integers are emitted as JSON numbers like the
reference's rapidjson writer (canonical proto3 JSON would quote them).
"""

from __future__ import annotations

import base64
import json
import math
from dataclasses import dataclass
from typing import Any, Optional, Tuple

from google.protobuf import descriptor as _desc

from incubator_brpc_tpu.utils.iobuf import IOBuf

_TYPE = _desc.FieldDescriptor

OUTPUT_ENUM_BY_NAME = "name"  # reference EnumOption (pb_to_json.h:37)
OUTPUT_ENUM_BY_NUMBER = "number"


@dataclass
class Json2PbOptions:
    """Mirrors reference Json2PbOptions (json_to_pb.h:29-44)."""

    base64_to_bytes: bool = True
    array_to_single_repeated: bool = False
    allow_remaining_bytes_after_parsing: bool = False
    allow_unknown_fields: bool = True


@dataclass
class Pb2JsonOptions:
    """Mirrors reference Pb2JsonOptions (pb_to_json.h:34-71)."""

    enum_option: str = OUTPUT_ENUM_BY_NAME
    pretty_json: bool = False
    enable_protobuf_map: bool = True
    bytes_to_base64: bool = True
    jsonify_empty_array: bool = False
    always_print_primitive_fields: bool = False
    single_repeated_to_array: bool = False


class _ConvertError(Exception):
    pass


# ---------------------------------------------------------------------------
# pb → json
# ---------------------------------------------------------------------------


def _is_map_field(f) -> bool:
    return (
        f.is_repeated
        and f.type == _TYPE.TYPE_MESSAGE
        and f.message_type.GetOptions().map_entry
    )


def _scalar_to_json(f, v, opts: Pb2JsonOptions):
    if f.type == _TYPE.TYPE_BYTES:
        if opts.bytes_to_base64:
            return base64.b64encode(v).decode("ascii")
        return v.decode("latin-1")
    if f.type == _TYPE.TYPE_ENUM:
        if opts.enum_option == OUTPUT_ENUM_BY_NUMBER:
            return v
        ev = f.enum_type.values_by_number.get(v)
        return ev.name if ev is not None else v
    if f.type in (_TYPE.TYPE_FLOAT, _TYPE.TYPE_DOUBLE):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        return v
    return v  # ints, bool, string


def _field_to_json(msg, f, opts: Pb2JsonOptions):
    if _is_map_field(f):
        entries = getattr(msg, f.name)
        vf = f.message_type.fields_by_name["value"]
        if opts.enable_protobuf_map:
            return {
                str(k): (
                    _message_to_dict(v, opts)
                    if vf.type == _TYPE.TYPE_MESSAGE
                    else _scalar_to_json(vf, v, opts)
                )
                for k, v in entries.items()
            }
        # raw entry list (reference with enable_protobuf_map=false)
        return [
            {
                "key": k,
                "value": _message_to_dict(v, opts)
                if vf.type == _TYPE.TYPE_MESSAGE
                else _scalar_to_json(vf, v, opts),
            }
            for k, v in entries.items()
        ]
    if f.is_repeated:
        items = getattr(msg, f.name)
        if f.type == _TYPE.TYPE_MESSAGE:
            return [_message_to_dict(m, opts) for m in items]
        return [_scalar_to_json(f, v, opts) for v in items]
    if f.type == _TYPE.TYPE_MESSAGE:
        return _message_to_dict(getattr(msg, f.name), opts)
    return _scalar_to_json(f, getattr(msg, f.name), opts)


def _message_to_dict(msg, opts: Pb2JsonOptions) -> dict:
    out = {}
    for f in msg.DESCRIPTOR.fields:
        if f.is_repeated:
            if not getattr(msg, f.name) and not opts.jsonify_empty_array:
                continue
            out[f.name] = _field_to_json(msg, f, opts)
            continue
        if f.type == _TYPE.TYPE_MESSAGE:
            if msg.HasField(f.name):
                out[f.name] = _field_to_json(msg, f, opts)
            continue
        # scalar: proto2 presence via HasField; proto3 default-skip
        # unless always_print_primitive_fields (pb_to_json.h:62-66)
        if f.has_presence:
            if msg.HasField(f.name):
                out[f.name] = _field_to_json(msg, f, opts)
            elif opts.always_print_primitive_fields:
                out[f.name] = _scalar_to_json(f, f.default_value, opts)
            continue
        v = getattr(msg, f.name)
        if v != f.default_value or opts.always_print_primitive_fields:
            out[f.name] = _field_to_json(msg, f, opts)
    return out


def proto_to_json_with_options(
    message, options: Optional[Pb2JsonOptions] = None
) -> Tuple[Optional[str], str]:
    """ProtoMessageToJson analog: → (json_string | None, error)."""
    opts = options or Pb2JsonOptions()
    try:
        fields = message.DESCRIPTOR.fields
        if (
            opts.single_repeated_to_array
            and len(fields) == 1
            and fields[0].is_repeated
            and not _is_map_field(fields[0])
        ):
            doc: Any = _field_to_json(message, fields[0], opts)
        else:
            doc = _message_to_dict(message, opts)
        return (
            json.dumps(doc, indent=2 if opts.pretty_json else None),
            "",
        )
    except Exception as e:  # noqa: BLE001 — (ok, error) surface
        return None, str(e)


# ---------------------------------------------------------------------------
# json → pb
# ---------------------------------------------------------------------------

_INT_TYPES = {
    _TYPE.TYPE_INT32, _TYPE.TYPE_INT64, _TYPE.TYPE_UINT32,
    _TYPE.TYPE_UINT64, _TYPE.TYPE_SINT32, _TYPE.TYPE_SINT64,
    _TYPE.TYPE_FIXED32, _TYPE.TYPE_FIXED64, _TYPE.TYPE_SFIXED32,
    _TYPE.TYPE_SFIXED64,
}


def _scalar_from_json(f, v, opts: Json2PbOptions):
    if f.type == _TYPE.TYPE_BYTES:
        if not isinstance(v, str):
            raise _ConvertError(f"expect string for bytes field {f.name}")
        if opts.base64_to_bytes:
            try:
                return base64.b64decode(v, validate=True)
            except Exception as e:  # noqa: BLE001
                raise _ConvertError(
                    f"invalid base64 in field {f.name}: {e}"
                ) from e
        return v.encode("latin-1")
    if f.type == _TYPE.TYPE_ENUM:
        if isinstance(v, str):
            ev = f.enum_type.values_by_name.get(v)
            if ev is None:
                raise _ConvertError(f"unknown enum value {v!r} for {f.name}")
            return ev.number
        if isinstance(v, int) and not isinstance(v, bool):
            return v
        raise _ConvertError(f"invalid enum value for {f.name}")
    if f.type == _TYPE.TYPE_BOOL:
        if not isinstance(v, bool):
            raise _ConvertError(f"expect bool for field {f.name}")
        return v
    if f.type in _INT_TYPES:
        if isinstance(v, bool) or not isinstance(v, (int, str)):
            raise _ConvertError(f"expect integer for field {f.name}")
        try:
            return int(v)
        except ValueError as e:
            raise _ConvertError(
                f"expect integer for field {f.name}: {v!r}"
            ) from e
    if f.type in (_TYPE.TYPE_FLOAT, _TYPE.TYPE_DOUBLE):
        if v in ("NaN", "Infinity", "-Infinity"):
            return float(v.replace("Infinity", "inf"))
        if isinstance(v, bool) or not isinstance(v, (int, float, str)):
            raise _ConvertError(f"expect number for field {f.name}")
        try:
            # canonical proto3 JSON allows quoted numbers; json_format
            # accepted them, so the restful path must keep doing so
            return float(v)
        except ValueError as e:
            raise _ConvertError(
                f"expect number for field {f.name}: {v!r}"
            ) from e
    if f.type == _TYPE.TYPE_STRING:
        if not isinstance(v, str):
            raise _ConvertError(f"expect string for field {f.name}")
        return v
    raise _ConvertError(f"unsupported field type {f.type} for {f.name}")


def _set_map_field(msg, f, v, opts: Json2PbOptions):
    target = getattr(msg, f.name)
    kf = f.message_type.fields_by_name["key"]
    vf = f.message_type.fields_by_name["value"]

    def coerce_key(k):
        if kf.type == _TYPE.TYPE_STRING:
            return k
        if kf.type == _TYPE.TYPE_BOOL:
            return k in ("true", "True", True)
        return int(k)

    def set_entry(k, val):
        if vf.type == _TYPE.TYPE_MESSAGE:
            _dict_to_message(val, target[coerce_key(k)], opts)
        else:
            target[coerce_key(k)] = _scalar_from_json(vf, val, opts)

    if isinstance(v, dict):
        for k, val in v.items():
            set_entry(k, val)
        return
    if isinstance(v, list):  # repeated {key,value} entry form
        for entry in v:
            if not isinstance(entry, dict) or "key" not in entry:
                raise _ConvertError(f"bad map entry for {f.name}")
            set_entry(entry["key"], entry.get("value"))
        return
    raise _ConvertError(f"expect object/array for map field {f.name}")


_JSON_NAME_CACHE: dict = {}  # descriptor → {json_name: field}


def _json_names(descriptor):
    m = _JSON_NAME_CACHE.get(descriptor)
    if m is None:
        m = _JSON_NAME_CACHE[descriptor] = {
            f.json_name: f for f in descriptor.fields
        }
    return m


def _dict_to_message(doc, msg, opts: Json2PbOptions):
    if not isinstance(doc, dict):
        raise _ConvertError(
            f"expect JSON object for message {msg.DESCRIPTOR.name}"
        )
    by_name = msg.DESCRIPTOR.fields_by_name
    by_json = _json_names(msg.DESCRIPTOR)
    for key, v in doc.items():
        f = by_name.get(key) or by_json.get(key)
        if f is None:
            if opts.allow_unknown_fields:
                continue
            raise _ConvertError(f"unknown field {key!r}")
        if v is None:
            continue
        if _is_map_field(f):
            _set_map_field(msg, f, v, opts)
        elif f.is_repeated:
            if not isinstance(v, list):
                raise _ConvertError(f"expect array for repeated {f.name}")
            tgt = getattr(msg, f.name)
            for item in v:
                if f.type == _TYPE.TYPE_MESSAGE:
                    _dict_to_message(item, tgt.add(), opts)
                else:
                    tgt.append(_scalar_from_json(f, item, opts))
        elif f.type == _TYPE.TYPE_MESSAGE:
            _dict_to_message(v, getattr(msg, f.name), opts)
        else:
            setattr(msg, f.name, _scalar_from_json(f, v, opts))


def json_to_proto_with_options(
    data, message, options: Optional[Json2PbOptions] = None
) -> Tuple[bool, str, int]:
    """JsonToProtoMessage analog → (ok, error, parsed_offset)."""
    opts = options or Json2PbOptions()
    if isinstance(data, IOBuf):
        data = data.to_bytes()
    was_bytes = isinstance(data, (bytes, bytearray))
    if was_bytes:
        data = bytes(data).decode("utf-8", errors="replace")
    stripped = data.lstrip()
    if not stripped:
        # reference: empty doc returns false; error text stays empty
        # under allow_remaining (json_to_pb.h:50-53)
        return False, (
            "" if opts.allow_remaining_bytes_after_parsing
            else "The document is empty"
        ), 0
    try:
        if opts.allow_remaining_bytes_after_parsing:
            doc, end = json.JSONDecoder().raw_decode(data, len(data) - len(stripped))
        else:
            doc = json.loads(data)
            end = len(data)
    except ValueError as e:
        return False, f"invalid JSON: {e}", 0
    try:
        fields = message.DESCRIPTOR.fields
        if isinstance(doc, list):
            if not (
                opts.array_to_single_repeated
                and len(fields) == 1
                and fields[0].is_repeated
                and not _is_map_field(fields[0])
            ):
                raise _ConvertError(
                    "JSON array needs array_to_single_repeated and a "
                    "single-repeated-field message (json_to_pb.h:37-39)"
                )
            _dict_to_message({fields[0].name: doc}, message, opts)
        else:
            _dict_to_message(doc, message, opts)
        # required-field check (proto2), ONCE over the whole tree —
        # FindInitializationErrors is itself recursive, so calling it
        # per nested message would be quadratic
        missing = message.FindInitializationErrors()
        if missing:
            raise _ConvertError(f"missing required fields: {missing}")
        if was_bytes:
            # parsed_offset is a BYTE offset into the caller's buffer
            # (json_to_pb.h:41-58); the decoder gave a character count.
            # Exact for cleanly-decoded UTF-8; inputs that hit the
            # errors='replace' substitution were never resumable anyway.
            end = len(data[:end].encode("utf-8"))
        return True, "", end
    except (_ConvertError, ValueError, TypeError) as e:
        # ValueError/TypeError: protobuf range checks (int32 overflow),
        # map-key coercion — the contract is (ok, error), no exceptions
        return False, str(e), 0


# ---------------------------------------------------------------------------
# legacy surface (pre-options wrappers; HTTP restful mapping uses these)
# ---------------------------------------------------------------------------


def json_to_proto(data, message) -> Tuple[bool, str]:
    """Parse JSON (bytes/str/IOBuf) into `message`. Returns (ok, error)."""
    ok, err, _ = json_to_proto_with_options(data, message)
    return ok, err


def proto_to_json(message, pretty: bool = False) -> str:
    out, err = proto_to_json_with_options(
        message, Pb2JsonOptions(pretty_json=pretty)
    )
    if out is None:
        raise ValueError(err)
    return out
