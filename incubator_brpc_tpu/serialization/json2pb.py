"""JSON ↔ protobuf conversion (reference src/json2pb/, 1,740 LoC).

The reference hand-rolls a rapidjson-based streaming converter over
IOBuf; protobuf's canonical json_format provides the same mapping here,
wrapped to operate on IOBuf and to match the reference's error
surface (returns None + error string instead of raising, as
JsonToProtoMessage does).
"""

from __future__ import annotations

from typing import Optional, Tuple

from google.protobuf import json_format

from incubator_brpc_tpu.utils.iobuf import IOBuf


def json_to_proto(data, message) -> Tuple[bool, str]:
    """Parse JSON (bytes/str/IOBuf) into `message`. Returns (ok, error)."""
    if isinstance(data, IOBuf):
        data = data.to_bytes()
    if isinstance(data, (bytes, bytearray)):
        data = data.decode("utf-8", errors="replace")
    try:
        json_format.Parse(data, message, ignore_unknown_fields=True)
        return True, ""
    except json_format.ParseError as e:
        return False, str(e)


def proto_to_json(message, pretty: bool = False) -> str:
    return json_format.MessageToJson(
        message,
        indent=2 if pretty else None,
        preserving_proto_field_name=True,
    )
