"""Variable base + global registry (reference bvar/variable.h:102).

expose()/hide() register into a process-global name→variable map that
powers the /vars builtin service and the Prometheus exporter; dump
supports the reference's wildcard filters (`?`/`*`).
"""

from __future__ import annotations

import fnmatch
import threading
from typing import Dict, List, Optional, Tuple

_registry: Dict[str, "Variable"] = {}
# RLock, deliberately: Variable.__del__ calls hide(), and GC can fire
# inside expose()'s critical section (dict insert allocates) ON THE SAME
# THREAD — a plain Lock self-deadlocks there (seen hanging the full test
# suite). Re-entrant hide() only pops a different (dying) variable's
# key, which every section here tolerates.
_registry_lock = threading.RLock()


class Variable:
    def __init__(self):
        self._name: Optional[str] = None

    # -- subclass interface --
    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        v = self.get_value()
        return f"{v:.6g}" if isinstance(v, float) else str(v)

    # -- registry --
    def expose(self, name: str, prefix: str = "") -> "Variable":
        full = f"{prefix}_{name}" if prefix else name
        full = _sanitize(full)
        with _registry_lock:
            if self._name and _registry.get(self._name) is self:
                _registry.pop(self._name, None)
            _registry[full] = self
            self._name = full
        return self

    def expose_as(self, prefix: str, name: str) -> "Variable":
        return self.expose(name, prefix)

    def hide(self):
        with _registry_lock:
            if self._name:
                # pop only our own registration: a dying variable whose
                # name was re-exposed by a NEWER variable must not
                # unregister the newer one from under it
                if _registry.get(self._name) is self:
                    _registry.pop(self._name, None)
                self._name = None

    @property
    def name(self) -> Optional[str]:
        return self._name

    def __del__(self):
        try:
            self.hide()
        except Exception:
            pass


def _sanitize(name: str) -> str:
    out = []
    last_us = False
    for ch in name.lower():
        if ch.isalnum():
            out.append(ch)
            last_us = False
        elif not last_us and out:
            out.append("_")
            last_us = True
    return "".join(out).strip("_")


def list_exposed() -> List[str]:
    with _registry_lock:
        return sorted(_registry)


def describe_exposed(name: str) -> Optional[str]:
    with _registry_lock:
        var = _registry.get(name)
    return var.describe() if var else None


def dump_exposed(wildcards: str = "*") -> List[Tuple[str, str]]:
    """Dump (name, value) pairs matching `;`/`,`-separated wildcards
    (reference Variable::dump_exposed with WildcardMatcher)."""
    patterns = [w for w in wildcards.replace(";", ",").split(",") if w]
    with _registry_lock:
        names = sorted(_registry)
    out = []
    for n in names:
        if any(fnmatch.fnmatch(n, p) for p in patterns):
            d = describe_exposed(n)
            if d is not None:
                out.append((n, d))
    return out
