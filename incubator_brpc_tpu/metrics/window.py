"""Window / PerSecond — time-windowed views over reducers.

Reference bvar/window.h:174,197 + detail/sampler.cpp: a background
sampler thread takes one sample per second from every windowed
variable into a per-variable ring; Window reads the delta over the
last N seconds, PerSecond divides by the window span.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

from incubator_brpc_tpu.metrics.variable import Variable
from incubator_brpc_tpu.metrics.reducer import Adder, Maxer, Miner, Reducer


class _SamplerThread:
    """One global 1 Hz sampling thread (reference SamplerCollector)."""

    def __init__(self):
        self._samplers: List["_WindowSampler"] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def add(self, s: "_WindowSampler"):
        with self._lock:
            self._samplers.append(s)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="tpubrpc-bvar-sampler"
                )
                self._thread.start()

    def remove(self, s: "_WindowSampler"):
        with self._lock:
            try:
                self._samplers.remove(s)
            except ValueError:
                pass

    def _run(self):
        while True:
            start = time.monotonic()
            with self._lock:
                samplers = list(self._samplers)
            for s in samplers:
                try:
                    s.take_sample()
                except Exception:
                    pass
            elapsed = time.monotonic() - start
            time.sleep(max(0.05, 1.0 - elapsed))


_sampler_thread = _SamplerThread()


class _WindowSampler:
    """Per-variable ring of (cumulative) samples."""

    def __init__(self, var: Reducer, window_size: int):
        self.var = var
        self.window_size = window_size
        self.samples: deque = deque(maxlen=window_size + 1)
        self.lock = threading.Lock()
        _sampler_thread.add(self)

    def take_sample(self):
        with self.lock:
            self.samples.append((time.monotonic(), self.var.get_value()))

    def window(self):
        """(oldest, newest, span_seconds) or None if <2 samples."""
        with self.lock:
            if len(self.samples) < 2:
                return None
            t0, v0 = self.samples[0]
            t1, v1 = self.samples[-1]
            return v0, v1, max(t1 - t0, 1e-9)


class Window(Variable):
    """Value over the last `window_size` seconds (bvar::Window).

    For Adder: delta over the window. For Maxer/Miner: extremum of the
    in-window deltas is not recoverable from cumulative samples, so the
    sampler records per-second reset values instead (matching the
    reference, which stores per-sample values for non-additive ops).
    """

    def __init__(self, var: Reducer, window_size: int = 10):
        super().__init__()
        self._var = var
        self._additive = not isinstance(var, (Maxer, Miner))
        self._sampler = _WindowSampler(var, window_size)
        self._resets: deque = deque(maxlen=window_size)
        if not self._additive:
            # sample by reset for extremum reducers
            self._sampler.take_sample = self._take_reset_sample  # type: ignore

    def _take_reset_sample(self):
        self._resets.append(self._var.reset())

    def get_value(self):
        if not self._additive:
            vals = list(self._resets)
            if not vals:
                return self._var.get_value()
            return max(vals) if isinstance(self._var, Maxer) else min(vals)
        w = self._sampler.window()
        if w is None:
            return self._var.get_value()
        v0, v1, _ = w
        return v1 - v0

    def window_size(self) -> int:
        return self._sampler.window_size


class PerSecond(Variable):
    """Windowed delta divided by elapsed seconds (bvar::PerSecond)."""

    def __init__(self, var: Reducer, window_size: int = 10):
        super().__init__()
        self._sampler = _WindowSampler(var, window_size)
        self._var = var

    def get_value(self) -> float:
        w = self._sampler.window()
        if w is None:
            return 0.0
        v0, v1, span = w
        return (v1 - v0) / span
