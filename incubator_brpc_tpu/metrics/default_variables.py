"""Process/system metrics from /proc (reference bvar/default_variables.cpp).

Exposed lazily by ``expose_default_variables()`` (the server calls this
at start): process_cpu_usage, process_memory_resident, process_fd_count,
process_uptime, plus runtime-specific gauges (worker/blocked counts).
"""

from __future__ import annotations

import os
import time

from incubator_brpc_tpu.metrics.passive_status import PassiveStatus

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_start_time = time.time()
_exposed = False


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except Exception:
        return 0


def _cpu_seconds() -> float:
    try:
        with open("/proc/self/stat") as f:
            parts = f.read().rsplit(")", 1)[1].split()
        utime, stime = int(parts[11]), int(parts[12])
        return (utime + stime) / _CLK
    except Exception:
        return 0.0


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except Exception:
        return 0


def _thread_count() -> int:
    try:
        return len(os.listdir("/proc/self/task"))
    except Exception:
        return 0


def expose_default_variables():
    global _exposed
    if _exposed:
        return
    _exposed = True
    PassiveStatus(_rss_bytes).expose("process_memory_resident")
    PassiveStatus(_cpu_seconds).expose("process_cpu_seconds")
    PassiveStatus(_fd_count).expose("process_fd_count")
    PassiveStatus(_thread_count).expose("process_thread_count")
    PassiveStatus(lambda: time.time() - _start_time).expose("process_uptime")
    PassiveStatus(os.getpid).expose("process_pid")

    def _workers():
        from incubator_brpc_tpu.runtime.scheduler import _default_control

        return _default_control.worker_count() if _default_control else 0

    def _blocked():
        from incubator_brpc_tpu.runtime.scheduler import _default_control

        return _default_control.blocked_count() if _default_control else 0

    PassiveStatus(_workers).expose("runtime_worker_count")
    PassiveStatus(_blocked).expose("runtime_blocked_count")
