"""Collector — global bounded sampling pipeline.

Reference bvar/collector.{h,cpp} (collector.h:48-72): shared base for
rpcz spans and mutex-contention samples. Producers call
``Collected.submit()``; a speed limiter keeps collection below
`max_samples_per_second` (sampling, not backpressure: excess samples
are dropped), and a background drain thread groups samples by
preprocessor and invokes ``dump_and_destroy``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Optional

COLLECTOR_SAMPLING_BASE = 64
_MAX_PER_SECOND = 1000


class Collected:
    """Base for collectable samples (rpcz Span subclasses this)."""

    def submit(self):
        get_collector().submit(self)

    def dump_and_destroy(self):  # overridden
        pass

    def speed_limit(self) -> int:
        return _MAX_PER_SECOND


class Collector:
    def __init__(self):
        self._q: Deque[Collected] = deque(maxlen=4096)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._window_start = time.monotonic()
        # per-sample-class counts: rpcz spans declare a higher
        # speed_limit than contention samples, and a shared counter
        # would let heavy span traffic starve the other sample types
        self._window_counts: dict = {}
        self.dropped = 0
        self.collected = 0

    def submit(self, sample: Collected):
        now = time.monotonic()
        cls = type(sample)
        # over-limit fast path WITHOUT the lock: a dirty read of the
        # window counters may mis-drop/mis-admit a handful of samples
        # at the window edge (sampling is approximate by design), but
        # saturated producers — the RPC hot path under load — skip the
        # lock acquire entirely
        if (
            self._window_counts.get(cls, 0) >= sample.speed_limit()
            and now - self._window_start < 1.0
        ):
            self.dropped += 1
            return
        with self._lock:
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._window_counts.clear()
            cnt = self._window_counts.get(cls, 0)
            if cnt >= sample.speed_limit():
                self.dropped += 1
                return
            self._window_counts[cls] = cnt + 1
            self._q.append(sample)
            self.collected += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True, name="tpubrpc-collector"
                )
                self._thread.start()
            # No per-sample notify: the drain thread polls in rounds
            # (reference collector.cpp likewise sleeps between grabs).
            # Waking it per sample costs a futex wake + context switch
            # on the RPC hot path — thousands per second under load.

    _DRAIN_PERIOD_S = 0.1

    def _drain(self):
        while True:
            time.sleep(self._DRAIN_PERIOD_S)
            with self._lock:
                if not self._q:
                    continue
                batch = list(self._q)
                self._q.clear()
            for sample in batch:
                try:
                    sample.dump_and_destroy()
                except Exception:
                    pass


_collector: Optional[Collector] = None
_collector_lock = threading.Lock()


def get_collector() -> Collector:
    global _collector
    if _collector is None:
        with _collector_lock:
            if _collector is None:
                _collector = Collector()
    return _collector
