"""Collector — global bounded sampling pipeline.

Reference bvar/collector.{h,cpp} (collector.h:48-72): shared base for
rpcz spans and mutex-contention samples. Producers call
``Collected.submit()``; a speed limiter keeps collection below
`max_samples_per_second` (sampling, not backpressure: excess samples
are dropped), and a background drain thread groups samples by
preprocessor and invokes ``dump_and_destroy``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Optional

COLLECTOR_SAMPLING_BASE = 64
_MAX_PER_SECOND = 1000


class Collected:
    """Base for collectable samples (rpcz Span subclasses this)."""

    def submit(self):
        get_collector().submit(self)

    def dump_and_destroy(self):  # overridden
        pass

    def speed_limit(self) -> int:
        return _MAX_PER_SECOND


class Collector:
    def __init__(self):
        self._q: Deque[Collected] = deque(maxlen=4096)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._window_start = time.monotonic()
        self._window_count = 0
        self.dropped = 0
        self.collected = 0

    def submit(self, sample: Collected):
        now = time.monotonic()
        with self._lock:
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._window_count = 0
            if self._window_count >= sample.speed_limit():
                self.dropped += 1
                return
            self._window_count += 1
            self._q.append(sample)
            self.collected += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True, name="tpubrpc-collector"
                )
                self._thread.start()
            self._cond.notify()

    def _drain(self):
        while True:
            with self._lock:
                while not self._q:
                    self._cond.wait(1.0)
                batch = list(self._q)
                self._q.clear()
            for sample in batch:
                try:
                    sample.dump_and_destroy()
                except Exception:
                    pass


_collector: Optional[Collector] = None
_collector_lock = threading.Lock()


def get_collector() -> Collector:
    global _collector
    if _collector is None:
        with _collector_lock:
            if _collector is None:
                _collector = Collector()
    return _collector
