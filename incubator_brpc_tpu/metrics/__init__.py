"""Metrics layer — the bvar analog (reference src/bvar/).

Write-path design follows the reference (reducer.h:69): each writer
thread owns an *agent* holding a private partial value — writes are
uncontended (~ns in the reference); reads combine all agents (~µs).
Everything above instruments itself with these at construction, exactly
as brpc does (SURVEY.md §7 step 3).
"""

from incubator_brpc_tpu.metrics.variable import (  # noqa: F401
    Variable,
    dump_exposed,
    list_exposed,
    describe_exposed,
)
from incubator_brpc_tpu.metrics.reducer import Adder, Maxer, Miner  # noqa: F401
from incubator_brpc_tpu.metrics.window import Window, PerSecond  # noqa: F401
from incubator_brpc_tpu.metrics.recorder import IntRecorder  # noqa: F401
from incubator_brpc_tpu.metrics.latency_recorder import LatencyRecorder  # noqa: F401
from incubator_brpc_tpu.metrics.passive_status import PassiveStatus, Status  # noqa: F401
from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension  # noqa: F401
from incubator_brpc_tpu.metrics.collector import Collected, get_collector  # noqa: F401
