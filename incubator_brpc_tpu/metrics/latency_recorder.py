"""LatencyRecorder — qps + avg + max + log-bucketed percentiles.

Analog of bvar::LatencyRecorder (latency_recorder.h:75) built on the
same parts as the reference: an IntRecorder for the windowed average, a
Maxer for windowed max, an Adder+PerSecond for qps, and a log-bucketed
Percentile (reference detail/percentile.h, the "79.4%-effort"
log-interval design) for p50/p90/p99/p99.9.

expose(prefix) registers the same derived variable names the reference
emits: <prefix>_latency, _latency_50/90/99/999, _max_latency, _qps,
_count — these names feed /vars and the Prometheus exporter.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import List

from incubator_brpc_tpu.metrics.variable import Variable
from incubator_brpc_tpu.metrics.reducer import Adder, Maxer
from incubator_brpc_tpu.metrics.recorder import IntRecorder
from incubator_brpc_tpu.metrics.window import PerSecond, Window, _sampler_thread
from incubator_brpc_tpu.metrics.passive_status import PassiveStatus

_NUM_BUCKETS = 512  # 32 octaves × 16 sub-buckets, covers 1us..~4e9us (>1h)


def _bucket_of(us: int) -> int:
    # exact below 16us; 16 log sub-buckets per octave above (monotonic)
    if us < 0:
        us = 0
    if us < 16:
        return us
    e = us.bit_length() - 1  # >= 4
    sub = (us >> (e - 4)) & 0xF
    return min(e * 16 + sub, _NUM_BUCKETS - 1)


# first latency value belonging to the NEXT bucket (exclusive upper
# bound of bucket idx) — powers run-length folding in update_sorted
def _bucket_hi_of(idx: int) -> int:
    if idx < 16:
        return idx + 1
    e, sub = divmod(idx, 16)
    if e < 4:  # indices 16..63 are unreachable (us>=16 → e>=4)
        return idx + 1
    return (17 + sub) << (e - 4)


_BUCKET_HI = [_bucket_hi_of(i) for i in range(_NUM_BUCKETS - 1)] + [1 << 62]


def _bucket_mid(idx: int) -> float:
    if idx < 16:
        return float(idx)
    e, sub = divmod(idx, 16)
    lo = (16 + sub) << (e - 4)
    hi = (17 + sub) << (e - 4)
    return (lo + hi) / 2.0


def percentile_from_buckets(buckets, ratio: float) -> float:
    """The percentile read over raw bucket counts — THE algorithm
    (Percentile.get_percentile delegates here).  `buckets` is either a
    dense list indexed by bucket or a sparse {index: count} mapping.
    Because bucketing each sample is deterministic and this walk sees
    only counts, running it over the elementwise SUM of several
    processes' buckets yields exactly the percentile of the pooled
    samples — the mergeable-aggregation invariant /cluster relies on
    (and tests prove)."""
    if isinstance(buckets, dict):
        dense = [0] * _NUM_BUCKETS
        for i, c in buckets.items():
            dense[int(i)] += c
        buckets = dense
    total = sum(buckets)
    if total == 0:
        return 0.0
    target = math.ceil(total * ratio)
    acc = 0
    for i, c in enumerate(buckets):
        acc += c
        if acc >= target:
            return _bucket_mid(i)
    return _bucket_mid(_NUM_BUCKETS - 1)


def merge_latency_snapshots(snaps) -> dict:
    """Fold several LatencyRecorder.mergeable_snapshot() dicts into one
    of the same shape: counts/sums add, maxes max, histogram buckets
    add elementwise.  Never merges pre-computed percentiles — read
    them from the merged buckets via percentile_from_buckets."""
    out = {
        "count": 0,
        "latency_sum": 0,
        "latency_num": 0,
        "max_latency": 0.0,
        "qps": 0.0,
        "buckets": {},
    }
    merged_buckets = out["buckets"]
    for snap in snaps:
        if not snap:
            continue
        out["count"] += int(snap.get("count", 0))
        out["latency_sum"] += int(snap.get("latency_sum", 0))
        out["latency_num"] += int(snap.get("latency_num", 0))
        out["max_latency"] = max(
            out["max_latency"], float(snap.get("max_latency", 0))
        )
        out["qps"] += float(snap.get("qps", 0.0))
        for i, c in (snap.get("buckets") or {}).items():
            i = str(int(i))
            merged_buckets[i] = merged_buckets.get(i, 0) + int(c)
    return out


def snapshot_stats(snap: dict) -> dict:
    """Human stats {count, avg_us, p50_us, p90_us, p99_us, max_us} from
    one (possibly merged) mergeable snapshot."""
    num = snap.get("latency_num", 0)
    buckets = snap.get("buckets") or {}
    return {
        "count": snap.get("count", 0),
        "avg_us": (snap.get("latency_sum", 0) / num) if num else 0.0,
        "p50_us": percentile_from_buckets(buckets, 0.5),
        "p90_us": percentile_from_buckets(buckets, 0.9),
        "p99_us": percentile_from_buckets(buckets, 0.99),
        "max_us": float(snap.get("max_latency", 0)),
    }


class Percentile:
    """Log-bucketed percentile estimator (reference detail/percentile.h).

    Thread-local bucket counters merged on read; a ring of per-second
    snapshots gives windowed percentiles.
    """

    def __init__(self, window_size: int = 10):
        self._lock = threading.Lock()
        self._buckets = [0] * _NUM_BUCKETS
        self._ring: deque = deque(maxlen=window_size)

    def update(self, latency_us: int):
        idx = _bucket_of(int(latency_us))
        with self._lock:
            self._buckets[idx] += 1

    def update_bulk(self, latency_us: int, n: int):
        idx = _bucket_of(int(latency_us))
        with self._lock:
            self._buckets[idx] += n

    def update_sorted(self, items: List[int]):
        """Fold a pre-sorted batch: one bucket increment per bucket RUN
        instead of per item (the batched write path's flush)."""
        import bisect

        with self._lock:
            b = self._buckets
            i, n = 0, len(items)
            while i < n:
                idx = _bucket_of(items[i])
                j = bisect.bisect_left(items, _BUCKET_HI[idx], i + 1)
                b[idx] += j - i
                i = j

    def take_sample(self):
        with self._lock:
            snap = self._buckets[:]
            self._buckets = [0] * _NUM_BUCKETS
        self._ring.append(snap)

    def bucket_totals(self) -> List[int]:
        """Windowed bucket counts (ring snapshots + the current partial
        second) — the raw histogram state mergeable_snapshot exports."""
        snaps = list(self._ring)
        with self._lock:
            cur = self._buckets[:]
        total_buckets = cur
        for s in snaps:
            for i, c in enumerate(s):
                if c:
                    total_buckets[i] += c
        return total_buckets

    def get_percentile(self, ratio: float) -> float:
        """ratio in (0,1], e.g. 0.99."""
        return percentile_from_buckets(self.bucket_totals(), ratio)


class LatencyRecorder(Variable):
    def __init__(self, window_size: int = 10):
        super().__init__()
        self._latency = IntRecorder()
        self._max_latency = Maxer()
        self._count = Adder(0)
        self._qps = PerSecond(self._count, window_size)
        self._max_window = Window(self._max_latency, window_size)
        self._percentile = Percentile(window_size)
        self._win_sum = deque(maxlen=window_size)
        self._wtls = threading.local()  # fused write-path agent cache
        self.bulk_folded = False  # ever fed by update_bulk (mean folds)
        # batched write path: per-thread append-only buffers, folded by
        # the 1 Hz sampler (or any read) — see update_batched
        self._batches: List[List[int]] = []
        self._batch_reg_lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._derived: List[Variable] = []
        # optional lazy source: called before any read/sampler fold so
        # observations kept OUTSIDE Python (e.g. the native mux client's
        # C atomics, engine.cpp nc_mux_stats) flow in with ZERO per-call
        # Python work.  The source calls update_bulk/note_max itself.
        self._pull_source = None
        self._in_pull = False
        # ride the global 1 Hz sampler for percentile + windowed avg snapshots
        self._psampler = _PercentileSampler(self)
        _sampler_thread.add(self._psampler)

    def set_pull_source(self, fn) -> None:
        """fn() harvests externally-kept observations into this recorder
        (via update_bulk/note_max); invoked lazily before reads and at
        each sampler tick."""
        self._pull_source = fn

    def note_max(self, latency_us: int) -> None:
        """Fold an externally-observed max (no count/sum contribution)."""
        ma = self._max_latency._my_agent()
        us = int(latency_us)
        with ma.lock:
            if us > ma.value:
                ma.value = us

    # -- write path (hot): called once per finished RPC. Fused: one TLS
    # lookup caches this thread's component agents, updates go inline
    # (the layered component update() calls cost ~8us/RPC, measured) --
    def update(self, latency_us: int) -> "LatencyRecorder":
        us = int(latency_us)
        tls = self._wtls
        agents = getattr(tls, "agents", None)
        if agents is None:
            agents = (
                self._latency._my_agent(),
                self._max_latency._my_agent(),
                self._count._my_agent(),
            )
            tls.agents = agents
        la, ma, ca = agents
        with la.lock:
            la.sum += us
            la.num += 1
        with ma.lock:
            if us > ma.value:
                ma.value = us
        with ca.lock:
            ca.value += 1
        self._percentile.update(us)
        return self

    __lshift__ = update

    def update_batched(self, latency_us: int) -> None:
        """O(list-append) hot-path record (~0.15us vs ~1.6us for
        update): observations buffer in a per-thread list and fold into
        the real components at the next 1 Hz sampler tick or read.
        Windowed reads already lag by design; the native RPC paths use
        this because every microsecond of per-call GIL-held work caps
        aggregate qps at 1s/that on one core."""
        tls = self._wtls
        buf = getattr(tls, "batch", None)
        if buf is None:
            buf = tls.batch = []
            with self._batch_reg_lock:
                self._batches.append((threading.current_thread(), buf))
        buf.append(latency_us)

    def _flush_batches(self) -> None:
        """Fold all per-thread batch buffers into the components.
        Concurrent-writer safe under the GIL: we only remove the first
        n items we copied; appends racing in land in a later flush."""
        pull = self._pull_source
        if pull is not None:
            # under _flush_lock: the pull's read-diff-fold of external
            # counters is a read-modify-write — two concurrent readers
            # (sampler tick + a /vars read; the ctypes stats call drops
            # the GIL) would otherwise fold the same delta twice.
            # _in_pull guards recursion only (the source's update_bulk
            # path must not re-enter the pull).
            with self._flush_lock:
                if not self._in_pull:
                    self._in_pull = True
                    try:
                        pull()
                    finally:
                        self._in_pull = False
        if not self._batches:
            return
        with self._flush_lock:
            total = 0
            s = 0
            mx = 0
            dead = None
            for entry in self._batches:
                thread, buf = entry
                n = len(buf)
                if not n:
                    if not thread.is_alive():  # drained + writer gone:
                        dead = dead or []  # prune (thread-churny apps
                        dead.append(entry)  # would leak a list each)
                    continue
                items = buf[:n]
                del buf[:n]
                items.sort()
                total += n
                s += sum(items)
                if items[-1] > mx:
                    mx = items[-1]
                self._percentile.update_sorted(items)
            if dead:
                with self._batch_reg_lock:
                    for entry in dead:
                        self._batches.remove(entry)
            if not total:
                return
            la = self._latency._my_agent()
            ma = self._max_latency._my_agent()
            ca = self._count._my_agent()
            with la.lock:
                la.sum += s
                la.num += total
            with ma.lock:
                if mx > ma.value:
                    ma.value = mx
            with ca.lock:
                ca.value += total

    def update_bulk(self, latency_us: int, n: int) -> "LatencyRecorder":
        """Record `n` observations of `latency_us` at O(1) cost.  Used
        to harvest native-engine fast-path completions, which arrive as
        (count, latency sum) deltas: every harvested call lands in the
        average's bucket, so percentiles over harvested traffic read as
        the mean rather than the true spread."""
        if n <= 0:
            return self
        self.bulk_folded = True  # /status flags percentiles as approx
        us = int(latency_us)
        tls = self._wtls
        agents = getattr(tls, "agents", None)
        if agents is None:
            agents = (
                self._latency._my_agent(),
                self._max_latency._my_agent(),
                self._count._my_agent(),
            )
            tls.agents = agents
        la, ma, ca = agents
        with la.lock:
            la.sum += us * n
            la.num += n
        with ma.lock:
            if us > ma.value:
                ma.value = us
        with ca.lock:
            ca.value += n
        self._percentile.update_bulk(us, n)
        return self

    # -- reads (all fold pending batched writes first) --
    def latency(self) -> float:
        """Windowed average latency in us."""
        self._flush_batches()
        snaps = list(self._win_sum)
        s = sum(x[0] for x in snaps)
        n = sum(x[1] for x in snaps)
        if n == 0:
            return self._latency.get_value()
        return s / n

    def latency_percentile(self, ratio: float) -> float:
        self._flush_batches()
        return self._percentile.get_percentile(ratio)

    def max_latency(self) -> float:
        self._flush_batches()
        return self._max_window.get_value()

    def qps(self) -> float:
        self._flush_batches()
        return self._qps.get_value()

    def count(self) -> int:
        self._flush_batches()
        return self._count.get_value()

    def get_value(self) -> float:
        return self.latency()

    def mergeable_snapshot(self) -> dict:
        """Export the aggregation STATE (counts, sums, histogram
        buckets), never computed percentiles: elementwise merging of
        these dicts across replicas (merge_latency_snapshots) then
        percentile_from_buckets is exactly the percentile of the
        pooled samples.  Buckets are sparse {index: count} with string
        keys so the dict survives a JSON round-trip unchanged."""
        self._flush_batches()
        buckets = self._percentile.bucket_totals()
        snaps = list(self._win_sum)
        s = sum(x[0] for x in snaps)
        n = sum(x[1] for x in snaps)
        cs, cn = self._latency.sum_num()  # current partial second
        return {
            "count": self.count(),
            "latency_sum": s + cs,
            "latency_num": n + cn,
            "max_latency": self.max_latency(),
            "qps": self.qps(),
            "buckets": {
                str(i): c for i, c in enumerate(buckets) if c
            },
        }

    def describe(self) -> str:
        return (
            f"latency={self.latency():.0f}us p50={self.latency_percentile(0.5):.0f} "
            f"p99={self.latency_percentile(0.99):.0f} max={self.max_latency():.0f} "
            f"qps={self.qps():.1f} count={self.count()}"
        )

    def expose(self, name: str, prefix: str = "") -> "LatencyRecorder":
        super().expose(f"{name}_latency", prefix)
        base = self._name[: -len("_latency")]
        mk = lambda fn: PassiveStatus(fn)  # noqa: E731
        for suffix, fn in [
            ("latency_50", lambda: self.latency_percentile(0.5)),
            ("latency_90", lambda: self.latency_percentile(0.9)),
            ("latency_99", lambda: self.latency_percentile(0.99)),
            ("latency_999", lambda: self.latency_percentile(0.999)),
            ("max_latency", self.max_latency),
            ("qps", self.qps),
            ("count", self.count),
        ]:
            v = mk(fn).expose(f"{base}_{suffix}")
            self._derived.append(v)
        return self

    def hide(self):
        super().hide()
        for v in self._derived:
            v.hide()
        self._derived.clear()


class _PercentileSampler:
    def __init__(self, rec: LatencyRecorder):
        self._rec = rec
        self.window_size = rec._win_sum.maxlen

    def take_sample(self):
        self._rec._flush_batches()  # fold batched writes into this tick
        self._rec._percentile.take_sample()
        self._rec._win_sum.append(self._rec._latency.reset())
