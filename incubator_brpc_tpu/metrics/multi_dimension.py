"""MultiDimension — labeled metrics (reference bvar/multi_dimension.h:35).

A family of variables keyed by label values (Prometheus-style), e.g.
``MultiDimension(Adder, ["method", "status"])`` then
``m.get_stats(["Echo", "ok"]) << 1``. The Prometheus exporter walks
families to emit `name{label="v"} value` lines.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Sequence, Tuple

from incubator_brpc_tpu.metrics.variable import Variable, _registry, _registry_lock, _sanitize


class MultiDimension(Variable):
    def __init__(self, factory: Callable[[], Variable], labels: Sequence[str]):
        super().__init__()
        self._factory = factory
        self._labels = list(labels)
        self._stats: Dict[Tuple, Variable] = {}
        self._lock = threading.Lock()

    @property
    def labels(self) -> List[str]:
        return self._labels

    def get_stats(self, label_values: Sequence) -> Variable:
        key = tuple(label_values)
        if len(key) != len(self._labels):
            raise ValueError(f"expected {len(self._labels)} labels, got {len(key)}")
        with self._lock:
            var = self._stats.get(key)
            if var is None:
                var = self._factory()
                self._stats[key] = var
            return var

    def has_stats(self, label_values: Sequence) -> bool:
        return tuple(label_values) in self._stats

    def delete_stats(self, label_values: Sequence):
        with self._lock:
            self._stats.pop(tuple(label_values), None)

    def count_stats(self) -> int:
        return len(self._stats)

    def items(self):
        with self._lock:
            return list(self._stats.items())

    def get_value(self):
        return self.count_stats()

    # separator for label tuples flattened into JSON object keys; \t
    # cannot appear in metric label values that survive the /metrics
    # exposition, so the join is reversible
    _KEY_SEP = "\t"

    def mergeable_snapshot(self) -> dict:
        """{"labels": [...], "stats": {joined-key: state}} where state
        is the sub-variable's own mergeable_snapshot when it has one,
        or its numeric value for plain sum-mergeable counters (Adder);
        non-numeric subs without mergeable state are skipped — there is
        no exact merge for them."""
        stats = {}
        for key, var in self.items():
            snap_fn = getattr(var, "mergeable_snapshot", None)
            if snap_fn is not None:
                state = snap_fn()
            else:
                state = var.get_value()
                if isinstance(state, bool) or not isinstance(
                    state, (int, float)
                ):
                    continue
            stats[self._KEY_SEP.join(str(k) for k in key)] = state
        return {"labels": list(self._labels), "stats": stats}

    def describe(self) -> str:
        parts = []
        for key, var in self.items():
            lbl = ",".join(f'{k}="{v}"' for k, v in zip(self._labels, key))
            parts.append(f"{{{lbl}}} {var.describe()}")
        return "\n".join(parts)
