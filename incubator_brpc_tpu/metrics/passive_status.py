"""PassiveStatus / Status (reference bvar/passive_status.h:42, status.h:44)."""

from __future__ import annotations

from typing import Callable

from incubator_brpc_tpu.metrics.variable import Variable


class PassiveStatus(Variable):
    """Callback-valued variable: value computed at read time."""

    def __init__(self, getter: Callable[[], object]):
        super().__init__()
        self._getter = getter

    def get_value(self):
        return self._getter()


class Status(Variable):
    """Set-valued variable."""

    def __init__(self, value=None):
        super().__init__()
        self._value = value

    def set_value(self, value):
        self._value = value

    def get_value(self):
        return self._value
