"""Reducer → Adder / Maxer / Miner (reference bvar/reducer.h:69,224,258,308).

Per-thread agents make the write path uncontended: each writing thread
owns a private cell (reference detail/agent_group.h); ``get_value``
combines over all agents (detail/combiner.h). ``reset`` (used by the
Window sampler) atomically takes-and-zeros each agent.
"""

from __future__ import annotations

import threading
from typing import Callable, List

from incubator_brpc_tpu.metrics.variable import Variable


class _Agent:
    __slots__ = ("value", "lock")

    def __init__(self, identity):
        self.value = identity
        self.lock = threading.Lock()


class Reducer(Variable):
    def __init__(self, op: Callable, identity):
        super().__init__()
        self._op = op
        self._identity = identity
        self._agents: List[_Agent] = []
        self._agents_lock = threading.Lock()
        self._tls = threading.local()

    def _my_agent(self) -> _Agent:
        agent = getattr(self._tls, "agent", None)
        if agent is None:
            agent = _Agent(self._identity)
            with self._agents_lock:
                self._agents.append(agent)
            self._tls.agent = agent
        return agent

    def update(self, value) -> "Reducer":
        """The hot write path: touch only this thread's agent."""
        agent = self._my_agent()
        with agent.lock:  # uncontended unless a read combines concurrently
            agent.value = self._op(agent.value, value)
        return self

    __lshift__ = update  # adder << 1, like the reference's operator<<

    def get_value(self):
        result = self._identity
        with self._agents_lock:
            agents = list(self._agents)
        for a in agents:
            with a.lock:
                result = self._op(result, a.value)
        return result

    def reset(self):
        """Combine and zero all agents (reference Reducer::reset, used by
        the window sampler for series)."""
        result = self._identity
        with self._agents_lock:
            agents = list(self._agents)
        for a in agents:
            with a.lock:
                result = self._op(result, a.value)
                a.value = self._identity
        return result


class Adder(Reducer):
    """bvar::Adder (reducer.h:224)."""

    def __init__(self, value=0):
        super().__init__(lambda a, b: a + b, type(value)())
        if value:
            self.update(value)

    def update(self, value) -> "Adder":
        # specialized hot path: no lambda dispatch (this is the single
        # most-called metrics op — several calls per RPC)
        agent = getattr(self._tls, "agent", None)
        if agent is None:
            agent = self._my_agent()
        with agent.lock:
            agent.value += value
        return self

    __lshift__ = update


class Maxer(Reducer):
    """bvar::Maxer (reducer.h:258)."""

    def __init__(self):
        super().__init__(max, float("-inf"))

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("-inf") else v

    def reset(self):
        v = super().reset()
        return 0 if v == float("-inf") else v


class Miner(Reducer):
    """bvar::Miner (reducer.h:308)."""

    def __init__(self):
        super().__init__(min, float("inf"))

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("inf") else v

    def reset(self):
        v = super().reset()
        return 0 if v == float("inf") else v
