"""IntRecorder — average over a stream of ints (reference bvar/recorder.h:84).

The reference packs (sum, num) into one 64-bit word per agent for
atomicity; here each thread's agent keeps (sum, num) under its lock.
"""

from __future__ import annotations

import threading
from typing import List, Tuple

from incubator_brpc_tpu.metrics.variable import Variable


class _Agent:
    __slots__ = ("sum", "num", "lock")

    def __init__(self):
        self.sum = 0
        self.num = 0
        self.lock = threading.Lock()


class IntRecorder(Variable):
    def __init__(self):
        super().__init__()
        self._agents: List[_Agent] = []
        self._agents_lock = threading.Lock()
        self._tls = threading.local()

    def _my_agent(self) -> _Agent:
        a = getattr(self._tls, "agent", None)
        if a is None:
            a = _Agent()
            with self._agents_lock:
                self._agents.append(a)
            self._tls.agent = a
        return a

    def update(self, value: int) -> "IntRecorder":
        a = self._my_agent()
        with a.lock:
            a.sum += value
            a.num += 1
        return self

    __lshift__ = update

    def sum_num(self) -> Tuple[int, int]:
        s = n = 0
        with self._agents_lock:
            agents = list(self._agents)
        for a in agents:
            with a.lock:
                s += a.sum
                n += a.num
        return s, n

    def get_value(self) -> float:
        s, n = self.sum_num()
        return s / n if n else 0.0

    average = get_value

    def mergeable_snapshot(self) -> dict:
        """Aggregation state for cross-process merging: (sum, num) add
        elementwise, so the merged average is exactly the pooled
        average — never export the computed average itself."""
        s, n = self.sum_num()
        return {"sum": s, "num": n}

    def reset(self) -> Tuple[int, int]:
        s = n = 0
        with self._agents_lock:
            agents = list(self._agents)
        for a in agents:
            with a.lock:
                s += a.sum
                n += a.num
                a.sum = 0
                a.num = 0
        return s, n
