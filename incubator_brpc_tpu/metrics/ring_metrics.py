"""Process-wide ring fast-path counters (docs/fastpath.md).

Step-log counters for the submission/response ring lanes, exposed on
/metrics (module listed in analysis.invariants.METRIC_MODULES so the
metrics lint render-checks them).  Counts, never timing — the proof
that the windowed paths aren't silently degraded is arithmetic:

- ``rpc_ring_crossings``   Python↔C boundary crossings on the ring
  lane: client submit windows + harvest batches + windowed shard
  fan-out sub-windows.  A healthy windowed workload shows
  crossings ≪ calls.
- ``rpc_ring_windows``     submission windows flushed (client side,
  one ``mux_submit_many`` each) + shard fan-out windows (one per
  SHARD, not per key).
- ``rpc_ring_flush_bursts`` server response-ring bursts: each is one
  ``ns_send_burst`` → one writev burst flushing a harvested window's
  replies for one connection.

Import-light and jax-free by construction (the lint imports this
module in a bare interpreter).
"""

from __future__ import annotations

from incubator_brpc_tpu.metrics.reducer import Adder

rpc_ring_crossings = Adder(0).expose("rpc_ring_crossings")
rpc_ring_windows = Adder(0).expose("rpc_ring_windows")
rpc_ring_flush_bursts = Adder(0).expose("rpc_ring_flush_bursts")


def snapshot() -> dict:
    """Current counter values (the /status ``ring:`` line reads this)."""
    return {
        "crossings": rpc_ring_crossings.get_value(),
        "windows": rpc_ring_windows.get_value(),
        "flush_bursts": rpc_ring_flush_bursts.get_value(),
    }
