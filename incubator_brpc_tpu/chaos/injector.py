"""Process-wide fault-injection registry (the chaos/ runtime core).

Injection sites are fixed, named points in the transport/runtime where
a fault can be applied.  Wired sites check the module-level ``armed``
flag inline — one global load on the hot path while disarmed — and
only call :func:`check` when a plan is armed.  ``check`` resolves the
site's specs (prebuilt at arm time), applies match + the seeded
deterministic schedule, records the hit (per-site log +
``chaos_injected_total{site,action}``), and returns the firing spec
for the site to interpret.

Site catalog (see docs/chaos.md for the action matrix):

  socket.write        Socket.write queue-time   drop|delay_us|reset|corrupt
  socket.write_io     per write chunk           short_write|eagain_storm
  socket.read         read loop, per round      short_read|drop|delay_us|
                                                reset|eagain_storm
  dispatcher.dispatch epoll IN hand-off         delay_us
  scheduler.callback  task run                  delay_us
  ici.send            fabric leg                drop|delay_us|reset|
                                                close_mid_batch
  ici.chunk           chunked-send pipeline,    delay_us|reset
                      per chunk
  dcn.send            bridge frame              drop|delay_us|reset|reorder
  stream.frame        streaming frame egress,   drop|delay_us|reorder|reset
                      per frame kind
  batch.flush         micro-batcher flush       delay_us|drop
  collective.merge    sharded-batch merge       delay_us|reset
  admission.decide    admission at dispatch     reject|delay_us
  replica.lease       lease grant/renewal       drop|delay_us
  replica.ack         follower quorum ack       drop|delay_us
  kv.ship             prefill KV SET into the   drop|delay_us
                      cache tier, per layer key
  session.migrate     decode-session handoff    drop|delay_us
  native.srv_read     engine.cpp worker read    short_read|eagain_storm|
                                                reset|delay_us
  native.srv_write    engine.cpp burst flush    short_write|eagain_storm|
                                                reset|delay_us

The two ``native.*`` sites live in C (engine.cpp ``ns_set_fault``):
arming a plan containing them programs the engine's per-site atomics
(action/arg/probability/seed/max_hits) so faults hit the in-place
partial-frame completion and burst-flush paths that never touch
Python.  Their hit counts are harvested back into
``chaos_injected_total`` whenever :func:`site_hits` runs (the
``/chaos`` builtin calls it per render).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu.chaos.plan import FaultPlan, FaultSpec, spec_seed
from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension
from incubator_brpc_tpu.metrics.reducer import Adder

# THE hot-path gate: wired sites do `if injector.armed:` inline and
# nothing else while no plan is armed.
armed = False

#: Prometheus-facing hit counter, labeled {site, action}
chaos_injected_total = MultiDimension(Adder, ["site", "action"]).expose(
    "chaos_injected_total"
)

# site → match keys the wired call site actually supplies to check().
# arm() validates against this: a matcher no site feeds (e.g. method
# on socket.write) would compare against None forever and the spec
# would silently never fire.
SITE_MATCH_KEYS: Dict[str, frozenset] = {
    "socket.write": frozenset({"peer"}),
    "socket.write_io": frozenset({"peer"}),
    "socket.read": frozenset({"peer"}),
    "dispatcher.dispatch": frozenset(),
    "scheduler.callback": frozenset(),
    "ici.send": frozenset({"peer"}),
    "ici.chunk": frozenset({"peer"}),
    "dcn.send": frozenset({"peer"}),
    # direction carries the FRAME KIND ("data"/"data_part"/"feedback"/
    # "close"/"half_close") so a plan can fault exactly one frame
    # class (e.g. FEEDBACK loss without touching DATA).  RST frames
    # are not injectable — they ARE the failure path
    "stream.frame": frozenset({"peer", "direction"}),
    "batch.flush": frozenset({"method"}),
    # method carries the batched method whose fused sharded execution
    # is about to dispatch its cross-shard merge (batching/sharded.py)
    "collective.merge": frozenset({"method"}),
    # tier carries the ADMISSION TIER the request resolved to, so a
    # storm plan can reject exactly one tier's traffic
    "admission.decide": frozenset({"method", "tier"}),
    # method carries the RPC method of the submission window about to
    # cross the boundary (client/ring.py SubmissionRing.flush);
    # direction selects the ring HALF — "submit" is the client window
    # flush, "flush" the server response-ring flush (server/server.py
    # resp_ring_flush), so a plan can fault exactly one side
    "ring.submit": frozenset({"method", "direction"}),
    # method carries the CACHE KEY being looked up (cache/store.py
    # HBMCacheStore.get), so a plan can fault exactly one key's reads
    "cache.lookup": frozenset({"method"}),
    # method carries the KEY being copied shard→shard by the live
    # re-sharding coordinator (resharding/migration.py), so a plan can
    # fault exactly one key's copy attempts
    "reshard.copy": frozenset({"method"}),
    # method carries the migration NAME about to bump its epoch
    "reshard.cutover": frozenset({"method"}),
    # method carries the replica GROUP whose lease is being granted or
    # renewed (replication/lease.py LeaseBoard) — drop forces a
    # failover by losing the grant/renewal
    "replica.lease": frozenset({"method"}),
    # method carries the replica GROUP, peer the FOLLOWER whose quorum
    # ack is in flight (replication/group.py ReplicaNode.apply) — a
    # plan can degrade exactly one follower's acks
    "replica.ack": frozenset({"method", "peer"}),
    # deep device-profile capture (observability/profiling.py
    # device_capture) — no match keys, the capture path is singular
    "profile.capture": frozenset(),
    # method carries the per-layer KV KEY being shipped into the cache
    # tier by prefill or a migration checkpoint (serving/prefill.py,
    # serving/decode.py), so a plan can fault exactly one session's —
    # or one layer's — ship
    "kv.ship": frozenset({"method"}),
    # method carries the SESSION id whose decode handoff is about to
    # run (serving/router.py SessionChannel), so a plan can abort
    # exactly one session's migration
    "session.migrate": frozenset({"method"}),
    "native.srv_read": frozenset(),  # native match is rejected anyway
    "native.srv_write": frozenset(),
}

# site → actions it actually applies.  arm() validates against this:
# an unsupported pair would otherwise count hits (budget, metrics,
# /chaos) while injecting nothing — a plan that silently tests nothing.
SITE_ACTIONS: Dict[str, frozenset] = {
    "socket.write": frozenset({"drop", "delay_us", "reset", "corrupt"}),
    "socket.write_io": frozenset({"short_write", "eagain_storm"}),
    "socket.read": frozenset(
        {"short_read", "drop", "delay_us", "reset", "eagain_storm"}
    ),
    "dispatcher.dispatch": frozenset({"delay_us"}),
    "scheduler.callback": frozenset({"delay_us"}),
    "ici.send": frozenset(
        {"drop", "delay_us", "reset", "close_mid_batch"}
    ),
    # per-chunk site inside the pipelined chunked send: "reset" faults
    # chunk k mid-stream (the frame fails with ONE ERPC error and its
    # window credits never leak — regression-tested), "delay_us"
    # stretches one pipeline stage
    "ici.chunk": frozenset({"delay_us", "reset"}),
    "dcn.send": frozenset({"drop", "delay_us", "reset", "reorder"}),
    # streaming-RPC frame egress (streaming/stream.py _send_frame):
    # "drop" loses one frame (a lost FEEDBACK must not deadlock a
    # blocked writer — the idle-timeout escape is regression-tested),
    # "reorder" stash-swaps adjacent frames, "reset" RSTs the STREAM
    # while the shared socket stays up
    "stream.frame": frozenset({"drop", "delay_us", "reorder", "reset"}),
    # micro-batcher flush decision (batching/batcher.py): "drop" loses
    # the flush — the whole window sheds cleanly, every queued
    # controller completes exactly once with EOVERCROWDED (the recovery
    # harness proves no window-credit or freelist-slot leak); "delay_us"
    # stretches one flush (queue_wait grows, deadline sheds may follow)
    "batch.flush": frozenset({"delay_us", "drop"}),
    # cross-shard collective merge of a fused sharded batch
    # (batching/sharded.py ShardedFusedKernel): "delay_us" stretches
    # the merge dispatch, "reset" fails it — the whole batch surfaces
    # ONE exception that the handler maps to per-row ERPC errors while
    # other key-groups in the same batch still execute
    "collective.merge": frozenset({"delay_us", "reset"}),
    # admission decision point (server/admission.py): "reject" forces
    # a shed (EOVERCROWDED, the retry-elsewhere code) — the storm
    # suite's deterministic admission-pressure knob; "delay_us"
    # stretches the decision itself
    "admission.decide": frozenset({"reject", "delay_us"}),
    # client submission-ring window about to cross into the C mux
    # (client/ring.py): "drop" loses the whole window BEFORE it reaches
    # the engine — every slot must still complete exactly once with
    # EFAILEDSOCKET (no stranded waiter, no registered cid leaked);
    # "delay_us" stretches the boundary crossing
    "ring.submit": frozenset({"drop", "delay_us"}),
    # HBM cache store lookup (cache/store.py): "drop" forces a miss
    # for a present key (the client's spill/refill path under a healthy
    # server), "delay_us" stretches the lookup (straggler replica —
    # the locality LB's shed-aware ordering is regression-tested
    # against it)
    "cache.lookup": frozenset({"drop", "delay_us"}),
    # live re-sharding per-key copy attempt (resharding/migration.py
    # ReshardCoordinator): "drop" skips this attempt (the key stays
    # pending and is retried next round — the complete-or-rollback
    # proof rides this), "corrupt" flips the post-copy checksum so the
    # range re-copies (counted in rpc_reshard_checksum_failures),
    # "delay_us" stretches one copy (widens the kill-mid-COPY window)
    "reshard.copy": frozenset({"drop", "delay_us", "corrupt"}),
    # the single epoch-bump publication that cuts traffic over to the
    # new scheme: "drop" aborts the cutover (the migration must roll
    # back to the old scheme cleanly), "delay_us" stretches the window
    # where in-flight fan-outs race the bump
    "reshard.cutover": frozenset({"drop", "delay_us"}),
    # leader-lease grant/renewal decision (replication/lease.py
    # LeaseBoard.acquire/renew): "drop" loses the grant or renewal —
    # the lease lapses and the group fails over within the TTL budget
    # (the RecoveryHarness leader-kill acceptance rides this);
    # "delay_us" stretches the decision (slow board)
    "replica.lease": frozenset({"drop", "delay_us"}),
    # a follower's quorum ack (replication/group.py ReplicaNode.apply):
    # "drop" loses the ack AFTER the follower applied the write — the
    # write is durable there but uncounted, so quorum degrades while
    # readable data does not (regression-tested); "delay_us" stretches
    # the ack (slow follower — the write waits, never wedges)
    "replica.ack": frozenset({"drop", "delay_us"}),
    # deep-capture entry (observability/profiling.py device_capture):
    # "drop" fails the capture before any profiler session arms (the
    # page degrades to an error response; serving and the trace-session
    # state must be untouched — regression-tested), "delay_us"
    # stretches the capture start (a slow capture must not stall
    # serving: it runs on the caller's worker only)
    "profile.capture": frozenset({"delay_us", "drop"}),
    # prefill's (or a checkpoint's) per-layer KV SET into the cache
    # tier (serving/prefill.py _ship_kv): "drop" fails the ship — the
    # prefill RPC surfaces ONE ERPC error to the client, NEVER a
    # silent recompute (a later retry re-executes prefill explicitly
    # and counts in prefill_executions); "delay_us" stretches one
    # layer's ship (slow cache replica)
    "kv.ship": frozenset({"drop", "delay_us"}),
    # the decode-session handoff decision (serving/router.py): "drop"
    # aborts the handoff — the session STAYS on its source replica and
    # keeps streaming there (ownership epoch does not bump);
    # "delay_us" stretches the handoff window (tokens drain, target
    # admission waits)
    "session.migrate": frozenset({"drop", "delay_us"}),
    "native.srv_read": frozenset(
        {"short_read", "eagain_storm", "reset", "delay_us"}
    ),
    "native.srv_write": frozenset(
        {"short_write", "eagain_storm", "reset", "delay_us"}
    ),
}

SITES: Dict[str, str] = {
    "socket.write": "Socket.write queue-time (drop/delay_us/reset/corrupt)",
    "socket.write_io": "per-chunk socket write (short_write/eagain_storm)",
    "socket.read": "transport read loop (short_read/drop/delay_us/reset/"
                   "eagain_storm)",
    "dispatcher.dispatch": "event-dispatcher IN hand-off (delay_us)",
    "scheduler.callback": "runtime task run (delay_us)",
    "ici.send": "ICI fabric leg (drop/delay_us/reset/close_mid_batch)",
    "ici.chunk": "chunked ICI send, per pipeline chunk (delay_us/reset)",
    "dcn.send": "DCN bridge frame (drop/delay_us/reset/reorder)",
    "stream.frame": "streaming-RPC frame egress, per frame kind "
                    "(drop/delay_us/reorder/reset→stream RST)",
    "batch.flush": "micro-batcher flush decision (delay_us/drop→shed)",
    "collective.merge": "cross-shard merge of a fused sharded batch "
                        "(delay_us/reset→per-row ERPC)",
    "admission.decide": "admission decision at dispatch "
                        "(reject→EOVERCROWDED shed/delay_us)",
    "ring.submit": "ring window crossing into C — direction=submit is "
                   "the client window (drop→whole window EFAILEDSOCKET"
                   "/delay_us), direction=flush the server response-"
                   "ring flush (drop→window's replies lost, clients "
                   "recover by timeout/retry)",
    "cache.lookup": "HBM cache store lookup, per key "
                    "(drop→forced miss/delay_us)",
    "reshard.copy": "live re-sharding per-key copy, shard→shard "
                    "(drop→retry next round/delay_us/corrupt→re-copy)",
    "reshard.cutover": "re-sharding epoch-bump publication "
                       "(drop→rollback/delay_us)",
    "replica.lease": "leader-lease grant/renewal, per replica group "
                     "(drop→forced failover/delay_us)",
    "replica.ack": "follower quorum ack, per group+follower "
                   "(drop→ack lost after apply/delay_us)",
    "profile.capture": "deep device-profile capture entry "
                       "(drop→error page, no armed trace leaked/delay_us)",
    "kv.ship": "prefill/checkpoint KV SET into the cache tier, per "
               "layer key (drop→ERPC to client, never a silent "
               "recompute/delay_us)",
    "session.migrate": "decode-session handoff, per session "
                       "(drop→handoff aborted, session stays on "
                       "source/delay_us)",
    "native.srv_read": "engine.cpp server read (short_read/eagain_storm/"
                       "reset/delay_us)",
    "native.srv_write": "engine.cpp server write/burst flush (short_write/"
                        "eagain_storm/reset/delay_us)",
}

_NATIVE_SITE_IDS = {"native.srv_read": 0, "native.srv_write": 1}
# engine.cpp FaultAction enum: 1=short 2=eagain 3=reset 4=delay
_NATIVE_ACTIONS = {
    "short_read": 1,
    "short_write": 1,
    "eagain_storm": 2,
    "reset": 3,
    "delay_us": 4,
}

# delays are test instruments, not stress weapons: cap one injected
# sleep so a bad plan can't wedge a dispatcher thread for seconds
MAX_DELAY_US = 200_000

_lock = threading.Lock()
_count_lock = threading.Lock()  # guards _hit_log/_site_counts updates
_plan: Optional[FaultPlan] = None
_by_site: Dict[str, List[FaultSpec]] = {}
_hit_log: List[Tuple[str, str, int]] = []
# replay-log cap: the determinism suite compares modest logs; a chaos
# load test firing millions of times must not pin memory (counts keep
# accumulating in _site_counts past the cap)
HIT_LOG_MAX = 100_000
# incremental per-(site, action) counters of the current plan — O(1)
# per hit, O(sites) per site_hits() render
_site_counts: Dict[Tuple[str, str], int] = {}
# site -> (action, cumulative hits) already folded into
# chaos_injected_total; kept across disarm (cleared at the next arm)
# so post-run renders still show what the plan did
_native_harvested: Dict[str, Tuple[str, int]] = {}


def sleep_us(us: int) -> None:
    _time.sleep(min(int(us), MAX_DELAY_US) / 1e6)


# ---------------------------------------------------------------------------
# arm / disarm
# ---------------------------------------------------------------------------

def arm(plan: FaultPlan) -> None:
    """Arm `plan` process-wide (replacing any armed plan).  Specs for
    ``native.*`` sites are programmed into the C engine.

    Validation is all-or-nothing and runs BEFORE any state changes: a
    bad plan raises without disarming the currently armed plan and
    without programming any native knob (a half-armed engine whose
    injector reports disarmed would be the worst possible state)."""
    global _plan, armed
    with _lock:
        by_site: Dict[str, List[FaultSpec]] = {}
        for spec in plan.specs:
            if spec.site not in SITES:
                raise ValueError(f"unknown injection site {spec.site!r}")
            if spec.action not in SITE_ACTIONS[spec.site]:
                raise ValueError(
                    f"site {spec.site} does not apply action "
                    f"{spec.action!r} (supported: "
                    f"{sorted(SITE_ACTIONS[spec.site])})"
                )
            bad_keys = set(spec.match) - SITE_MATCH_KEYS[spec.site]
            if bad_keys:
                raise ValueError(
                    f"site {spec.site} does not supply match keys "
                    f"{sorted(bad_keys)} (supported: "
                    f"{sorted(SITE_MATCH_KEYS[spec.site])}) — the spec "
                    f"would silently never fire"
                )
            by_site.setdefault(spec.site, []).append(spec)
        _validate_native(by_site)
        _disarm_locked()
        plan.reset_runtime()
        _arm_native(plan, by_site)
        _by_site.clear()
        _by_site.update(by_site)
        del _hit_log[:]
        _site_counts.clear()
        _native_harvested.clear()
        _plan = plan
        _attach_runtime_hooks()
        armed = True


def disarm() -> None:
    global armed
    with _lock:
        _disarm_locked()


def _disarm_locked() -> None:
    global _plan, armed
    armed = False
    _plan = None
    # fold the engine's final counters into the metric BEFORE clearing
    # the knobs (and before _by_site goes away — the harvest labels
    # hits with the armed spec's action), so post-disarm renders still
    # agree with chaos_injected_total for native sites too
    _harvest_native()
    _clear_native()
    _by_site.clear()
    _detach_runtime_hooks()


def active_plan() -> Optional[FaultPlan]:
    return _plan


# ---------------------------------------------------------------------------
# the per-site decision (hot only while armed)
# ---------------------------------------------------------------------------

def check(
    site: str,
    peer: Optional[str] = None,
    method: Optional[str] = None,
    direction: Optional[str] = None,
    tier: Optional[str] = None,
) -> Optional[FaultSpec]:
    """Evaluate `site` against the armed plan; returns the first spec
    that matches AND fires (recording the hit), else None."""
    plan = _plan
    if plan is None:
        return None
    specs = _by_site.get(site)
    if not specs:
        return None
    for spec in specs:
        if not spec.matches(peer, method, direction, tier):
            continue
        n = spec.should_fire(plan.seed)
        if n >= 0:
            # recording rides a dedicated lock: fires are rare (only
            # actual faults) and the read-modify-write on the counter
            # dict spans bytecodes — racing worker threads would lose
            # increments and break the /chaos == chaos_injected_total
            # agreement
            key = (site, spec.action)
            with _count_lock:
                if len(_hit_log) < HIT_LOG_MAX:
                    _hit_log.append((site, spec.action, n))
                _site_counts[key] = _site_counts.get(key, 0) + 1
            chaos_injected_total.get_stats([site, spec.action]) << 1
            return spec
    return None


def hit_log() -> List[Tuple[str, str, int]]:
    """The (site, action, traversal_index) sequence recorded since the
    last arm() — the replay artifact the determinism suite compares.
    Capped at HIT_LOG_MAX entries; counts keep accumulating in
    site_hits() past the cap."""
    return list(_hit_log)


def site_hits() -> Dict[str, Dict[str, int]]:
    """Per-site per-action hit counts of the CURRENT/most recent plan,
    native sites included (harvesting their C counters as a side
    effect so chaos_injected_total stays in agreement)."""
    _harvest_native()
    out: Dict[str, Dict[str, int]] = {}
    for (site, action), n in list(_site_counts.items()):
        out.setdefault(site, {})[action] = n
    for site, (action, total) in _native_harvested.items():
        if total:
            out.setdefault(site, {})[action] = total
    return out


def describe() -> dict:
    """State dump for the /chaos builtin."""
    plan = _plan
    return {
        "armed": armed,
        "plan": plan.to_dict() if plan is not None else None,
        "sites": site_hits(),
        "catalog": SITES,
    }


# ---------------------------------------------------------------------------
# native (engine.cpp) sites
# ---------------------------------------------------------------------------

def _native_lib():
    from incubator_brpc_tpu import native

    if not native.available():
        return None
    return native


def _native_spec_for(site: str) -> Optional[FaultSpec]:
    specs = _by_site.get(site)
    return specs[0] if specs else None


def _validate_native(by_site: Dict[str, List[FaultSpec]]) -> None:
    """Full validation of every native.* spec, run BEFORE any knob is
    programmed — arm() is all-or-nothing."""
    native_sites = [s for s in by_site if s.startswith("native.")]
    if not native_sites:
        return
    if _native_lib() is None:
        raise RuntimeError(
            "plan names native.* sites but the C engine is not built"
        )
    for site in native_sites:
        specs = by_site[site]
        if len(specs) > 1:
            raise ValueError(f"native site {site} supports one spec per plan")
        spec = specs[0]
        if spec.action not in _NATIVE_ACTIONS:
            raise ValueError(
                f"action {spec.action!r} unsupported on native site {site}"
            )
        # the C side has no every_nth/ttl knobs — refuse rather than
        # silently approximate (a "5s" native plan must not quietly
        # run forever).  match on native sites is already rejected by
        # arm()'s generic SITE_MATCH_KEYS check (they supply no keys).
        if spec.every_nth:
            raise ValueError(f"native site {site} takes probability, "
                             "not every_nth")
        if spec.ttl_s:
            raise ValueError(f"native site {site} has no TTL — bound it "
                             "with max_hits or an explicit disarm")


def _arm_native(plan: FaultPlan, by_site: Dict[str, List[FaultSpec]]) -> None:
    """Program the already-validated native specs into the engine."""
    for site in by_site:
        if not site.startswith("native."):
            continue
        spec = by_site[site][0]
        nat = _native_lib()
        prob_u32 = min(0xFFFFFFFF, int(spec.probability * 4294967296.0))
        nat.set_fault(
            _NATIVE_SITE_IDS[site], _NATIVE_ACTIONS[spec.action], spec.arg,
            prob_u32, spec_seed(plan.seed, spec.spec_id),
            spec.max_hits if spec.max_hits else -1,
        )


def _has_native_sites() -> bool:
    return any(s.startswith("native.") for s in _by_site)


def _clear_native() -> None:
    if not _has_native_sites():
        return  # never touch the engine (a lazy g++ build!) needlessly
    nat = _native_lib()
    if nat is not None:
        nat.clear_faults()


def _harvest_native() -> None:
    """Fold the C engine's per-site hit counters into
    chaos_injected_total (delta against the last harvest).  The armed
    spec's action is recorded WITH the count so post-disarm renders
    (when _by_site is gone) keep the right label.  The delta
    computation is a read-modify-write on _native_harvested: rides
    _count_lock so concurrent harvesters (/chaos renders vs disarm)
    cannot double-count a delta into the metric."""
    if not _has_native_sites():
        # python-only plan (or post-disarm): never touch _native_lib —
        # on a box without the built engine that would run a g++
        # compile inside a /chaos render
        return
    nat = _native_lib()
    if nat is None:
        return
    for site, sid in _NATIVE_SITE_IDS.items():
        spec = _native_spec_for(site)
        if spec is None:
            continue  # site not in the armed plan: counter stays 0
        total = nat.fault_hits(sid)
        with _count_lock:
            _, prev = _native_harvested.get(site, (spec.action, 0))
            if total <= prev:
                continue
            _native_harvested[site] = (spec.action, total)
        chaos_injected_total.get_stats([site, spec.action]) << (total - prev)


# ---------------------------------------------------------------------------
# low-level runtime hooks (scheduler / event dispatcher)
#
# Those modules sit below the metrics stack, so instead of importing
# this module they expose a hook slot the injector fills while armed —
# their disarmed cost is one `is None` check.
# ---------------------------------------------------------------------------

def _scheduler_hook() -> None:
    spec = check("scheduler.callback")
    if spec is not None and spec.action == "delay_us":
        sleep_us(spec.arg)


def _dispatcher_hook() -> None:
    spec = check("dispatcher.dispatch")
    if spec is not None and spec.action == "delay_us":
        sleep_us(spec.arg)


def _attach_runtime_hooks() -> None:
    from incubator_brpc_tpu.runtime import scheduler
    from incubator_brpc_tpu.transport import event_dispatcher

    if "scheduler.callback" in _by_site:
        scheduler.set_chaos_hook(_scheduler_hook)
    if "dispatcher.dispatch" in _by_site:
        event_dispatcher.set_chaos_hook(_dispatcher_hook)


def _detach_runtime_hooks() -> None:
    import sys

    sched = sys.modules.get("incubator_brpc_tpu.runtime.scheduler")
    if sched is not None:
        sched.set_chaos_hook(None)
    disp = sys.modules.get("incubator_brpc_tpu.transport.event_dispatcher")
    if disp is not None:
        disp.set_chaos_hook(None)
