"""Recovery-invariant harness — run a workload under a FaultPlan and
prove the framework recovered.

The harness owns the arm/run/disarm lifecycle and checks the
invariants every resilience path must hold:

  * no deadlock — the workload completes within a bounded wall clock
    (a wedged read loop / lost wakeup shows up here, not in prod);
  * only ERPC-family error codes surface to callers — transport chaos
    may fail RPCs, but never with exceptions or alien codes;
  * pooled Controllers carry no state across a failed call — the
    freelist hands out objects indistinguishable from fresh ones;
  * metrics / windows return to baseline once the plan is done —
    receive windows, concurrency counters and inflight gauges drain
    back to their pre-chaos values (leaks here wedge later traffic).

Reply-ordering invariants (HTTP/RESP FIFO) are protocol-specific and
live in the chaos test suites; the harness supplies the lifecycle and
the generic checks.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from incubator_brpc_tpu import errors as _errors
from incubator_brpc_tpu.chaos import injector
from incubator_brpc_tpu.chaos.plan import FaultPlan

#: every code the framework may legitimately surface to a caller
#: (the ERPC family defined in errors.py), plus 0 for success.
#: Internal trigger codes are EXCLUDED: they drive arbitration inside
#: the id lock and must never reach a caller — leaking one is exactly
#: the class of bug this invariant exists to catch.
ERROR_WHITELIST = (
    frozenset(
        v for k, v in vars(_errors).items()
        if k.isupper() and isinstance(v, int)
    )
    - {_errors.EBACKUPREQUEST, _errors.EPCHANFINISH}
) | {0}


class InvariantViolation(AssertionError):
    """A recovery invariant failed under the armed plan."""


@dataclass
class ChaosReport:
    wall_s: float = 0.0
    hits: Dict[str, Dict[str, int]] = field(default_factory=dict)
    error_codes: List[int] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    workload_result: object = None

    def ok(self) -> bool:
        return not self.violations


def wait_until(pred: Callable[[], bool], timeout_s: float = 5.0,
               interval_s: float = 0.01) -> bool:
    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        if pred():
            return True
        _time.sleep(interval_s)
    return bool(pred())


def controller_pool_clean(sample: int = 16) -> bool:
    """Sample the pooled-Controller freelist: every pooled object must
    be fully wiped (release() clears __dict__ back to class defaults).
    Non-destructive — sampled controllers go back to the pool."""
    from incubator_brpc_tpu.client.controller import (
        acquire_controller,
        release_controller,
    )

    taken = []
    clean = True
    for _ in range(sample):
        c = acquire_controller()
        if c.__dict__:
            clean = False
        taken.append(c)
    for c in taken:
        release_controller(c)
    return clean


class RecoveryHarness:
    """Arm a plan, run a workload with a bounded wall clock, disarm,
    then check the recovery invariants.

    ``baseline_probes`` is a sequence of (name, fn) pairs; each fn
    returns a number captured before arming.  After the run the
    harness waits up to ``settle_s`` for every probe to return to its
    captured value (receive windows, concurrency counters, …).

    The workload callable receives the harness and may report
    per-call outcomes via :meth:`record_error`; its return value lands
    on the report.
    """

    def __init__(
        self,
        plan: FaultPlan,
        wall_clock_s: float = 30.0,
        settle_s: float = 5.0,
        baseline_probes: Sequence[Tuple[str, Callable[[], float]]] = (),
        check_controller_pool: bool = True,
    ):
        self.plan = plan
        self.wall_clock_s = wall_clock_s
        self.settle_s = settle_s
        self.baseline_probes = list(baseline_probes)
        self.check_controller_pool = check_controller_pool
        self._errors: List[int] = []
        self._errors_lock = threading.Lock()

    def record_error(self, code: int) -> None:
        """Workloads report each finished call's error code here."""
        with self._errors_lock:
            self._errors.append(int(code))

    def run(self, workload: Callable[["RecoveryHarness"], object]) -> ChaosReport:
        report = ChaosReport()
        baselines = [(name, fn()) for name, fn in self.baseline_probes]
        box: dict = {}

        def _runner():
            try:
                box["result"] = workload(self)
            except BaseException as e:  # noqa: BLE001 — judged below
                box["exc"] = e

        injector.arm(self.plan)
        t0 = _time.monotonic()
        worker = threading.Thread(
            target=_runner, daemon=True, name="chaos-workload"
        )
        worker.start()
        worker.join(self.wall_clock_s)
        still_running = worker.is_alive()
        report.wall_s = _time.monotonic() - t0
        injector.disarm()
        # capture AFTER disarm: counters persist until the next arm, and
        # a fault firing between a pre-disarm capture and the disarm
        # would show in chaos_injected_total but not on the report
        report.hits = injector.site_hits()
        if still_running:
            # one grace join after disarm: a workload blocked ON an
            # injected fault may finish immediately once it clears
            worker.join(2.0)
            if worker.is_alive():
                report.violations.append(
                    f"deadlock: workload still running after "
                    f"{self.wall_clock_s:.1f}s wall clock"
                )
        if "exc" in box:
            report.violations.append(
                f"workload raised {box['exc']!r} — chaos must surface as "
                f"error codes, not exceptions"
            )
        report.workload_result = box.get("result")
        with self._errors_lock:
            report.error_codes = list(self._errors)
        for code in report.error_codes:
            if code not in ERROR_WHITELIST:
                report.violations.append(
                    f"non-ERPC error code {code} surfaced to a caller"
                )
        if self.check_controller_pool and not controller_pool_clean():
            report.violations.append(
                "pooled Controller carried state across release()"
            )
        for (name, fn), (_, base) in zip(self.baseline_probes, baselines):
            if not wait_until(lambda f=fn, b=base: f() == b, self.settle_s):
                report.violations.append(
                    f"metric {name!r} did not return to baseline "
                    f"({fn()} != {base}) within {self.settle_s:.1f}s"
                )
        return report

    def run_or_raise(self, workload) -> ChaosReport:
        report = self.run(workload)
        if not report.ok():
            raise InvariantViolation("; ".join(report.violations))
        return report
