"""FaultPlan — the declarative, seeded model of a chaos experiment.

A plan is a list of FaultSpecs.  Each spec names an injection SITE
(see chaos/injector.py for the catalog), an ACTION the site knows how
to apply, an optional MATCH on context (peer / method / direction),
and a SCHEDULE: either ``every_nth`` (fire on every Nth traversal of
the site) or ``probability`` driven by a seeded counter-mode PRNG.

Determinism is the load-bearing property: the fire/no-fire decision
for the k-th traversal of a spec is a pure function of
``(plan.seed, spec index, k)`` — no shared global PRNG whose state
interleaves across threads — so a replay of the same plan over the
same traversal sequence yields the identical injection sequence
(the chaos suite replays plans and compares per-site hit logs).

Plans load from dicts/JSON (the wire format of the ``/chaos`` builtin
and ``rpc_press --chaos-plan``) and are armed per-process through
``chaos.injector.arm``.
"""

from __future__ import annotations

import itertools
import json
import threading
import time as _time
from typing import Dict, List, Optional, Sequence

from incubator_brpc_tpu.utils.hashes import GOLDEN64 as _GOLDEN
from incubator_brpc_tpu.utils.hashes import fmix64 as _mix64

_MASK64 = (1 << 64) - 1

#: every action a site may be asked to apply; individual sites support
#: a subset (see docs/chaos.md for the site x action matrix)
ACTIONS = (
    "drop",
    "delay_us",
    "short_read",
    "short_write",
    "corrupt",
    "reset",
    "eagain_storm",
    "close_mid_batch",
    "reorder",
    "reject",
)


def spec_seed(seed: int, spec_id: int) -> int:
    """Per-spec seed derivation — the ONE place it is defined.  The
    native bridge (chaos/injector.py _arm_native) programs engine.cpp
    with this value, and the engine folds the traversal counter and
    mixes exactly like decide().  Each side replays ITS OWN sequence
    bit-identically; across languages the hash is identical but the
    probability compare differs in precision (C quantizes p to 32
    bits; probability=1.0 always fires on both sides)."""
    return (seed + spec_id * 0xBF58476D1CE4E5B9) & _MASK64


def decide(seed: int, spec_id: int, n: int) -> float:
    """Uniform [0,1) for the n-th traversal of spec `spec_id` under
    `seed` — pure, stateless, replayable."""
    return _mix64(spec_seed(seed, spec_id) + n * _GOLDEN) / 2.0**64


class FaultSpec:
    """One fault: site + match + action + schedule + budget.

    Runtime state (traversal counter, hit log) lives on the spec and is
    reset every time its plan is armed, so one plan object can be
    armed repeatedly and each run replays from traversal 0.
    """

    __slots__ = (
        "site", "action", "arg", "probability", "every_nth", "max_hits",
        "ttl_s", "match", "spec_id", "_counter", "_hits", "_deadline",
        "_budget_lock",
    )

    def __init__(
        self,
        site: str,
        action: str,
        arg: int = 0,
        probability: float = 1.0,
        every_nth: int = 0,
        max_hits: int = 0,
        ttl_s: float = 0.0,
        match: Optional[Dict[str, str]] = None,
    ):
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        probability = float(probability)
        if not 0.0 < probability <= 1.0:
            # p <= 0 arms successfully but can never fire — a plan
            # that silently tests nothing (a 0.0/negative typo must
            # fail loudly, like every other unusable-spec shape)
            raise ValueError(
                f"probability must be in (0, 1], got {probability}"
            )
        self.site = site
        self.action = action
        self.arg = int(arg)
        self.probability = probability
        self.every_nth = int(every_nth)
        # eagain_storm without a budget would starve the Python read
        # loop forever (it retries the same site until the spec stops
        # firing) — default it to a finite storm
        if action == "eagain_storm" and not max_hits:
            max_hits = 64
        self.max_hits = int(max_hits)
        self.ttl_s = float(ttl_s)
        self.match = dict(match) if match else {}
        if self.every_nth and probability != 1.0:
            raise ValueError(
                "every_nth and probability are alternative schedules — "
                "set one (probability would be silently ignored)"
            )
        self.spec_id = 0  # assigned by the plan
        self._counter = itertools.count()  # GIL-atomic traversal counter
        self._hits = 0
        self._budget_lock = threading.Lock()  # max_hits is a GATE: the
        # read-modify-write must not overshoot under concurrent fires
        self._deadline = 0.0

    # ---- runtime -----------------------------------------------------------
    def reset_runtime(self) -> None:
        self._counter = itertools.count()
        self._hits = 0
        self._deadline = (
            _time.monotonic() + self.ttl_s if self.ttl_s > 0 else 0.0
        )

    def matches(self, peer, method: Optional[str],
                direction: Optional[str],
                tier: Optional[str] = None) -> bool:
        m = self.match
        if not m:
            return True
        want = m.get("peer")
        # peer may be any object (EndPoint, coords); it is stringified
        # HERE, only when a spec actually matches on it — call sites
        # pass the raw object so the no-matcher path never pays str()
        if want and (peer is None or want not in str(peer)):
            return False
        want = m.get("method")
        if want and method != want:
            return False
        want = m.get("direction")
        if want and direction != want:
            return False
        want = m.get("tier")
        if want and tier != want:
            return False
        return True

    def should_fire(self, seed: int) -> int:
        """Advance the traversal counter; return the traversal index
        (>=0) if this traversal fires, else -1."""
        if self._deadline and _time.monotonic() >= self._deadline:
            return -1
        if self.max_hits and self._hits >= self.max_hits:
            return -1  # cheap early-out; the lock below is the gate
        n = next(self._counter)
        if self.every_nth > 0:
            if n % self.every_nth != self.every_nth - 1:
                return -1
        elif self.probability < 1.0:
            if decide(seed, self.spec_id, n) >= self.probability:
                return -1
        with self._budget_lock:
            if self.max_hits and self._hits >= self.max_hits:
                return -1  # a concurrent fire claimed the last slot
            self._hits += 1
        return n

    @property
    def hits(self) -> int:
        return self._hits

    # ---- (de)serialization -------------------------------------------------
    def to_dict(self) -> dict:
        d = {"site": self.site, "action": self.action}
        if self.arg:
            d["arg"] = self.arg
        if self.probability < 1.0:
            d["probability"] = self.probability
        if self.every_nth:
            d["every_nth"] = self.every_nth
        if self.max_hits:
            d["max_hits"] = self.max_hits
        if self.ttl_s:
            d["ttl_s"] = self.ttl_s
        if self.match:
            d["match"] = dict(self.match)
        return d

    _KNOWN_KEYS = frozenset({
        "site", "action", "arg", "probability", "every_nth", "max_hits",
        "ttl_s", "match",
    })

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        unknown = set(d) - cls._KNOWN_KEYS
        if unknown:
            # a typo'd key (max_hit vs max_hits) silently dropped would
            # arm a DIFFERENT experiment than the operator wrote
            raise ValueError(
                f"unknown fault spec keys {sorted(unknown)} "
                f"(known: {sorted(cls._KNOWN_KEYS)})"
            )
        return cls(
            site=d["site"],
            action=d["action"],
            arg=d.get("arg", 0),
            probability=d.get("probability", 1.0),
            every_nth=d.get("every_nth", 0),
            max_hits=d.get("max_hits", 0),
            ttl_s=d.get("ttl_s", 0.0),
            match=d.get("match"),
        )

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"FaultSpec({self.to_dict()!r})"


class FaultPlan:
    """An ordered list of FaultSpecs plus the seed that drives them."""

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: int = 0,
                 name: str = ""):
        self.specs: List[FaultSpec] = list(specs)
        self.seed = int(seed) & _MASK64
        self.name = name
        for i, spec in enumerate(self.specs):
            spec.spec_id = i

    def reset_runtime(self) -> None:
        for spec in self.specs:
            spec.reset_runtime()

    def sites(self) -> List[str]:
        return sorted({s.site for s in self.specs})

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "specs": [s.to_dict() for s in self.specs],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        unknown = set(d) - {"name", "seed", "specs"}
        if unknown:
            raise ValueError(
                f"unknown fault plan keys {sorted(unknown)} "
                f"(known: ['name', 'seed', 'specs'])"
            )
        return cls(
            specs=[FaultSpec.from_dict(s) for s in d.get("specs", [])],
            seed=d.get("seed", 0),
            name=d.get("name", ""),
        )

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        return cls.from_dict(json.loads(s))
