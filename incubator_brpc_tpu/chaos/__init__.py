"""chaos/ — deterministic, seeded fault injection.

Every resilience path in the framework (retry/backoff, circuit
breaker + cluster recovery, health checks, backup requests,
receive-window accounting, the native partial-frame/burst-flush state
machines) is only proven by the failures it survives.  This package
turns those failures into deterministic, replayable tier-1 tests:

  * :mod:`chaos.plan` — FaultPlan / FaultSpec: seeded, declarative
    fault specs loadable from JSON;
  * :mod:`chaos.injector` — the process-wide site registry (near-zero
    disarmed cost) + ``chaos_injected_total`` metrics + the native
    ``ns_set_fault`` bridge;
  * :mod:`chaos.harness` — run a workload under a plan and check
    recovery invariants (bounded wall clock, ERPC-only errors, pooled
    Controller hygiene, metrics back to baseline).

Runtime control: the ``/chaos`` builtin (GET state, POST plan,
``?disarm=1``) and ``rpc_press --chaos-plan``.  See docs/chaos.md.
"""

from incubator_brpc_tpu.chaos.plan import ACTIONS, FaultPlan, FaultSpec
from incubator_brpc_tpu.chaos.harness import (
    ChaosReport,
    InvariantViolation,
    RecoveryHarness,
    controller_pool_clean,
)
from incubator_brpc_tpu.chaos.storm import (
    admission_pressure_plan,
    replica_storm_plan,
    reshard_storm_plan,
    storm_plan,
)

__all__ = [
    "ACTIONS",
    "FaultPlan",
    "FaultSpec",
    "ChaosReport",
    "InvariantViolation",
    "RecoveryHarness",
    "controller_pool_clean",
    "admission_pressure_plan",
    "replica_storm_plan",
    "reshard_storm_plan",
    "storm_plan",
]
