"""Chaos storms — standing fault-plan shapes for the overload suite.

A *storm* is the composed failure mode production actually sees: some
fraction of links resetting while one replica turns slow, under mixed
multi-tenant load.  This module builds those plans from knobs instead
of hand-rolled spec lists, so the storm SUITE (tests/test_overload_
storm.py), the bench (`bench.py bench_overload_storm`) and operators
(`/chaos` POST of `plan.to_dict()`) all fire the identical seeded,
replayable experiment (docs/overload.md, docs/chaos.md).

Shapes:

* ``storm_plan`` — N% link resets across a peer set (socket.write
  ``reset``) + one slow replica (socket.read ``delay_us`` matched on
  that peer: every response read from it stalls, which is what a
  fabric-degraded or GC-wedged replica looks like from the client).
* ``admission_pressure_plan`` — deterministic admission rejections via
  the ``admission.decide`` site, optionally scoped to one tier: load
  tests of the shed/retry-elsewhere path with zero real saturation.
* ``reshard_storm_plan`` — the kill-mid-migration shape: link resets
  across the shard peers WHILE the live re-sharding coordinator's
  per-key copies drop/stall (``reshard.copy``), optionally stretching
  the cutover publication (``reshard.cutover``).  The acceptance suite
  (tests/test_resharding.py) kills a source shard under this plan and
  proves complete-or-rollback.
* ``replica_storm_plan`` — the leader-kill shape for the replicated
  HA tier: lease grants/renewals dropping (``replica.lease`` — forced
  failovers), one follower's quorum acks degrading (``replica.ack``),
  and optionally one replica's responses stalling on the client's
  read plane (socket.read ``delay_us``).  The acceptance suite
  (tests/test_replication.py) kills a LEADER mid-write-storm under
  this plan and proves zero acked-write loss.
"""

from __future__ import annotations

from typing import Optional, Sequence

from incubator_brpc_tpu.chaos.plan import FaultPlan, FaultSpec


def storm_plan(
    peers: Sequence[object],
    seed: int,
    reset_pct: float = 0.25,
    reset_max_hits: int = 0,
    slow_peer: Optional[object] = None,
    slow_delay_us: int = 50_000,
    slow_pct: float = 1.0,
    slow_max_hits: int = 0,
    name: str = "storm",
) -> FaultPlan:
    """``reset_pct`` of writes toward each peer in ``peers`` reset the
    connection; reads from ``slow_peer`` stall ``slow_delay_us`` each
    (capped by the injector's MAX_DELAY_US = 200ms).  Peers are
    matched as substrings of the remote endpoint ("127.0.0.1:8000",
    "slice0/chip1"...).  Budgets default unlimited — bound a standing
    storm with max_hits or ttl, or disarm explicitly."""
    specs = []
    for peer in peers:
        specs.append(
            FaultSpec(
                "socket.write", "reset",
                probability=reset_pct,
                max_hits=reset_max_hits,
                match={"peer": str(peer)},
            )
        )
    if slow_peer is not None:
        specs.append(
            FaultSpec(
                "socket.read", "delay_us",
                arg=int(slow_delay_us),
                probability=slow_pct,
                max_hits=slow_max_hits,
                match={"peer": str(slow_peer)},
            )
        )
    return FaultPlan(specs, seed=seed, name=name)


def reshard_storm_plan(
    peers: Sequence[object],
    seed: int,
    reset_pct: float = 0.25,
    reset_max_hits: int = 0,
    copy_drop_pct: float = 0.5,
    copy_max_hits: int = 0,
    copy_delay_us: int = 0,
    cutover_delay_us: int = 0,
    name: str = "reshard-storm",
) -> FaultPlan:
    """The standing re-sharding chaos shape: ``reset_pct`` of writes
    toward every shard peer reset the connection (the client sees
    flapping links while the migration streams ranges), and
    ``copy_drop_pct`` of the coordinator's per-key copy attempts drop
    (the key stays pending — the retry/rollback machinery must absorb
    it).  ``copy_delay_us`` > 0 additionally stretches the surviving
    copies, widening the kill-mid-COPY window the acceptance test
    aims its shard kill into; ``cutover_delay_us`` > 0 stretches the
    epoch-bump publication so in-flight fan-outs race it."""
    specs = []
    for peer in peers:
        specs.append(
            FaultSpec(
                "socket.write", "reset",
                probability=reset_pct,
                max_hits=reset_max_hits,
                match={"peer": str(peer)},
            )
        )
    specs.append(
        FaultSpec(
            "reshard.copy", "drop",
            probability=copy_drop_pct,
            max_hits=copy_max_hits,
        )
    )
    if copy_delay_us:
        specs.append(
            FaultSpec(
                "reshard.copy", "delay_us",
                arg=int(copy_delay_us),
                probability=1.0,
            )
        )
    if cutover_delay_us:
        specs.append(
            FaultSpec(
                "reshard.cutover", "delay_us",
                arg=int(cutover_delay_us),
                probability=1.0,
            )
        )
    return FaultPlan(specs, seed=seed, name=name)


def replica_storm_plan(
    seed: int,
    group: Optional[str] = None,
    lease_drop_pct: float = 0.0,
    lease_max_hits: int = 0,
    lease_delay_us: int = 0,
    ack_drop_pct: float = 0.0,
    ack_peer: Optional[str] = None,
    ack_max_hits: int = 0,
    slow_peer: Optional[object] = None,
    slow_delay_us: int = 50_000,
    slow_pct: float = 1.0,
    slow_max_hits: int = 0,
    name: str = "replica-storm",
) -> FaultPlan:
    """The replication tier's standing chaos shape.  ``lease_drop_pct``
    of lease grants/renewals are lost (scoped to ``group`` when given —
    that group keeps failing over while others stay stable);
    ``ack_drop_pct`` of follower acks vanish after the apply (scoped to
    ``ack_peer`` — one follower's quorum contribution degrades while
    its data stays intact); ``slow_peer`` stalls every response read
    from one replica on the CLIENT's read plane (socket.read) — the
    degraded-fabric shape the leader-kill acceptance runs under.  Note
    it stalls the reader's event loop, so it is NOT a hedging target:
    the hedged tail-cut bench slows a replica server-side instead
    (bench_replicated_ps)."""
    specs = []
    if lease_drop_pct > 0:
        specs.append(
            FaultSpec(
                "replica.lease", "drop",
                probability=lease_drop_pct,
                max_hits=lease_max_hits,
                match={"method": group} if group else None,
            )
        )
    if lease_delay_us:
        specs.append(
            FaultSpec(
                "replica.lease", "delay_us",
                arg=int(lease_delay_us),
                probability=1.0,
                match={"method": group} if group else None,
            )
        )
    if ack_drop_pct > 0:
        match = {}
        if group:
            match["method"] = group
        if ack_peer:
            match["peer"] = str(ack_peer)
        specs.append(
            FaultSpec(
                "replica.ack", "drop",
                probability=ack_drop_pct,
                max_hits=ack_max_hits,
                match=match or None,
            )
        )
    if slow_peer is not None:
        specs.append(
            FaultSpec(
                "socket.read", "delay_us",
                arg=int(slow_delay_us),
                probability=slow_pct,
                max_hits=slow_max_hits,
                match={"peer": str(slow_peer)},
            )
        )
    if not specs:
        raise ValueError("replica_storm_plan with every knob at zero")
    return FaultPlan(specs, seed=seed, name=name)


def admission_pressure_plan(
    seed: int,
    reject_pct: float = 0.5,
    tier: Optional[str] = None,
    method: Optional[str] = None,
    max_hits: int = 0,
    name: str = "admission-pressure",
) -> FaultPlan:
    """Force ``reject_pct`` of admission decisions to shed
    (EOVERCROWDED), optionally only for one tier and/or method — the
    deterministic knob behind the shed/retry-elsewhere tests."""
    match = {}
    if tier:
        match["tier"] = tier
    if method:
        match["method"] = method
    return FaultPlan(
        [
            FaultSpec(
                "admission.decide", "reject",
                probability=reject_pct,
                max_hits=max_hits,
                match=match or None,
            )
        ],
        seed=seed,
        name=name,
    )
