"""Parameter-server model family — sharded parameters behind RPC.

The north star names "existing echo / parameter-server brpc services
run across a v5e pod with no NIC in the data path". Two halves:

1. **RPC side** (PsService): Get/Put of named parameter shards whose
   payloads ride IOBuf device segments — a fetch over the ICI transport
   hands the client an HBM-resident jax.Array zero-copy.
2. **Device side** (make_training_step): the canonical data-parallel +
   tensor-parallel training step over a ("slice","chip") mesh in the
   scaling-book style: annotate shardings with NamedSharding, jit, and
   let XLA insert the collectives (psum for tp matmul partials and dp
   gradient reduction ride ICI). This is the "flagship model" step the
   multichip dry-run compiles and executes.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

import numpy as _np

from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.server.service import Service, ServiceStub, rpc_method


class PsService(Service):
    """Parameter server: store/fetch tensors by key.

    Uses EchoRequest.message as the key channel and attachments as the
    tensor payload (device segments stay in HBM over ICI transport).
    """

    SERVICE_NAME = "PsService"

    def __init__(self):
        self._store: Dict[str, object] = {}
        self._lock = threading.Lock()

    @rpc_method(EchoRequest, EchoResponse)
    def Put(self, controller, request, response, done):
        key = request.message
        att = controller.request_attachment
        arrays = None
        try:
            arrays = att.device_arrays()
        except ValueError:
            arrays = None
        with self._lock:
            if arrays:
                self._store[key] = arrays[0] if len(arrays) == 1 else arrays
            else:
                self._store[key] = att.to_bytes()
        response.message = key
        done()

    @rpc_method(EchoRequest, EchoResponse)
    def Get(self, controller, request, response, done):
        key = request.message
        with self._lock:
            val = self._store.get(key)
        if val is None:
            from incubator_brpc_tpu import errors

            controller.set_failed(errors.EREQUEST, f"no such key: {key}")
            done()
            return
        if isinstance(val, (bytes, bytearray)):
            controller.response_attachment.append(val)
        elif isinstance(val, list):
            for a in val:
                controller.response_attachment.append_device(a)
        else:
            controller.response_attachment.append_device(val)
        response.message = key
        done()


def ps_stub(channel) -> ServiceStub:
    return ServiceStub(channel, PsService)


# ---- device side: the flagship sharded training step -----------------------


def make_training_step(mesh, dim: int = 256, batch: int = 32, lr: float = 0.01):
    """Build (step_fn, params, batch) jitted over `mesh`.

    Shardings (scaling-book recipe — annotate, let XLA insert
    collectives):
      - W1: P(None, "chip")   tensor-parallel column shard
      - W2: P("chip", None)   tensor-parallel row shard (matmul partial
                               sums -> XLA inserts psum over "chip")
      - batch x: P("slice", None)  data-parallel; grad reduction ->
                               XLA inserts psum over "slice"
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def loss_fn(params, x):
        h = jnp.maximum(x @ params["w1"], 0.0)
        y = h @ params["w2"]
        return jnp.mean(y * y)

    def step(params, x):
        loss, grads = jax.value_and_grad(loss_fn)(params, x)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    w1 = jax.random.normal(k1, (dim, dim), jnp.float32) / (dim ** 0.5)
    w2 = jax.random.normal(k2, (dim, dim), jnp.float32) / (dim ** 0.5)
    x = jax.random.normal(k3, (batch, dim), jnp.float32)

    w1_s = NamedSharding(mesh, P(None, "chip"))
    w2_s = NamedSharding(mesh, P("chip", None))
    x_s = NamedSharding(mesh, P("slice", None))
    params = {
        "w1": jax.device_put(w1, w1_s),
        "w2": jax.device_put(w2, w2_s),
    }
    x = jax.device_put(x, x_s)
    step_jit = jax.jit(
        step,
        in_shardings=({"w1": w1_s, "w2": w2_s}, x_s),
        out_shardings=({"w1": w1_s, "w2": w2_s}, NamedSharding(mesh, P())),
    )
    return step_jit, params, x
