"""Parameter-server model family — sharded parameters behind RPC.

The north star names "existing echo / parameter-server brpc services
run across a v5e pod with no NIC in the data path". Two halves:

1. **RPC side** (PsService): Get/Put of named parameter shards whose
   payloads ride IOBuf device segments — a fetch over the ICI transport
   hands the client an HBM-resident jax.Array zero-copy.
2. **Device side** (make_training_step): the canonical data-parallel +
   tensor-parallel training step over a ("slice","chip") mesh in the
   scaling-book style: annotate shardings with NamedSharding, jit, and
   let XLA insert the collectives (psum for tp matmul partials and dp
   gradient reduction ride ICI). This is the "flagship model" step the
   multichip dry-run compiles and executes.

**Replication (docs/replication.md):** PsService itself is
replication-agnostic — the HA tier wraps it from OUTSIDE through the
PsShardStore adapter (resharding/migration.py) that replication/
ReplicaNode applies quorum writes and repair copies through, and
clients swap ``sharded_ps_channel`` for
``replication.replicated_ps_channel`` (same stub surface: Put/Delete
become quorum writes through the leader, Get hedges across replicas,
Forward fans through per-group leaders).  No forked service, no
server-side protocol change: a PS shard joins a replica group by
being listed in the group's endpoints.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from incubator_brpc_tpu.batching.fused import FusedKernel
from incubator_brpc_tpu.batching.policy import BatchPolicy
from incubator_brpc_tpu.observability.profiling import hbm_account, kernel_section
from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.server.service import (
    Service,
    ServiceStub,
    batched_method,
    rpc_method,
)

# HBM heap profiler hookup (observability/profiling.py): every stored
# device parameter is adopted under this tag, so /hotspots/hbm shows
# how much HBM the parameter store pins.  The handle is resolved at
# import so no store-lock holder ever touches the registry lock.
_PS_ACCT = hbm_account("ps.params")
_NO_CHARGE = (0, 0)


def _hbm_charge(val):
    """Adopt a stored value's device bytes; (bytes, allocs) to remember
    for release at replace/delete.  Host ``bytes`` payloads carry no
    ``.nbytes`` and charge nothing."""
    if isinstance(val, list):
        charges = [_PS_ACCT.adopt(a) for a in val]
        return sum(charges), sum(1 for c in charges if c)
    n = _PS_ACCT.adopt(val)
    return n, (1 if n else 0)


def _hbm_release(charge) -> None:
    nbytes, allocs = charge
    if nbytes:
        _PS_ACCT.release(nbytes, allocs)


def max_servable_dim(per_chip_bytes: int, n_shards: int = 1,
                     dtype_bytes: int = 4) -> int:
    """HBM-ceiling math (docs/sharded_ps.md): the largest square (d, d)
    parameter matrix servable when each chip budgets ``per_chip_bytes``
    for it.  Row-sharding over n chips stores d*d*dtype/n per chip, so
    d_max = floor(sqrt(per_chip_bytes * n / dtype)) — the ceiling grows
    with sqrt(n): 4 shards serve 2x the single-chip d, 16 shards 4x.
    Sharded results round DOWN to a multiple of n_shards (the row dim
    must divide evenly to shard)."""
    d = int((per_chip_bytes * n_shards / dtype_bytes) ** 0.5)
    if n_shards > 1:
        d -= d % n_shards
    return d

# Default coalescing contract of the PS methods (docs/batching.md):
# engages only on servers started with enable_batching=True; everywhere
# else the synthesized single-request adapter keeps the pre-batching
# behavior bit-for-bit.  Buckets cover every batch size ≤ 32, so the
# fused Forward kernel retraces at most 6 times per row shape.
PS_BATCH_POLICY = BatchPolicy(
    max_batch_size=32,
    max_wait_us=1000,
    padding_buckets=(1, 2, 4, 8, 16, 32),
)

# Fused Forward kernel: Y = X @ W, one GEMM per batch.  This is where
# server-side micro-batching actually pays on hardware: N separate
# matvecs each stream the full W from memory (bandwidth-bound), while
# the batched (rows, d) @ W streams W ONCE for the whole batch — the
# weight-reuse economics of inference serving.  FusedKernel shares the
# batching.fused trace counter, so padding buckets bound its retraces
# the same way they bound the stack kernel's.
_FORWARD_KERNEL = FusedKernel(
    lambda w, x: x @ w,
    label="ps.forward",
    batch_buckets=PS_BATCH_POLICY.padding_buckets,
)


class PsService(Service):
    """Parameter server: store/fetch tensors by key.

    Uses EchoRequest.message as the key channel and attachments as the
    tensor payload (device segments stay in HBM over ICI transport).

    All methods are @batched_method — the flagship users of the
    micro-batching subsystem.  Get/Put coalesce dispatch: one handler
    invocation and one store-lock acquisition serve the whole window.
    Forward is the fused device op: N concurrent calls become ONE
    padded (bucket, d) @ W GEMM that streams the parameter matrix once
    for the batch instead of once per request.

    Pod-scale mode (docs/sharded_ps.md): construct with ``mesh=`` and
    the store SHARDS eligible parameters across the mesh — a 2D matrix
    whose row dim divides the "chip" axis is device_put row-sharded, so
    each chip holds d/n rows and the servable parameter size is bounded
    by per-chip HBM times the shard count (``max_servable_dim``).
    Forward on a sharded key lowers the SAME padded batched GEMM
    through shard_map/pjit (batching/sharded.ShardedFusedKernel): one
    fused sharded execution, cross-shard partials merged by ONE psum
    collective per batch.  ``mesh=None`` (the default) is byte-for-byte
    the single-chip service — the sharded branch costs one attribute
    check per batch group (the bench's overhead triplet pins ≈0%).
    """

    SERVICE_NAME = "PsService"

    def __init__(self, mesh=None, shard_axis: str = "chip"):
        self._store: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._sharded_keys: set = set()
        # per-key (bytes, allocs) HBM charge, mutated under self._lock
        self._hbm: Dict[str, tuple] = {}
        self._shard_kernel = None
        if mesh is not None and int(mesh.shape.get(shard_axis, 1)) > 1:
            from incubator_brpc_tpu.batching.sharded import ShardedFusedKernel

            self._shard_kernel = ShardedFusedKernel(
                mesh, shard_axis, label=f"{self.SERVICE_NAME}.Forward"
            )

    @property
    def shard_kernel(self):
        """The sharded batch kernel (None on single-chip services) —
        its ``executions`` / ``collective_merges`` step log is how
        tests and the bench-smoke guard prove the fused lowering."""
        return self._shard_kernel

    def put_param(self, key: str, value) -> bool:
        """Server-side store API (the bench and ops tooling seed
        through this; the Put RPC routes here too).  Returns True when
        the value was sharded across the mesh."""
        sharded = False
        if self._shard_kernel is not None:
            try:
                value = self._shard_kernel.shard_param(value)
                sharded = True
            except (ValueError, AttributeError):
                pass  # ineligible shape: single-chip storage as-is
        charge = _hbm_charge(value)  # metadata-only: fine outside the lock
        with self._lock:
            _hbm_release(self._hbm.pop(key, _NO_CHARGE))
            self._store[key] = value
            if charge[0]:
                self._hbm[key] = charge
            if sharded:
                self._sharded_keys.add(key)
            else:
                self._sharded_keys.discard(key)
        return sharded

    @batched_method(EchoRequest, EchoResponse, policy=PS_BATCH_POLICY)
    def Put(self, controllers, requests, responses, done):
        rows = []
        for controller, request, response in zip(controllers, requests, responses):
            att = controller.request_attachment
            try:
                arrays = att.device_arrays()
            except ValueError:
                arrays = None
            if arrays:
                val = arrays[0] if len(arrays) == 1 else arrays
            else:
                val = att.to_bytes()
            sharded = False
            if self._shard_kernel is not None:
                # placement (a device_put) runs OUTSIDE the store lock;
                # only the dict writes below hold it
                try:
                    val = self._shard_kernel.shard_param(val)
                    sharded = True
                except (ValueError, AttributeError):
                    pass  # ineligible: single-chip storage as-is
            rows.append((request.message, val, sharded, _hbm_charge(val)))
            response.message = request.message
        with self._lock:  # one acquisition serves the whole window
            for key, val, sharded, charge in rows:
                _hbm_release(self._hbm.pop(key, _NO_CHARGE))
                self._store[key] = val
                if charge[0]:
                    self._hbm[key] = charge
                if sharded:
                    self._sharded_keys.add(key)
                else:
                    self._sharded_keys.discard(key)
        done()

    @batched_method(EchoRequest, EchoResponse, policy=PS_BATCH_POLICY)
    def Get(self, controllers, requests, responses, done):
        # Get has no device compute to fuse — the stored jax.Array
        # attaches to the response as-is (zero device ops; stacking
        # value-identical copies would only add HBM traffic).  Batching
        # still pays off the per-request overheads: one handler
        # invocation, one store-lock acquisition, one dispatch per
        # window instead of N.  Forward below is the fused-compute
        # flagship.
        from incubator_brpc_tpu import errors

        with self._lock:
            vals = [self._store.get(r.message) for r in requests]
        for val, controller, request, response in zip(
            vals, controllers, requests, responses
        ):
            if val is None:
                controller.set_failed(
                    errors.EREQUEST, f"no such key: {request.message}"
                )
                continue
            if isinstance(val, (bytes, bytearray)):
                controller.response_attachment.append(val)
            elif isinstance(val, list):
                for a in val:
                    controller.response_attachment.append_device(a)
            else:
                controller.response_attachment.append_device(val)
            response.message = request.message
        done()


    @rpc_method(EchoRequest, EchoResponse)
    def Keys(self, controller, request, response, done):
        """Enumerate this shard's live keys (newline-joined, sorted, in
        the response attachment) — the re-sharding coordinator's
        PREPARE phase reads every shard's key census through this.
        Control-plane rate: plain (unbatched) by design."""
        with self._lock:
            keys = sorted(self._store)
        controller.response_attachment.append(
            "\n".join(keys).encode("utf-8")
        )
        response.message = str(len(keys))
        done()

    @rpc_method(EchoRequest, EchoResponse)
    def Delete(self, controller, request, response, done):
        """Remove a key (idempotent — a retried DRAIN must not fail on
        an already-deleted key).  response.message is "1" when the key
        was live, "0" when it was already gone: the coordinator's
        drained-key step log sums these."""
        with self._lock:
            existed = request.message in self._store
            self._store.pop(request.message, None)
            self._sharded_keys.discard(request.message)
            _hbm_release(self._hbm.pop(request.message, _NO_CHARGE))
        response.message = "1" if existed else "0"
        done()

    def remesh(self, mesh, shard_axis: str = "chip") -> int:
        """Re-mesh the sharded store live (the server-side half of a
        scheme migration): rebuild the sharded batch kernel over the
        new mesh and re-place every currently-sharded parameter under
        the new sharding (batching/sharded.ShardedFusedKernel.remesh).
        Returns the number of parameters re-placed.  ``mesh=None``
        drops to single-chip mode."""
        if mesh is None or int(mesh.shape.get(shard_axis, 1)) <= 1:
            with self._lock:
                self._shard_kernel = None
                self._sharded_keys.clear()
            return 0
        from incubator_brpc_tpu.batching.sharded import ShardedFusedKernel

        if self._shard_kernel is not None:
            self._shard_kernel.remesh(mesh, shard_axis)
            kernel = self._shard_kernel
        else:
            kernel = ShardedFusedKernel(
                mesh, shard_axis, label=f"{self.SERVICE_NAME}.Forward"
            )
        with self._lock:
            sharded = {k: self._store[k] for k in self._sharded_keys}
        replaced = {}
        still_sharded = set()
        for key, val in sharded.items():
            # placement (device_puts) runs outside the store lock
            try:
                replaced[key] = kernel.shard_param(val)
                still_sharded.add(key)
            except (ValueError, AttributeError):
                replaced[key] = val  # no longer shardable on new mesh
        with self._lock:
            self._shard_kernel = kernel
            for key, val in replaced.items():
                if key in self._store:  # deleted while re-placing: skip
                    _hbm_release(self._hbm.pop(key, _NO_CHARGE))
                    self._store[key] = val
                    charge = _hbm_charge(val)
                    if charge[0]:
                        self._hbm[key] = charge
                    if key not in still_sharded:
                        self._sharded_keys.discard(key)
        return len(still_sharded)

    @batched_method(EchoRequest, EchoResponse, policy=PS_BATCH_POLICY)
    def Forward(self, controllers, requests, responses, done):
        """Apply a stored parameter matrix to a caller-supplied input:
        ``y = x @ W`` where ``W`` is the (d, d) tensor stored under
        ``request.message`` and ``x`` rides the request attachment as
        d float32s.  The response attachment carries ``y`` (d float32s).

        The flagship fused device op: a batch of N concurrent Forwards
        becomes ONE padded (bucket, d) @ W GEMM — one host-to-device
        transfer of the stacked inputs, one kernel that streams W once
        instead of N times, one device-to-host pull of all outputs.
        Per-row validation failures (unknown key, wrong input size) fail
        only that row's controller; batch-mates still execute.
        """
        import numpy as np

        from incubator_brpc_tpu import errors
        from incubator_brpc_tpu.analysis.device_witness import allowed_transfer
        from incubator_brpc_tpu.batching.batcher import current_batch
        from incubator_brpc_tpu.observability.span import current_span

        with self._lock:
            params = {r.message: self._store.get(r.message) for r in requests}
            sharded = {k for k in params if k in self._sharded_keys}
        # per-row parse + validate, grouped by parameter key so mixed
        # batches still fuse per key
        groups: Dict[str, list] = {}
        for i, (controller, request) in enumerate(zip(controllers, requests)):
            w = params.get(request.message)
            if w is None or len(getattr(w, "shape", ())) != 2:
                controller.set_failed(
                    errors.EREQUEST,
                    f"no parameter matrix under key: {request.message!r}",
                )
                continue
            d = int(w.shape[0])
            raw = controller.request_attachment.to_bytes()
            if len(raw) != d * 4:
                controller.set_failed(
                    errors.EREQUEST,
                    f"Forward input must be {d} float32s ({d * 4} bytes), "
                    f"got {len(raw)}",
                )
                continue
            groups.setdefault(request.message, []).append(
                (i, np.frombuffer(raw, np.float32))
            )
        ctx = current_batch()
        for key, rows in groups.items():
            w = params[key]
            n = len(rows)
            # bucket even without a batching context: direct multi-row
            # calls would otherwise specialize the kernel per exact n,
            # voiding the retrace bound the buckets exist to enforce
            policy = ctx.policy if ctx is not None else PS_BATCH_POLICY
            pad_to = policy.bucket_for(n)
            # stack on host (zero-padded to the bucket), ship once
            X = np.zeros((max(pad_to, n), int(w.shape[0])), np.float32)
            for j, (_, x) in enumerate(rows):
                X[j] = x
            # sharded keys lower through the mesh kernel (one fused
            # sharded execution + one psum merge); everything else
            # rides the single-chip kernel unchanged
            kernel = (
                self._shard_kernel
                if key in sharded and self._shard_kernel is not None
                else _FORWARD_KERNEL
            )
            try:
                # device window: dispatch → the manifested pull below is
                # the sanctioned completion point, so the section (and
                # the span's device phase) times real device work
                # without adding any sync
                span = current_span()
                if span is not None:
                    span.stamp("device_start_us")
                with kernel_section("ps.forward"):
                    out = kernel(w, X)
                    # pull ONLY the n live rows: the pad rows never cross
                    # the device boundary (slice happens device-side)
                    with allowed_transfer("ps.forward-pull"):
                        Y = np.asarray(out[:n] if pad_to > n else out)
                if span is not None:
                    span.stamp("device_done_us")
            except Exception as e:  # noqa: BLE001 — a failed merge
                # (chaos collective.merge reset, or a real dispatch
                # error) fails ONLY this key-group's rows; other
                # groups in the batch still execute
                for i, _ in rows:
                    controllers[i].set_failed(
                        errors.EINTERNAL,
                        f"sharded forward failed for {key!r}: {e}",
                    )
                continue
            for j, (i, _) in enumerate(rows):
                # zero-copy attach: the row view keeps Y alive
                controllers[i].response_attachment.append_user_data(Y[j])
                responses[i].message = key
        done()


def ps_stub(channel) -> ServiceStub:
    return ServiceStub(channel, PsService)


# ---- client side: shard-routed deployment helpers --------------------------
#
# The shard-PER-SERVER deployment (docs/sharded_ps.md): N PsService
# servers each own rows [k*d/N, (k+1)*d/N) of every partitioned
# parameter (plus the keyspace slice the consistent hash assigns them).
# Get/Put route to the owning shard only; Forward fans out once —
# each shard contracts the matching slice of x against its local rows
# and returns a PARTIAL y, merged client-side by one fused sum
# (ops/merge.merge_partial_sum).


def ps_forward_prepare_leg(i, n, request, parent_ctrl, sub_ctrl):
    """Slice the caller's x by shard rows: leg i carries bytes
    [i*d/n*4, (i+1)*d/n*4) of the request attachment."""
    raw = parent_ctrl.request_attachment.to_bytes()
    if len(raw) % (4 * n):
        raise ValueError(
            f"Forward input of {len(raw)} bytes does not split into "
            f"{n} float32 row shards"
        )
    chunk = len(raw) // n
    sub_ctrl.request_attachment.append_user_data(raw[i * chunk:(i + 1) * chunk])
    return request


def ps_forward_merge(parent_ctrl, parent_resp, sub_ctrls, sub_resps):
    """Sum the per-shard partial y vectors (one fused device op); a
    failed leg inside fail_limit simply contributes nothing — the
    degraded combo-channel contract."""
    import numpy as np

    from incubator_brpc_tpu.analysis.device_witness import allowed_transfer
    from incubator_brpc_tpu.ops.merge import merge_partial_sum

    parts = []
    key = ""
    for sc, sr in zip(sub_ctrls, sub_resps):
        if sc is None or sc.failed():
            continue
        parts.append(
            np.frombuffer(sc.response_attachment.to_bytes(), np.float32)
        )
        key = key or sr.message
    if not parts:
        raise ValueError("no successful shard legs to merge")
    with allowed_transfer("ps.client-merge"):
        y = np.asarray(merge_partial_sum(parts))
    parent_ctrl.response_attachment.append_user_data(y.tobytes())
    parent_resp.message = key


def sharded_ps_channel(sub_channels=None, endpoints=None, fail_limit=0,
                       timeout_ms=20000, seed=0, channel_options=None):
    """A ShardRoutedChannel wired for PsService: keyed Get/Put routing
    plus the Forward fan-out contract above.  Pass explicit
    ``sub_channels`` or ``endpoints`` (e.g. ``ici_endpoints(mesh)``)."""
    from incubator_brpc_tpu.client.combo import (
        ParallelChannelOptions,
        ShardRoutedChannel,
    )

    opts = ParallelChannelOptions(fail_limit=fail_limit, timeout_ms=timeout_ms)
    if endpoints is not None:
        ch = ShardRoutedChannel.from_endpoints(
            endpoints, options=opts, channel_options=channel_options,
            seed=seed,
        )
    else:
        ch = ShardRoutedChannel(options=opts, seed=seed)
        ch.set_partitions(list(sub_channels or []))
    ch.set_fanout("Forward", ps_forward_prepare_leg, ps_forward_merge)
    return ch


def scatter_param(shard_channel, key: str, w) -> None:
    """Row-scatter a parameter across the shard servers: shard k gets
    rows [k*d/n, (k+1)*d/n) as a device payload under the same key
    (PR 5's per-row scatter, applied to parameter placement).  After
    this, a fan-out Forward against `key` serves the full matrix."""
    import jax.numpy as jnp

    from incubator_brpc_tpu.client.controller import Controller

    parts = shard_channel.partitions()
    n = len(parts)
    d = int(w.shape[0])
    if n == 0 or d % n:
        raise ValueError(f"{d} rows do not scatter over {n} shards")
    rows = d // n
    for i, part in enumerate(parts):
        stub = ps_stub(part)
        c = Controller()
        c.request_attachment.append_device(
            jnp.asarray(w[i * rows:(i + 1) * rows])
        )
        stub.Put(c, EchoRequest(message=key))
        if c.failed():
            raise RuntimeError(
                f"scatter_param: shard {i} Put failed: {c.error_text()}"
            )


# ---- device side: the flagship sharded training step -----------------------


def make_training_step(mesh, dim: int = 256, batch: int = 32, lr: float = 0.01):
    """Build (step_fn, params, batch) jitted over `mesh`.

    Shardings (scaling-book recipe — annotate, let XLA insert
    collectives):
      - W1: P(None, "chip")   tensor-parallel column shard
      - W2: P("chip", None)   tensor-parallel row shard (matmul partial
                               sums -> XLA inserts psum over "chip")
      - batch x: P("slice", None)  data-parallel; grad reduction ->
                               XLA inserts psum over "slice"
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def loss_fn(params, x):
        h = jnp.maximum(x @ params["w1"], 0.0)
        y = h @ params["w2"]
        return jnp.mean(y * y)

    def step(params, x):
        loss, grads = jax.value_and_grad(loss_fn)(params, x)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    w1 = jax.random.normal(k1, (dim, dim), jnp.float32) / (dim ** 0.5)
    w2 = jax.random.normal(k2, (dim, dim), jnp.float32) / (dim ** 0.5)
    x = jax.random.normal(k3, (batch, dim), jnp.float32)

    w1_s = NamedSharding(mesh, P(None, "chip"))
    w2_s = NamedSharding(mesh, P("chip", None))
    x_s = NamedSharding(mesh, P("slice", None))
    params = {
        "w1": jax.device_put(w1, w1_s),
        "w2": jax.device_put(w2, w2_s),
    }
    x = jax.device_put(x, x_s)
    step_jit = jax.jit(
        step,
        in_shardings=({"w1": w1_s, "w2": w2_s}, x_s),
        out_shardings=({"w1": w1_s, "w2": w2_s}, NamedSharding(mesh, P())),
    )
    return step_jit, params, x
