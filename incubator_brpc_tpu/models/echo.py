"""EchoService — the canonical test/benchmark service.

Analog of reference example/echo_c++/server.cpp plus the
behavior-controlled fault-injection service the test suite uses
(test/brpc_channel_unittest.cpp:134-162): the request can ask the
server to fail, close the connection, or sleep before answering.
"""

from __future__ import annotations

import time

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.server.service import Service, ServiceStub, rpc_method


class EchoService(Service):
    """Echoes request.message; honors fault-injection fields."""

    def __init__(self, attach_echo: bool = True):
        self._attach_echo = attach_echo

    def native_fastpaths(self):
        """Echo answers entirely inside the C++ engine when the server
        runs with native_engine=True; the engine falls back to the
        Python handler above whenever a fault-injection field is set."""
        return {"Echo": ("echo", self._attach_echo)}

    def native_http_fastpaths(self):
        """Raw-body HTTP echo served entirely in C on native-engine
        servers (response body = request body — the reference
        http_server example's handler shape).  The pb/JSON semantic
        route at /EchoService/Echo stays on the Python stack."""
        return ["/EchoService/Echo.raw"]

    @rpc_method(EchoRequest, EchoResponse)
    def Echo(self, controller, request, response, done):
        if request.server_fail:
            controller.set_failed(request.server_fail, "injected failure")
            done()
            return
        if request.close_fd:
            controller.close_connection()
            done()
            return
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        response.message = request.message
        response.code = request.code
        # echo the attachment back (reference echo example does this)
        if self._attach_echo and len(controller.request_attachment):
            controller.response_attachment.append(controller.request_attachment)
        done()


def echo_stub(channel) -> ServiceStub:
    return ServiceStub(channel, EchoService)
