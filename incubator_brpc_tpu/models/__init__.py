"""Example service families (the framework's "models"): echo,
streaming echo, parameter server — analogs of reference example/*."""
