"""Streaming echo — bidirectional stream service.

Analog of reference example/streaming_echo_c++: the client creates a
stream on the Echo RPC; the server accepts and echoes every received
chunk back on the same stream.
"""

from __future__ import annotations

from incubator_brpc_tpu.client.stream import Stream, StreamHandler, StreamOptions
from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.server.service import Service, rpc_method


class _EchoBack(StreamHandler):
    def on_received_messages(self, stream, messages):
        for m in messages:
            stream.write(m)


class StreamingEchoService(Service):
    SERVICE_NAME = "StreamingEchoService"

    @rpc_method(EchoRequest, EchoResponse)
    def StartStream(self, controller, request, response, done):
        if controller._remote_stream_settings is None:
            from incubator_brpc_tpu import errors

            controller.set_failed(errors.EREQUEST, "no stream in request")
            done()
            return
        Stream.accept(controller, _EchoBack())
        response.message = "stream-accepted"
        done()
