"""The TPU data plane (SURVEY.md §2.7 "to build" row): ICI fabric
transport with HBM-resident payloads, mesh management, and the
collective lowerings that fan-out/partition/streaming channels use."""

from incubator_brpc_tpu.parallel.mesh import (  # noqa: F401
    create_mesh,
    default_mesh,
    ici_endpoints,
)
from incubator_brpc_tpu.parallel.ici import (  # noqa: F401
    IciFabric,
    get_fabric,
)
