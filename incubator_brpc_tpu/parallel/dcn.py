"""DCN bridge — the cross-process/cross-host leg of the ICI fabric.

Analog of the reference RDMA endpoint's TCP-assisted bootstrap
(rdma/rdma_endpoint.h:93-108 handshake state machine, rdma_helper
global init): a TCP side channel carries the fabric hello and every
fabric frame between processes.

Bulk path (v2, the RDMA endpoint's windowed send queue analog,
rdma_endpoint.h:83-137):
- device→host staging of ALL device segments starts up front
  (``copy_to_host_async`` fires every D2H DMA before the first wire
  byte moves);
- a stager thread slices segment bytes into ~2MB wire chunks and feeds
  them through a BOUNDED queue (the send window, default 8 chunks =
  16MB) to the socket writer — staging of segment k+1 overlaps the
  kernel send of segment k;
- the receiver streams each segment off the socket and hands completed
  device segments to an upload worker, so host→device re-placement of
  segment k overlaps the read of segment k+1.  (Within a SINGLE device
  segment the upload still waits for its full bytes: per-chunk device
  uploads would pay one tunnel round trip per chunk on remote-TPU
  deployments, which measures far worse than one bulk upload.)

The wire format is unchanged from v1 — chunking is purely a local
pipelining strategy, so mixed-version bridges interoperate.

Topology flow:
- server process: ``listen_dcn(port)`` — accepts bridge connections.
- client process: ``connect_dcn(host, port)`` — handshake learns the
  remote fabric's server coords; the local fabric records them as
  remote routes, so ``tpu://`` naming resolves them and
  ``IciFabric.send`` ships frames over the bridge transparently.
- reverse path: a frame's src coords are learned as a route back
  through the connection it arrived on (client ports are created
  lazily, so they cannot be advertised in the hello).

Wire format (all big-endian):
- hello:      b"ICI1" u32(len) json{role, server_coords:[[s,c]..]}
- hello-ack:  same shape from the acceptor
- frame:      b"ICIF" u32(len) json{src, dst, segs:[{k,"n",dtype?,shape?}..]}
              followed by the segments' raw bytes in order
  seg kind "b" = host bytes; "d" = a whole device array (dtype/shape
  re-materialize it on the receiving side).
"""

from __future__ import annotations

import json
import queue as _queue
import select as _select
import socket as _pysocket
import ssl as _ssl
import struct
import threading
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.observability.span import Span
from incubator_brpc_tpu.utils.segmentation import (
    WIRE_CHUNK_BYTES,
    chunk_buffer,
    chunk_views,
)
from incubator_brpc_tpu.utils.iobuf import DeviceRef, IOBuf
from incubator_brpc_tpu.utils.logging import log_error, log_info

_HELLO_MAGIC = b"ICI1"
_FRAME_MAGIC = b"ICIF"
_MAX_HEADER = 16 << 20
# ~4MB wire chunks (RDMA endpoint frame granularity) — the SHARED
# segmentation policy (utils/segmentation.py), same planner the ICI
# chunked transmit and the kernel-socket write loop use
_WIRE_CHUNK = WIRE_CHUNK_BYTES
_SEND_WINDOW = 8  # staged-but-unsent chunks allowed in flight (32MB)


def _coords_to_wire(coords) -> list:
    return list(coords)


def _coords_from_wire(raw, server: bool = False) -> Optional[Tuple]:
    """Validate peer-supplied coords. Port keys are 2-tuples: servers
    are (slice:int, chip:int); client ports are ("client", "pid-seq").
    Anything else is dropped — a malformed peer must not crash the
    naming service or fabric that later consumes these."""
    try:
        if len(raw) != 2:
            return None
        s, c = raw
    except TypeError:
        return None
    ok_types = (int,) if server else (int, str)
    if isinstance(s, bool) or isinstance(c, bool):
        return None
    if not isinstance(s, ok_types) or not isinstance(c, ok_types):
        return None
    return (s, c)


def _plan_frame(frame: IOBuf, src, dst):
    """Plan the wire encoding of an IOBuf: returns (header_bytes,
    producers, total_payload_bytes) where each producer() yields the
    corresponding segment's payload as memoryview chunks of
    ≤ _WIRE_CHUNK bytes.

    Every whole-array device segment's D2H DMA is kicked off HERE via
    ``copy_to_host_async`` — all device transfers run concurrently with
    each other and with the socket writes of earlier segments."""
    segs = []
    producers = []
    pending_host: List[memoryview] = []  # views into `frame` (alive
    # for the whole send): staging copies nothing

    # chunking comes from the shared segmentation policy
    # (utils/segmentation.py): chunk_buffer for contiguous staging
    # buffers, chunk_views for ref lists
    def flush_host():
        if pending_host:
            views = list(pending_host)
            segs.append({"k": "b", "n": sum(len(v) for v in views)})
            producers.append(
                lambda views=views: chunk_views(views, _WIRE_CHUNK)
            )
            pending_host.clear()

    for ref in frame._refs:
        if isinstance(ref, DeviceRef):
            arr = ref.whole_array()
            if arr is not None:
                flush_host()
                import numpy as np

                if hasattr(arr, "copy_to_host_async"):
                    try:
                        arr.copy_to_host_async()  # start the DMA now
                    except Exception:  # noqa: BLE001 — fetch still works
                        pass
                dtype = np.dtype(arr.dtype)
                shape = tuple(arr.shape)
                nbytes = int(dtype.itemsize)
                for d in shape:
                    nbytes *= int(d)
                segs.append(
                    {
                        "k": "d",
                        "n": nbytes,
                        "dtype": str(dtype),
                        "shape": list(shape),
                    }
                )

                def produce(arr=arr):
                    import numpy as np

                    from incubator_brpc_tpu.analysis.device_witness import (
                        allowed_transfer,
                    )

                    # the DCN bridge IS the device/host boundary: the
                    # segment must become contiguous host bytes to hit
                    # the socket (manifested as dcn.wire)
                    with allowed_transfer("dcn.wire"):
                        host = np.ascontiguousarray(np.asarray(arr))
                    return chunk_buffer(
                        host.view(np.uint8).reshape(-1), _WIRE_CHUNK
                    )

                producers.append(produce)
                continue
            # split device segment: ship its byte window as host bytes
        pending_host.append(ref.view())  # already a memoryview
    flush_host()
    header = json.dumps(
        {"src": _coords_to_wire(src), "dst": _coords_to_wire(dst), "segs": segs}
    ).encode()
    return header, producers, sum(s["n"] for s in segs)


_warmed = False
_warm_lock = threading.Lock()


def _warm_bulk_path():
    """One-time per-process warmup of everything a first bulk frame
    would otherwise pay inline (the measured 0.403s first-64MB-echo
    straggler, BENCH_r05 dcn_64mb_echo_s_all):

    - pre-touch a wire-chunk-sized receive buffer so the allocator
      arenas the first ``recv_into`` faults into are already mapped;
    - run one tiny host→device upload, because the first
      ``jnp.asarray`` in a fresh process pays the whole jax platform
      init — by far the biggest share of the straggler — inside the
      reader's upload worker.

    Runs on a daemon thread off listen()/connect(); jax-free processes
    simply skip the upload half."""
    global _warmed
    with _warm_lock:
        if _warmed:
            return
        _warmed = True
    try:
        import numpy as np

        buf = np.empty(_WIRE_CHUNK, dtype=np.uint8)
        buf[::4096] = 0  # fault every page in
        del buf
    except ImportError:
        bytearray(_WIRE_CHUNK)  # zeroing touches every page
    try:
        import jax.numpy as jnp
        import numpy as np

        jnp.asarray(np.ones(8, dtype=np.float32)).block_until_ready()
    except Exception:  # noqa: BLE001 — no jax here: uploads keep bytes
        pass


def _spawn_warmup():
    if not _warmed:
        threading.Thread(
            target=_warm_bulk_path, daemon=True, name="dcn-warmup"
        ).start()


def _recv_exact(conn, n: int) -> Optional[bytes]:
    out = bytearray()
    while len(out) < n:
        chunk = conn.recv(min(1 << 20, n - len(out)))
        if not chunk:
            return None
        out += chunk
    return bytes(out)


def _read_header(conn) -> Optional[Tuple[bytes, dict]]:
    """Read one message's magic + JSON header (shared by the handshake
    reader and the streaming frame loop). → (magic, header) or None on
    EOF/garbage."""
    head = _recv_exact(conn, 8)
    if head is None:
        return None
    magic, hlen = head[:4], struct.unpack(">I", head[4:])[0]
    if magic not in (_HELLO_MAGIC, _FRAME_MAGIC) or hlen > _MAX_HEADER:
        return None
    raw = _recv_exact(conn, hlen)
    if raw is None:
        return None
    try:
        header = json.loads(raw)
    except ValueError:
        return None
    return magic, header


def _read_message(conn) -> Optional[Tuple[bytes, dict, bytes]]:
    """→ (magic, header_json, body) or None on EOF/garbage.  Handshake
    use only — frame bodies are drained whole here, not streamed."""
    msg = _read_header(conn)
    if msg is None:
        return None
    magic, header = msg
    body = b""
    if magic == _FRAME_MAGIC:
        total = sum(s["n"] for s in header.get("segs", ()))
        body = _recv_exact(conn, total)
        if body is None:
            return None
    return magic, header, body


class _LockedTlsSocket:
    """Serializes all I/O on one TLS bridge connection.

    OpenSSL's ``SSL*`` is not thread-safe for simultaneous
    SSL_read/SSL_write and CPython's ``_ssl`` adds no per-object lock,
    yet the bridge reads (reader_loop) and writes (send_frame) from
    different threads on the same connection.  Every SSL call holds one
    lock.  Reads do a non-blocking probe under the lock and then park
    in select() OUTSIDE it, so an idle reader costs no SSL/lock churn
    and never starves the writer.  Writes go out in bounded chunks with
    a per-chunk timeout, so a wedged peer fails the send (send_frame
    then closes the bridge) instead of holding the lock forever.
    Plaintext connections bypass this class entirely (kernel sockets
    are full-duplex safe).
    """

    _CHUNK = 64 << 10
    _SEND_TIMEOUT_S = 20.0  # floor rate ~3 KB/s before we declare wedged
    _PARK_S = 0.5

    def __init__(self, sock: _ssl.SSLSocket):
        self._sock = sock
        self._lock = threading.Lock()

    def sendall(self, data) -> None:
        mv = memoryview(data)
        if not len(mv):
            return
        for off in range(0, len(mv), self._CHUNK):
            with self._lock:
                self._sock.settimeout(self._SEND_TIMEOUT_S)
                self._sock.sendall(mv[off : off + self._CHUNK])

    def _recv_op(self, op):
        while True:
            with self._lock:
                self._sock.settimeout(0)  # instant probe: never parks
                try:
                    return op()
                except (
                    _ssl.SSLWantReadError,
                    _ssl.SSLWantWriteError,  # renegotiation mid-read
                    BlockingIOError,
                ):
                    pass
            # park OUTSIDE the lock: select on the fd is safe alongside
            # a concurrent SSL_write, unlike a blocking SSL_read
            _select.select([self._sock], [], [], self._PARK_S)

    def recv(self, n: int) -> bytes:
        return self._recv_op(lambda: self._sock.recv(n))

    def recv_into(self, view, nbytes: int = 0) -> int:
        return self._recv_op(lambda: self._sock.recv_into(view, nbytes))

    def settimeout(self, t) -> None:  # timeouts are managed per-call
        pass

    def close(self) -> None:
        self._sock.close()


class _BridgeConn:
    """One established bridge connection (either direction)."""

    def __init__(self, bridge: "DcnBridge", conn: _pysocket.socket, peer: str):
        if isinstance(conn, _ssl.SSLSocket):
            conn = _LockedTlsSocket(conn)
        else:
            # deep kernel buffers: bulk frames move in multi-MB chunks,
            # and the default ~208KB socket buffers force one syscall
            # per ~200KB on the receive side (best-effort; the kernel
            # clamps to its rmem/wmem limits)
            try:
                conn.setsockopt(
                    _pysocket.SOL_SOCKET, _pysocket.SO_SNDBUF, 8 << 20
                )
                conn.setsockopt(
                    _pysocket.SOL_SOCKET, _pysocket.SO_RCVBUF, 8 << 20
                )
            except OSError:
                pass
        self.bridge = bridge
        self.conn = conn
        self.peer = peer
        self._send_lock = threading.Lock()
        self.closed = False
        self.primed_seen = False  # peer's priming frame arrived
        # chaos "reorder": one held-back frame swapped with its successor
        self._chaos_stash = None
        self._chaos_stash_gen = 0  # ties each backstop timer to ITS stash
        self._chaos_stash_lock = threading.Lock()

    def send_prime(self) -> None:
        """Priming exchange, half of the straggler fix: a zero-segment
        frame sent right after the handshake exercises the peer's whole
        receive path (magic/header read, JSON parse, reader-loop warm)
        before the first real bulk frame, and its arrival proves the
        link full-duplex.  The receiver skips it via the ``prime``
        header key; peers that predate the key would try to route it
        and log one dropped-frame line — wire framing stays intact
        either way."""
        header = json.dumps(
            {"prime": 1, "src": [-1, -1], "dst": [-1, -1], "segs": []}
        ).encode()
        try:
            with self._send_lock:
                self.conn.sendall(
                    _FRAME_MAGIC + struct.pack(">I", len(header)) + header
                )
        except OSError:
            pass  # the reader loop will notice a genuinely dead conn

    def send_frame(self, frame: IOBuf, dst, src) -> int:
        from incubator_brpc_tpu import errors

        if _chaos.armed:
            spec = _chaos.check("dcn.send", peer=self.peer)
            if spec is not None:
                act = spec.action
                if act == "drop":
                    return 0  # frame vanishes on the wide-area hop
                if act == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif act == "reset":
                    # bridge disconnect mid-traffic: the reader loop
                    # sees EOF and the routing table drops this conn
                    self.close()
                    return errors.EFAILEDSOCKET
                elif act == "reorder":
                    with self._chaos_stash_lock:
                        if self._chaos_stash is None:
                            # hold this frame; it ships AFTER the next
                            # frame on this conn (frame reordering on
                            # the DCN path, deterministic swap).  A
                            # timer backstop flushes it if no successor
                            # ever comes — "reorder" must never degrade
                            # into a silent permanent drop
                            self._chaos_stash = (frame, dst, src)
                            self._chaos_stash_gen += 1
                            gen = self._chaos_stash_gen
                            from incubator_brpc_tpu.runtime.timer_thread import (
                                get_timer_thread,
                            )

                            get_timer_thread().schedule(
                                self._chaos_flush_stash, 0.2, gen
                            )
                            return 0
        stashed = None
        if self._chaos_stash is not None:
            with self._chaos_stash_lock:
                stashed, self._chaos_stash = self._chaos_stash, None
        rc = self._send_frame_now(frame, dst, src)
        if stashed is not None:
            self._send_stashed(*stashed)
        return rc

    def _send_stashed(self, frame, dst, src):
        """Ship a reorder-held frame; a failure here has no caller to
        return to, so it must at least be LOUD (the hold-back comment
        promises reorder never degrades into a silent drop)."""
        rc = self._send_frame_now(frame, dst, src)
        if rc:
            log_error(
                "dcn chaos reorder: held frame for %s lost on re-send "
                "(rc=%s)", dst, rc,
            )

    def _chaos_flush_stash(self, gen):
        """Timer backstop: ship a reorder-held frame that never got a
        successor to swap with (runs spawned off the timer thread —
        send_frame can block on the socket).  The generation check
        drops a stale timer whose stash was already swapped out —
        without it, the timer of stash A would flush a LATER stash C
        early, turning a deterministic swap into a timing-dependent
        plain delay."""
        with self._chaos_stash_lock:
            if gen != self._chaos_stash_gen:
                return
            stashed, self._chaos_stash = self._chaos_stash, None
        if stashed is not None and not self.closed:
            from incubator_brpc_tpu.runtime import scheduler

            scheduler.spawn(self._send_stashed, *stashed)

    def _send_frame_now(self, frame: IOBuf, dst, src) -> int:
        from incubator_brpc_tpu import errors

        # rpcz collective sub-span: the cross-host leg of this frame
        # (parented to the active RPC span; None outside a traced RPC)
        leg = Span.create_collective("dcn", f"{src}->{dst} via {self.peer}")
        if leg is not None:
            leg.request_size = len(frame)
            leg.remote_side = self.peer

        def _done(rc: int) -> int:
            if leg is not None:
                leg.end(rc)
            return rc

        # Planning failures are LOCAL — no wire byte moved, the bridge
        # stays healthy and only this frame fails.
        try:
            header, producers, total = _plan_frame(frame, src, dst)
        except Exception as e:  # noqa: BLE001
            log_error("dcn frame to %s unserializable: %r", self.peer, e)
            return _done(errors.EREQUEST)
        if total > (2 << 30):
            # mirror of the receiver's cap: failing here keeps the
            # bridge alive; streaming it would kill the peer's reader
            log_error("dcn frame to %s too large: %d bytes", self.peer, total)
            return _done(errors.EREQUEST)
        # Once the header is on the wire the stream is committed: ANY
        # failure (socket or stager) desyncs the framing → close.
        try:
            with self._send_lock:
                self.conn.sendall(
                    _FRAME_MAGIC + struct.pack(">I", len(header)) + header
                )
                if producers:
                    self._stream_payloads(producers, leg)
            return _done(0)
        except Exception as e:  # noqa: BLE001 — stager errors included
            log_error("dcn send to %s failed: %r", self.peer, e)
            self.close()
            return _done(errors.EFAILEDSOCKET)

    def _stream_payloads(self, producers, leg=None):
        """Windowed overlap: a stager thread fills a bounded queue with
        wire chunks (staging = D2H fetch + slicing) while this thread
        drains it into the socket.  The queue bound IS the send window
        (reference rdma_endpoint.h:83-137 sq window).  ``leg`` (the
        rpcz collective sub-span) gets a timestamped mark per wire
        chunk, so /rpcz shows the staging/write overlap."""
        nchunk = [0]

        def mark_sent(chunk):
            if leg is not None:
                leg.chunk_mark("dcn wire", nchunk[0], 0, len(chunk))
            nchunk[0] += 1

        if len(producers) == 1:
            # single segment: stage inline (a thread would add handoff
            # cost with nothing to overlap — the fetch happened above)
            for chunk in producers[0]():
                self.conn.sendall(chunk)
                mark_sent(chunk)
            return
        q: _queue.Queue = _queue.Queue(maxsize=_SEND_WINDOW)

        def stage():
            try:
                for p in producers:
                    for chunk in p():
                        q.put(chunk)
                q.put(None)
            except Exception as e:  # noqa: BLE001 — surfaced to writer
                q.put(e)

        t = threading.Thread(target=stage, daemon=True, name="dcn-stager")
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    raise item
                self.conn.sendall(item)
                mark_sent(item)
        finally:
            # unblock a stager stuck on a full window if we bailed early
            while t.is_alive():
                try:
                    q.get_nowait()
                except _queue.Empty:
                    t.join(0.05)

    def _receive_frame_body(self, header):
        """Stream segment payloads off the socket; completed device
        segments upload host→device on worker threads WHILE later
        segments are still arriving. Returns (frame, src, dst)."""
        segs = header.get("segs", ())
        sizes = [int(s["n"]) for s in segs]
        # per-segment validation: a negative size could offset the sum
        # below the cap while another segment demands a huge allocation
        if any(n < 0 for n in sizes):
            raise ValueError("negative segment size")
        total = sum(sizes)
        if total > (2 << 30):
            raise ValueError(f"frame body too large: {total}")
        slots: List = [None] * len(segs)  # bytes | (thread,) placeholder
        uploads: List[threading.Thread] = []

        def upload(i, seg, buf):
            try:
                import jax.numpy as jnp
                import numpy as np

                arr = np.frombuffer(buf, dtype=seg["dtype"]).reshape(
                    seg["shape"]
                )
                slots[i] = ("dev", jnp.asarray(arr))
            except Exception:  # noqa: BLE001 — no jax here: keep the bytes
                slots[i] = ("host", buf)

        for i, seg in enumerate(segs):
            n = int(seg["n"])
            # np.empty skips the memset a bytearray(n) pays — zeroing a
            # 64MB receive buffer costs ~10ms per leg on this class of
            # host, and every byte is overwritten by recv_into anyway
            try:
                import numpy as _np

                buf = _np.empty(n, dtype=_np.uint8)
            except ImportError:  # numpy-less: plain (zeroed) bytearray
                buf = bytearray(n)
            view = memoryview(buf)
            got = 0
            while got < n:
                r = self.conn.recv_into(
                    view[got:], min(_WIRE_CHUNK, n - got)
                )
                if r == 0:
                    raise ConnectionError("peer closed mid-frame")
                got += r
            if seg["k"] == "d":
                t = threading.Thread(
                    target=upload, args=(i, seg, buf), daemon=True,
                    name="dcn-upload",
                )
                t.start()
                uploads.append(t)
            else:
                slots[i] = ("host", buf)
        for t in uploads:
            t.join()
        frame = IOBuf()
        for slot in slots:
            kind, val = slot
            if kind == "dev":
                frame.append_device(val)
            else:
                # zero-copy: the bytearray is owned solely by this
                # frame from here on (append() would memcpy it again)
                frame.append_user_data(val)
        src = _coords_from_wire(header["src"])
        dst = _coords_from_wire(header["dst"])
        if src is None or dst is None:
            raise ValueError("malformed frame coords")
        return frame, src, dst

    def reader_loop(self):
        """Frames from the peer: learn reverse routes, deliver locally."""
        from incubator_brpc_tpu.parallel.ici import get_fabric

        fabric = get_fabric()
        while not self.closed:
            msg = _read_header(self.conn)
            if msg is None:
                break
            magic, header = msg
            if magic != _FRAME_MAGIC:
                continue
            if header.get("prime"):
                # the peer's connect-time priming frame: receive path
                # is warm, nothing to deliver
                self.primed_seen = True
                continue
            try:
                frame, src, dst = self._receive_frame_body(header)
            except Exception as e:  # noqa: BLE001
                log_error("dcn frame from %s malformed: %r", self.peer, e)
                break
            # the peer can reach coords `src`: route replies back here
            # (assignment, not setdefault — a reconnected peer's fresh
            # connection must supersede the dead one's stale route)
            with self.bridge._lock:
                self.bridge._routes[src] = self
            # bridged frames force past the local receive window: the
            # remote sender is already bounded by ITS bridge send
            # window, and dropping a delivered frame here would lose it
            # silently mid-protocol (the wire has no NACK)
            rc = fabric.send(
                frame, dst, src, _local_only=True, ignore_eovercrowded=True
            )
            if rc:
                log_error("dcn frame for unknown local coords %s dropped", (dst,))
        self.close()

    def close(self):
        if self.closed:
            return
        self.closed = True
        try:
            self.conn.close()
        except OSError:
            pass
        self.bridge._drop_conn(self)


class DcnBridge:
    """Per-process singleton: listener + outbound connections + routes."""

    def __init__(self):
        self._routes: Dict[Tuple, _BridgeConn] = {}
        self._remote_servers: Dict[Tuple, _BridgeConn] = {}
        self._conns: List[_BridgeConn] = []
        self._lock = threading.Lock()
        self._listener: Optional[_pysocket.socket] = None
        self._uds_listener: Optional[_pysocket.socket] = None
        self._uds_path: Optional[str] = None
        self._uds_dir: Optional[str] = None
        self._ssl_context = None
        self.port = 0

    # ---- routing (used by IciFabric.send) ----------------------------------
    def route(self, coords) -> Optional[_BridgeConn]:
        # check each table independently: a DEAD learned route must not
        # shadow a live advertised one (and vice versa); drop corpses.
        # _lock guards both tables — accept/reader threads insert while
        # the naming service iterates.
        with self._lock:
            for table in (self._routes, self._remote_servers):
                conn = table.get(coords)
                if conn is None:
                    continue
                if conn.closed:
                    table.pop(coords, None)
                    continue
                return conn
        return None

    def remote_server_coords(self) -> List[Tuple]:
        with self._lock:
            items = list(self._remote_servers.items())
        return sorted((c for c, conn in items if not conn.closed), key=str)

    def _drop_conn(self, conn: _BridgeConn):
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)

    # ---- server side --------------------------------------------------------
    def listen(self, port: int = 0, host: str = "0.0.0.0",
               ssl_context=None) -> int:
        """Start accepting bridge connections; returns the bound port.
        ssl_context (an ``ssl.SSLContext`` from
        transport/ssl_helper.make_server_context) encrypts every bridge
        link — the cross-HOST leg is the one that actually crosses
        untrusted networks (reference: ssl on the RDMA bootstrap's TCP
        side channel would be the analog)."""
        if self._listener is not None:
            return self.port
        ls = _pysocket.socket()
        ls.setsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(16)
        self._listener = ls
        self._ssl_context = ssl_context
        self.port = ls.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        # same-host fast path: a UDS listener alongside TCP, advertised
        # in the hello.  Loopback TCP moves ~2.4 GB/s on this class of
        # host where UDS moves ~7.6 GB/s (one less protocol stack), so
        # a same-host peer upgrades its bridge to the UDS path after
        # the TCP handshake.  Skipped under TLS (the TCP link is the
        # authenticated one; same-host traffic needs no wire crypto,
        # but silently downgrading crypto would surprise operators).
        if ssl_context is None:
            import os as _os
            import tempfile as _tmp

            udir = None
            try:
                # private directory (mkdtemp = 0700) + 0600 socket file,
                # both set BEFORE the path is advertised in the hello:
                # a world-writable /tmp socket would let any local user
                # connect to (or pre-create/squat) the bridge endpoint
                udir = _tmp.mkdtemp(prefix=f"dcnbridge-{_os.getpid()}-")
                upath = _os.path.join(udir, "bridge.sock")
                uls = _pysocket.socket(_pysocket.AF_UNIX)
                uls.bind(upath)
                _os.chmod(upath, 0o600)
                uls.listen(16)
                self._uds_listener = uls
                self._uds_path = upath
                self._uds_dir = udir
                threading.Thread(
                    target=self._accept_loop_uds, daemon=True
                ).start()
            except OSError as e:  # no UDS support: TCP-only is fine
                log_error("DCN UDS listener unavailable: %r", e)
                if udir is not None:  # don't orphan the private dir
                    import shutil as _shutil

                    _shutil.rmtree(udir, ignore_errors=True)
        log_info("DCN bridge listening on %s:%d%s", host, self.port,
                 " (TLS)" if ssl_context else "")
        _spawn_warmup()
        return self.port

    def _accept_loop(self):
        while self._listener is not None:
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn, f"{addr[0]}:{addr[1]}"),
                daemon=True,
            ).start()

    def _accept_loop_uds(self):
        while self._uds_listener is not None:
            try:
                conn, _ = self._uds_listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn, f"uds:{self._uds_path}"),
                daemon=True,
            ).start()

    def _serve_conn(self, conn: _pysocket.socket, peer: str):
        from incubator_brpc_tpu.parallel.ici import get_fabric

        if self._ssl_context is not None:
            from incubator_brpc_tpu.transport.ssl_helper import (
                wrap_server_side,
            )

            conn = wrap_server_side(
                conn, self._ssl_context, 5.0, peer, log_error
            )
            if conn is None:
                return
        msg = _read_message(conn)
        if msg is None or msg[0] != _HELLO_MAGIC:
            conn.close()
            return
        bc = _BridgeConn(self, conn, peer)
        with self._lock:
            self._conns.append(bc)
            # the peer's advertised servers are reachable through it
            # (newest connection wins: reconnects supersede dead routes)
            for raw in msg[1].get("server_coords", ()):
                c = _coords_from_wire(raw, server=True)
                if c is not None:
                    self._remote_servers[c] = bc
        self._send_hello(bc, get_fabric())
        bc.send_prime()  # warm the peer's receive path pre-traffic
        bc.reader_loop()

    # ---- client side --------------------------------------------------------
    def connect(self, host: str, port: int, timeout_s: float = 5.0,
                ssl_context=None, server_hostname: str = "") -> List[Tuple]:
        """Dial a remote bridge; returns its advertised server coords.
        ssl_context (from transport/ssl_helper.make_client_context)
        encrypts the link; server_hostname feeds SNI/verification."""
        from incubator_brpc_tpu.parallel.ici import get_fabric

        conn = _pysocket.create_connection((host, port), timeout=timeout_s)
        conn.settimeout(timeout_s)
        if ssl_context is not None:
            conn = ssl_context.wrap_socket(
                conn, server_hostname=server_hostname or None
            )
        # handshake on the raw socket BEFORE _BridgeConn wraps a TLS
        # conn in _LockedTlsSocket: single-threaded here, and the
        # timeout_s bound stays in force (the guard manages timeouts
        # per-call and would unbound this read)
        try:
            conn.sendall(self._hello_bytes(get_fabric()))
            msg = _read_message(conn)
        except OSError:
            msg = None
        if msg is None or msg[0] != _HELLO_MAGIC:
            conn.close()
            raise ConnectionError(f"dcn handshake with {host}:{port} failed")
        conn.settimeout(None)
        # same-host upgrade: a loopback peer advertising a UDS endpoint
        # gets the bridge over AF_UNIX instead (~3x loopback-TCP
        # bandwidth: one protocol stack less per byte).  The TCP
        # connection is discarded after a successful UDS handshake;
        # any failure falls back to the TCP link just established.
        uds_path = msg[1].get("uds")
        if (
            ssl_context is None
            and isinstance(uds_path, str)
            and host in ("127.0.0.1", "localhost", "::1")
        ):
            uconn = None
            try:
                uconn = _pysocket.socket(_pysocket.AF_UNIX)
                uconn.settimeout(timeout_s)
                uconn.connect(uds_path)
                uconn.sendall(self._hello_bytes(get_fabric()))
                umsg = _read_message(uconn)
                if umsg is not None and umsg[0] == _HELLO_MAGIC:
                    uconn.settimeout(None)
                    conn.close()
                    conn = uconn
                    uconn = None  # ownership moved: don't close below
                    msg = umsg
                    port_label = f"uds:{uds_path}"
                else:
                    port_label = f"{host}:{port}"
            except OSError:
                port_label = f"{host}:{port}"
            finally:
                if uconn is not None:
                    try:
                        uconn.close()
                    except OSError:
                        pass
        else:
            port_label = f"{host}:{port}"
        bc = _BridgeConn(self, conn, port_label)
        coords = [
            c
            for raw in msg[1].get("server_coords", ())
            if (c := _coords_from_wire(raw, server=True)) is not None
        ]
        with self._lock:
            for c in coords:
                self._remote_servers[c] = bc
            self._conns.append(bc)
        threading.Thread(target=bc.reader_loop, daemon=True).start()
        _spawn_warmup()
        bc.send_prime()  # warm the acceptor's receive path pre-traffic
        return coords

    def _hello_bytes(self, fabric) -> bytes:
        body = {
            "role": "fabric",
            "server_coords": [
                _coords_to_wire(c) for c in fabric.local_server_coords()
            ],
        }
        if self._uds_path is not None:
            # same-host peers may upgrade to this UDS endpoint (~3x the
            # loopback-TCP bandwidth); unknown keys are ignored by old
            # peers, so the wire stays version-compatible
            body["uds"] = self._uds_path
        header = json.dumps(body).encode()
        return _HELLO_MAGIC + struct.pack(">I", len(header)) + header

    def _send_hello(self, bc: _BridgeConn, fabric):
        with bc._send_lock:
            bc.conn.sendall(self._hello_bytes(fabric))

    def close(self):
        ls, self._listener = self._listener, None
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        uls, self._uds_listener = self._uds_listener, None
        if uls is not None:
            try:
                uls.close()
            except OSError:
                pass
        if self._uds_path is not None:
            import os as _os

            try:
                _os.unlink(self._uds_path)
            except OSError:
                pass
            self._uds_path = None
        if getattr(self, "_uds_dir", None) is not None:
            import os as _os

            try:
                _os.rmdir(self._uds_dir)
            except OSError:
                pass
            self._uds_dir = None
        with self._lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            c.close()
        with self._lock:
            self._routes.clear()
            self._remote_servers.clear()


_bridge: Optional[DcnBridge] = None
_bridge_lock = threading.Lock()


def get_bridge() -> DcnBridge:
    global _bridge
    if _bridge is None:
        with _bridge_lock:
            if _bridge is None:
                _bridge = DcnBridge()
    return _bridge


def listen_dcn(port: int = 0, host: str = "0.0.0.0", ssl_context=None) -> int:
    return get_bridge().listen(port, host, ssl_context=ssl_context)


def connect_dcn(
    host: str, port: int, timeout_s: float = 5.0, ssl_context=None,
    server_hostname: str = "",
) -> List[Tuple]:
    return get_bridge().connect(
        host, port, timeout_s, ssl_context=ssl_context,
        server_hostname=server_hostname,
    )
