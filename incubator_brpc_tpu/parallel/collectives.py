"""Collective lowerings for combo channels and streaming.

The north star's mapping (SURVEY.md §2.6), implemented as jitted
shard_map programs over a Mesh:

| RPC construct            | XLA collective lowering               |
|--------------------------|---------------------------------------|
| ParallelChannel broadcast + merge | psum / all_gather over "chip" |
| PartitionChannel scatter/reshard  | all_to_all over "chip"        |
| Streaming RPC ring (long payload) | ppermute neighbor exchange    |
| Backup request (hedged read)      | psum of first-valid mask      |

These are the *data-plane* lowering: when a ParallelChannel's
sub-responses are tensors sharded over the mesh, the merge executes as
ONE fused collective instead of N host-side RPC merges. Control-plane
semantics (fail_limit, partial merges) stay host-side in
client/combo.py, which falls back to per-sub-call RPC when a
sub-channel is unhealthy — collectives don't have partial-failure
semantics, so the lowering only fires on the all-healthy fast path.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _traced(fn: Callable, op: str, axis: str) -> Callable:
    """Wrap a jitted collective so each invocation inside a traced RPC
    leaves an rpcz sub-span (kind "collective") under the active
    task-local span — a fan-out RPC whose merge lowers to a collective
    shows the leg in its trace. Outside any RPC (a plain training
    loop) no span is created: parentless spans at kHz step rates would
    drown the Collector's sampling budget and churn the /rpcz ring.
    The span brackets dispatch (XLA executes asynchronously; device
    time shows up in the op's own profile, not here)."""

    from incubator_brpc_tpu.observability.span import Span

    label = f"{op}@{axis}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        span = Span.create_collective("collective", label)
        try:
            out = fn(*args, **kwargs)
        except Exception:
            if span is not None:
                span.end(1)
            raise
        if span is not None:
            span.end(0)
        return out

    return wrapper


def shard_map_relaxed(f, mesh, in_specs, out_specs):
    """shard_map with the replication check relaxed (all_gather /
    ppermute results are replicated/varying in ways the static checker
    can't always infer; kwarg name differs across jax versions).
    Shared by the lowerings below and by the sharded batch kernels
    (batching/sharded.py)."""
    try:
        shard_map = jax.shard_map  # jax >= 0.8 public API
    except AttributeError:
        from jax.experimental.shard_map import shard_map

    for kw in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
        except TypeError:
            continue
    raise RuntimeError("shard_map unavailable")


_shard_map = shard_map_relaxed


def parallel_merge(mesh: Mesh, axis: str = "chip", op: str = "sum") -> Callable:
    """ParallelChannel merge: every node holds a sub-response shard
    [*, ...]; returns the fused merged response replicated on all nodes.
    Lowera to psum (sum/mean/max) on the ICI axis."""
    def merged(x):
        if op == "sum":
            return jax.lax.psum(x, axis)
        if op == "mean":
            return jax.lax.pmean(x, axis)
        if op == "max":
            return jax.lax.pmax(x, axis)
        raise ValueError(op)

    fn = _shard_map(merged, mesh, P(axis), P())
    return _traced(jax.jit(fn), f"psum_{op}", axis)


def parallel_broadcast_gather(mesh: Mesh, axis: str = "chip") -> Callable:
    """ParallelChannel fan-out with concat merge: each node contributes
    its shard; all nodes receive the concatenation (AllGather)."""
    fn = _shard_map(
        lambda x: jax.lax.all_gather(x, axis, tiled=True), mesh, P(axis), P()
    )
    return _traced(jax.jit(fn), "all_gather", axis)


def partition_reshard(mesh: Mesh, axis: str = "chip") -> Callable:
    """PartitionChannel re-partitioning: switch which dimension is
    sharded across the partition group (AllToAll) — the collective form
    of DynamicPartitionChannel migrating partition schemes
    (partition_channel.h:54-110)."""
    def reshard(x):  # x: [local_rows, cols] sharded on rows; out: cols sharded
        n = jax.lax.psum(1, axis)
        xs = x.reshape(x.shape[0], n, x.shape[1] // n)
        out = jax.lax.all_to_all(xs, axis, split_axis=1, concat_axis=0, tiled=False)
        return out.reshape(-1, x.shape[1] // n)

    fn = _shard_map(reshard, mesh, P(axis, None), P(axis, None))
    return _traced(jax.jit(fn), "all_to_all", axis)


def ring_stream(mesh: Mesh, axis: str = "chip", hops: Optional[int] = None) -> Callable:
    """Streaming RPC's neighbor pipeline: pass chunks around the ICI
    ring with ppermute (the collective form of flow-controlled
    StreamWrite chains; also the building block of ring attention /
    sequence parallelism on this fabric). Each hop both forwards the
    buffer and folds it into a running accumulator, so after N-1 hops
    every node has seen every shard while only ever holding one."""
    def ring(x):
        n = jax.lax.psum(1, axis)
        steps = (n - 1) if hops is None else hops

        def hop(carry, _):
            buf, acc = carry
            nxt = jax.lax.ppermute(
                buf,
                axis,
                perm=[(i, (i + 1) % mesh.shape[axis]) for i in range(mesh.shape[axis])],
            )
            return (nxt, acc + nxt), None

        (buf, acc), _ = jax.lax.scan(hop, (x, x), None, length=steps)
        return acc

    fn = _shard_map(ring, mesh, P(axis), P(axis))
    return _traced(jax.jit(fn), "ppermute_ring", axis)


def hedged_first_valid(mesh: Mesh, axis: str = "chip") -> Callable:
    """Backup-request merge on tensors: each replica offers (response,
    valid flag); every node gets the response of the lowest-indexed
    valid replica (hedged read)."""
    def pick(x, valid):
        idx = jax.lax.axis_index(axis)
        n = jax.lax.psum(1, axis)
        # a replica is valid if any of its flag elements is set; valid
        # replicas rank by index, invalid ones are pushed past the end
        me_valid = jnp.max(valid) > 0
        score = jnp.where(me_valid, idx, n + 1).astype(jnp.int32)
        best = jax.lax.pmin(score, axis)
        contribution = jnp.where(score == best, x, jnp.zeros_like(x))
        return jax.lax.psum(contribution, axis)

    fn = _shard_map(pick, mesh, (P(axis), P(axis)), P())
    return _traced(jax.jit(fn), "hedged_first_valid", axis)
