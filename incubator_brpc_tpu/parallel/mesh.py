"""Mesh management + ICI topology naming.

The north star: "brpc's naming-service layer resolves TPU slice
coordinates". Here the device mesh is the cluster: each device is an
``ici://slice<i>/chip<j>`` endpoint, ``create_mesh`` builds the
jax.sharding.Mesh the collective lowerings run over, and
``ici_endpoints`` enumerates the addressable nodes (consumed by the
ici:// naming service and the PartitionChannel).

Axis convention: ("slice", "chip") — "slice" is the DCN-ish outer axis
(cross-slice), "chip" the ICI-ish inner axis. Collectives should ride
"chip" first (ICI, not DCN), mirroring how shardings are laid out in
the scaling-book recipe.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from incubator_brpc_tpu.utils.endpoint import EndPoint


def create_mesh(
    shape: Optional[Tuple[int, int]] = None,
    axis_names: Tuple[str, str] = ("slice", "chip"),
    devices: Optional[Sequence] = None,
):
    """Build a 2D Mesh over the available devices.

    shape=None picks (1, n_devices) — one slice, all chips on ICI.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if shape is None:
        shape = (1, n)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, axis_names)


_default_mesh = None


def default_mesh():
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = create_mesh()
    return _default_mesh


def ici_endpoints(mesh=None) -> List[EndPoint]:
    """Enumerate mesh coordinates as ici:// endpoints (the topology the
    ici:// naming service serves)."""
    if mesh is None:
        mesh = default_mesh()
    out = []
    n_slices, n_chips = mesh.devices.shape
    for s in range(n_slices):
        for c in range(n_chips):
            out.append(EndPoint.ici(s, c))
    return out


def device_of(mesh, ep: EndPoint):
    s, c = ep.coords
    return mesh.devices[s][c]
