"""ICI fabric transport — the RDMA-endpoint analog for TPU.

Reference template: the RDMA subsystem (rdma/rdma_endpoint.h:63-227):
an alternative data path under the same Socket abstraction, with
pre-registered memory (block_pool), zerocopy send/recv straight from
IOBuf blocks, and completion polling wired into the same event
machinery. Here (north star): frames are IOBufs whose DeviceRef
segments are HBM-resident jax.Arrays; "transmission" runs the payload
through the fused Pallas copy+checksum kernel (same chip — one real
HBM traversal per hop, receiver gets a fresh buffer + integrity
checksum) or issues an XLA device-to-device transfer (cross chip) —
host bytes only ever materialize for the small meta header. Set
``IciFabric.zero_copy`` for the explicit reference-move fast path. Completion delivery uses an ExecutionQueue per port — the
"libtpu completion queue polled instead of epoll" — feeding the exact
same protocol parse path as TCP (one framing, two transports).

Single-process scope in round 1: the fabric routes between ici://
coordinates registered in this process (the test harness's in-process
multi-node pattern, SURVEY.md §4); the cross-host hop (DCN bootstrap,
like RDMA's TCP side-channel handshake) plugs in behind
``IciFabric.send`` later without touching callers.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.observability.span import Span
from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue
from incubator_brpc_tpu.transport import socket as socket_mod
from incubator_brpc_tpu.transport.input_messenger import InputMessenger
from incubator_brpc_tpu.transport.socket import Socket, SocketOptions
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.iobuf import IOBuf, DeviceRef
from incubator_brpc_tpu.utils.logging import log_error


class _LazyPeer:
    """Defers _fmt() until a chaos spec actually matches on peer — the
    armed-but-unmatched send path pays no string formatting (the
    injector's raw-object contract), while matchers still see the
    ``sliceN/chipM`` label, not the raw tuple repr."""

    __slots__ = ("coords",)

    def __init__(self, coords):
        self.coords = coords

    def __str__(self):
        return _fmt(self.coords)


def _fmt(coords) -> str:
    """ici://-ish label for span methods: (0, 1) → slice0/chip1."""
    try:
        s, c = coords
        if isinstance(s, int) and isinstance(c, int):
            return f"slice{s}/chip{c}"
        return f"{s}:{c}"
    except Exception:  # noqa: BLE001
        return str(coords)


class IciPort:
    """One endpoint on the fabric (analog RdmaEndpoint). Owns the
    completion queue whose consumer parses frames through the shared
    InputMessenger machinery."""

    def __init__(self, fabric: "IciFabric", coords: Tuple[int, int], server=None, device=None):
        self.fabric = fabric
        self.coords = coords
        self.server = server  # non-None = server port (accepts requests)
        self.device = device  # jax device owning this port's HBM
        self.messenger = InputMessenger()
        # completion queue: frames arrive here (the "CQ polled instead
        # of epoll"); consumer runs on the runtime like ProcessEvent.
        # Queue wait feeds /latency_breakdown's _runtime/ici_cq row.
        from incubator_brpc_tpu.observability.latency_breakdown import (
            queue_wait_recorder,
        )

        self._cq = ExecutionQueue(
            self._drain_completions,
            wait_recorder=queue_wait_recorder("ici_cq"),
        )
        # receive-window flow control (the RDMA endpoint's sq window /
        # socket _overcrowded analog, rdma_endpoint.h:83-137): bytes
        # delivered but not yet consumed.  A stalled consumer pushes
        # senders into EOVERCROWDED instead of growing the queue
        # without bound.
        self._queued_bytes = 0
        self._qb_lock = threading.Lock()
        self.overcrowded_bytes = 256 << 20
        # per-peer connection sockets (fd-less), keyed by peer coords
        self._conns: Dict[Tuple[int, int], int] = {}
        self._lock = threading.Lock()
        self.closed = False

    # ---- completion processing ---------------------------------------------
    def _drain_completions(self, batch):
        for i, (frame, peer_coords) in enumerate(batch):
            n = len(frame)
            try:
                if self.closed:
                    # the finally below releases THIS frame's window
                    # bytes; the undrained rest of the batch would leak
                    # theirs (and wedge senders at EOVERCROWDED on a
                    # port reopened at these coords) — release them all
                    rest = sum(len(f) for f, _ in batch[i + 1:])
                    if rest:
                        with self._qb_lock:
                            self._queued_bytes -= rest
                    return
                sock = self._conn_socket(peer_coords)
                if sock is None or sock.failed:
                    continue
                # rpcz received stamp: the fabric CQ's epoll-IN analog
                sock.last_read_event_us = _time.time_ns() // 1000
                sock.read_buf.append(frame)  # ref move, zero-copy
                try:
                    # the SAME cut/dispatch loop as TCP, auth gate
                    # included; parse sees DeviceRefs untouched
                    self.messenger.cut_and_dispatch(sock)
                except Exception as e:  # noqa: BLE001
                    log_error("ici completion processing failed: %r", e)
            finally:
                # consumed: open the receive window back up
                with self._qb_lock:
                    self._queued_bytes -= n

    def deliver(self, frame: IOBuf, from_coords: Tuple[int, int],
                inline_ok: bool = False, force: bool = False) -> bool:
        """Called by the fabric: enqueue a received frame (a completion).

        Server ports and bridge-delivered frames ALWAYS go through the
        completion queue: inline dispatch would run user service
        handlers on the SENDER's thread (breaking the non-blocking send
        contract) or block the DCN bridge reader mid-stream.  CLIENT
        ports on a local same-process send may run inline
        (execute_or_inline): response processing is framework code plus
        the done callback, and skipping the queue handoff saves one
        thread wakeup on the sync RPC turnaround — the reference
        likewise runs response processing on the event thread that
        read it (process_response, input_messenger.cpp)."""
        n = len(frame)
        with self._qb_lock:
            if (
                not force
                and self._queued_bytes + n > self.overcrowded_bytes
            ):
                return False  # receive window full → sender gets
                # EOVERCROWDED (socket.h _overcrowded analog)
            self._queued_bytes += n
        socket_mod.g_in_bytes << n
        if inline_ok and self.server is None:
            self._cq.execute_or_inline((frame, from_coords))
        else:
            self._cq.execute((frame, from_coords))
        return True

    # ---- connection sockets -------------------------------------------------
    def _conn_socket(self, peer_coords: Tuple[int, int]) -> Optional[Socket]:
        # the whole check-then-create runs under the lock so concurrent
        # callers can't mint duplicate (and leaked) sockets for one peer
        with self._lock:
            sid = self._conns.get(peer_coords)
            if sid is not None:
                sock = Socket.address(sid)
                if sock is not None and not sock.failed:
                    return sock
            sid = Socket.create(
                SocketOptions(
                    fd=None,
                    remote=EndPoint.ici(*peer_coords),
                    messenger=self.messenger,
                    server=self.server,
                )
            )
            sock = Socket.address(sid)
            sock.ici_port = self
            sock.ici_peer_coords = peer_coords
            self._conns[peer_coords] = sid
            return sock

    def connect(self, peer_coords: Tuple[int, int]):
        """Client-side: SocketId for the connection to peer coords,
        or None (note: 0 is a valid SocketId — the first pool slot)."""
        sock = self._conn_socket(peer_coords)
        return sock.sid if sock is not None else None

    def close(self):
        self.closed = True
        self._cq.stop()
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sid in conns:
            s = Socket.address(sid)
            if s is not None:
                s.set_failed(errors.ECLOSE, "ici port closed")


class IciFabric:
    """The interconnect: routes frames between registered ports and
    places device payload onto the destination's device (the XLA
    device-to-device transfer; a no-op when src and dst share a chip)."""

    def __init__(self):
        self._ports: Dict[Tuple[int, int], IciPort] = {}
        self._lock = threading.Lock()
        # False (default): same-chip delivery runs every device segment
        # through the Pallas transmit op (ops/transfer.transmit_array) so
        # the payload demonstrably traverses HBM once per hop — the
        # honest model of an ICI transmission. True: move by reference
        # (the in-process fast path; no device bytes move).
        self.zero_copy = False

    def register(self, coords: Tuple[int, int], server=None, device=None) -> IciPort:
        with self._lock:
            if coords in self._ports and not self._ports[coords].closed:
                raise ValueError(f"ici coords {coords} already registered")
            port = IciPort(self, coords, server=server, device=device)
            self._ports[coords] = port
            return port

    def unregister(self, coords: Tuple[int, int]):
        with self._lock:
            port = self._ports.pop(coords, None)
        if port is not None:
            port.close()

    def port(self, coords: Tuple[int, int]) -> Optional[IciPort]:
        port = self._ports.get(coords)
        return port if port is not None and not port.closed else None

    def send(
        self,
        frame: IOBuf,
        dst: Tuple[int, int],
        src: Tuple[int, int],
        zero_copy: Optional[bool] = None,
        _local_only: bool = False,
        ignore_eovercrowded: bool = False,
    ) -> int:
        """Ship a frame. Device segments are re-placed onto the dst
        device if it differs (jax.device_put = the ICI/DCN hop);
        same-device segments traverse HBM through the Pallas transmit
        op unless zero_copy — then they move by reference. Coords not
        registered in this process route over the DCN bridge
        (parallel/dcn.py), the RDMA-TCP-bootstrap analog."""
        dst_port = self.port(dst)
        if dst_port is None:
            if not _local_only:
                from incubator_brpc_tpu.parallel.dcn import get_bridge

                route = get_bridge().route(dst)
                if route is not None:
                    # the DCN bridge records its own collective leg span
                    rc = route.send_frame(frame, dst, src)
                    if rc == 0:
                        socket_mod.g_out_bytes << len(frame)
                        socket_mod.g_out_messages << 1
                    return rc
            return errors.EFAILEDSOCKET
        close_after_deliver = False
        if _chaos.armed:
            spec = _chaos.check("ici.send", peer=_LazyPeer(dst))
            if spec is not None:
                act = spec.action
                if act == "drop":
                    # the leg silently vanishes (an in-flight hop lost
                    # on the fabric): callers recover via deadlines
                    return 0
                if act == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif act == "reset":
                    return errors.EFAILEDSOCKET
                elif act == "close_mid_batch":
                    # deliver THIS frame, then close the destination
                    # port so its completion-queue drain observes the
                    # close mid-batch (the receive-window release path)
                    close_after_deliver = True
        # rpcz collective sub-span: one ICI leg (placement + delivery),
        # parented to the active RPC span so fan-out traces show every
        # per-chip hop (skipped entirely outside a traced RPC)
        leg = Span.create_collective("ici", f"{_fmt(src)}->{_fmt(dst)}")
        if leg is not None:
            leg.request_size = len(frame)
        try:
            try:
                if dst_port.device is not None:
                    zc = self.zero_copy if zero_copy is None else zero_copy
                    self._place_segments(frame, dst_port.device, zc)
                if not _local_only:
                    # bridged inbound frames (_local_only) are RECEIVED
                    # traffic; counting them here would inflate the
                    # outbound metrics
                    socket_mod.g_out_bytes << len(frame)
                    socket_mod.g_out_messages << 1
                delivered = dst_port.deliver(
                    frame, src, inline_ok=not _local_only,
                    force=ignore_eovercrowded,
                )
            except BaseException:
                # close the leg with an error before re-raising: the
                # trace must show the hop that failed, not silently
                # lose it
                if leg is not None:
                    leg.end(errors.EINTERNAL)
                raise
        finally:
            # an injected close must happen however delivery went
            # (success, window-full, raise): the spec's hit budget is
            # already consumed, so skipping here would record a close
            # that never happened
            if close_after_deliver:
                dst_port.close()
        if not delivered:
            if leg is not None:
                leg.end(errors.EOVERCROWDED)
            return errors.EOVERCROWDED
        if leg is not None:
            leg.end(0)
        return 0

    def local_server_coords(self):
        """Server ports registered in THIS process (what the DCN hello
        advertises to peers)."""
        with self._lock:
            items = list(self._ports.items())
        return sorted(
            coords
            for coords, port in items
            if not port.closed
            and port.server is not None
            and isinstance(coords[0], int)
            and isinstance(coords[1], int)
        )

    def server_coords(self):
        """Every reachable server port: local ones plus those learned
        over DCN bridges (the tpu:// naming service reads this, so a
        cross-process cluster resolves like a local one)."""
        coords = set(self.local_server_coords())
        from incubator_brpc_tpu.parallel.dcn import _bridge

        if _bridge is not None:
            coords.update(
                c
                for c in _bridge.remote_server_coords()
                if isinstance(c[0], int) and isinstance(c[1], int)
            )
        return sorted(coords)

    def routable(self, coords) -> bool:
        """True if coords are a local port or reachable over a bridge."""
        if self.port(coords) is not None:
            return True
        from incubator_brpc_tpu.parallel.dcn import _bridge

        return _bridge is not None and _bridge.route(coords) is not None

    @staticmethod
    def _place_segments(frame: IOBuf, device, zero_copy: bool):
        import jax

        from incubator_brpc_tpu.ops.transfer import transmit_array

        for ref in frame.device_segments():
            arr = ref.whole_array()
            if arr is None:
                continue  # split segment: materialized as bytes downstream
            src_devs = getattr(arr, "devices", lambda: set())()
            if device not in src_devs:
                ref.array = jax.device_put(arr, device)
            elif not zero_copy:
                # same-chip hop: the payload traverses HBM once through
                # the fused copy+checksum kernel — receiver gets a fresh
                # buffer plus a device-resident integrity checksum
                ref.array, ref.csum = transmit_array(arr)


_fabric: Optional[IciFabric] = None
_fabric_lock = threading.Lock()


def get_fabric() -> IciFabric:
    global _fabric
    if _fabric is None:
        with _fabric_lock:
            if _fabric is None:
                _fabric = IciFabric()
    return _fabric


import itertools as _itertools
import os as _os

_client_port_seq = _itertools.count(1)


def acquire_client_port(device=None) -> IciPort:
    """Register a uniquely-keyed client port (shared helper for
    Channel and LoadBalancerWithNaming). Keys carry the pid so client
    ports of DIFFERENT processes bridged to one server can't collide in
    its DCN reply-routing table."""
    return get_fabric().register(
        ("client", f"{_os.getpid()}-{next(_client_port_seq)}"),
        server=None,
        device=device,
    )
