"""ICI fabric transport — the RDMA-endpoint analog for TPU.

Reference template: the RDMA subsystem (rdma/rdma_endpoint.h:63-227):
an alternative data path under the same Socket abstraction, with
pre-registered memory (block_pool), zerocopy send/recv straight from
IOBuf blocks, and completion polling wired into the same event
machinery. Here (north star): frames are IOBufs whose DeviceRef
segments are HBM-resident jax.Arrays; "transmission" runs the payload
through the fused Pallas copy+checksum kernel (same chip — one real
HBM traversal per hop, receiver gets a fresh buffer + integrity
checksum) or issues an XLA device-to-device transfer (cross chip) —
host bytes only ever materialize for the small meta header. Set
``IciFabric.zero_copy`` for the explicit reference-move fast path. Completion delivery uses an ExecutionQueue per port — the
"libtpu completion queue polled instead of epoll" — feeding the exact
same protocol parse path as TCP (one framing, two transports).

Single-process scope in round 1: the fabric routes between ici://
coordinates registered in this process (the test harness's in-process
multi-node pattern, SURVEY.md §4); the cross-host hop (DCN bootstrap,
like RDMA's TCP side-channel handshake) plugs in behind
``IciFabric.send`` later without touching callers.
"""

from __future__ import annotations

import contextlib
import threading
import time as _time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.observability.profiling import hbm_account, kernel_section
from incubator_brpc_tpu.observability.span import Span
from incubator_brpc_tpu.utils.segmentation import (
    DEVICE_CHUNK_BYTES,
    MIN_CHUNKS,
)
from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue
from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.transport import socket as socket_mod
from incubator_brpc_tpu.transport.input_messenger import InputMessenger
from incubator_brpc_tpu.transport.socket import Socket, SocketOptions
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.iobuf import IOBuf, DeviceRef
from incubator_brpc_tpu.utils.logging import log_error

# thread-local delivery burst (see IciFabric.delivery_burst): while a
# burst is open on this thread, queued (non-inline) deliveries collect
# here and each destination port's completion queue wakes ONCE at
# burst close instead of once per frame — the engine.cpp
# flush_pending_burst read-cycle batching, applied to the fabric.
_BURST_TLS = threading.local()

# Frames at or above this size bypass burst capture and wake the
# destination queue immediately: the wake being amortized costs
# microseconds, so holding a bulk frame (milliseconds of parse +
# placement work the receiver could already be overlapping with the
# sender's next placement) until burst close would trade real pipeline
# overlap for nothing.  Coalescing is a small-RPC optimization.
BURST_BYPASS_BYTES = 256 << 10

# HBM heap profiler tags (observability/profiling.py): ring-resident
# staging slots, and device payloads placed for an in-flight frame
# (charged from device_put until the carrying DeviceRef dies)
_STAGING_ACCT = hbm_account("ici.staging")
_INFLIGHT_ACCT = hbm_account("ici.inflight")

# Pallas DMA lane counters (chunk_mode="pallas"; registered in
# analysis.invariants.METRIC_MODULES for the render lint).  ``frames``
# counts fused kernel dispatches — the bench structure guard pins
# frames == dispatches so a silent fallback to the chunked pipeline
# fails loudly; ``fallbacks`` counts frames the lane declined
# (off-TPU, untileable) and routed to the legacy transmit instead.
ici_pallas_frames = Adder(0).expose("rpc_ici_pallas_frames")
ici_pallas_bytes = Adder(0).expose("rpc_ici_pallas_bytes")
ici_pallas_fallbacks = Adder(0).expose("rpc_ici_pallas_fallbacks")
ici_pallas_stacked_frames = Adder(0).expose("rpc_ici_pallas_stacked_frames")
ici_pallas_stacked_segments = Adder(0).expose(
    "rpc_ici_pallas_stacked_segments"
)


class _LazyPeer:
    """Defers _fmt() until a chaos spec actually matches on peer — the
    armed-but-unmatched send path pays no string formatting (the
    injector's raw-object contract), while matchers still see the
    ``sliceN/chipM`` label, not the raw tuple repr."""

    __slots__ = ("coords",)

    def __init__(self, coords):
        self.coords = coords

    def __str__(self):
        return _fmt(self.coords)


def _fmt(coords) -> str:
    """ici://-ish label for span methods: (0, 1) → slice0/chip1."""
    try:
        s, c = coords
        if isinstance(s, int) and isinstance(c, int):
            return f"slice{s}/chip{c}"
        return f"{s}:{c}"
    except Exception:  # noqa: BLE001
        return str(coords)


class StagingRing:
    """Ring of persistent per-peer device staging buffers — the RDMA
    block_pool analog (rdma_endpoint.h:63-227 pre-registered memory).

    The pipelined chunked send donates a ring slot to each chunk's
    copy+checksum kernel (ops/transfer.device_copy_with_checksum_chunk_
    into): the kernel output aliases the slot's memory, the output goes
    back into the ring after the frame assembles, and steady-state
    sends perform ZERO per-call device allocation for chunk staging.
    Slots are keyed by (shape, dtype); the ring holds at most ``depth``
    slots per key (2-4 covers the double-buffer plus one in flight) and
    at most ``max_keys`` shapes (LRU-evicted — a port cycling many
    payload shapes degrades to plain allocation, never to unbounded
    HBM)."""

    def __init__(self, depth: int = 4, max_keys: int = 8):
        self.depth = depth
        self.max_keys = max_keys
        self._slots: Dict[Tuple, deque] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def acquire(self, shape, dtype):
        """A reusable buffer of (shape, dtype), or None (caller
        allocates; release() later seeds the ring).  An acquired slot
        leaves the ``ici.staging`` HBM ledger — it is the caller's
        (in-flight frame's) memory until released back."""
        key = (tuple(shape), str(dtype))
        with self._lock:
            q = self._slots.get(key)
            if q:
                # LRU touch: move key to the back of the eviction order
                self._slots[key] = self._slots.pop(key)
                self.hits += 1
                arr, charge = q.popleft()
                _STAGING_ACCT.release(charge)
                return arr
            self.misses += 1
            return None

    def release(self, arr) -> None:
        """Return a frame's staging output to the ring.  Only call for
        buffers nothing downstream holds (the chunked send releases
        chunk outputs only after a concat copied them out)."""
        key = (tuple(arr.shape), str(arr.dtype))
        with self._lock:
            q = self._slots.get(key)
            if q is None:
                while len(self._slots) >= self.max_keys:
                    # LRU eviction: dict preserves insertion order and
                    # acquire() re-inserts on hit, so the first key is
                    # the least recently used
                    evq = self._slots.pop(next(iter(self._slots)))
                    for _, charge in evq:
                        _STAGING_ACCT.release(charge)
                q = self._slots[key] = deque()
            if len(q) < self.depth:
                q.append((arr, _STAGING_ACCT.adopt(arr)))

    def clear(self) -> None:
        with self._lock:
            for q in self._slots.values():
                for _, charge in q:
                    _STAGING_ACCT.release(charge)
            self._slots.clear()


class IciPort:
    """One endpoint on the fabric (analog RdmaEndpoint). Owns the
    completion queue whose consumer parses frames through the shared
    InputMessenger machinery."""

    def __init__(self, fabric: "IciFabric", coords: Tuple[int, int], server=None, device=None):
        self.fabric = fabric
        self.coords = coords
        self.server = server  # non-None = server port (accepts requests)
        self.device = device  # jax device owning this port's HBM
        self.messenger = InputMessenger()
        # completion queue: frames arrive here (the "CQ polled instead
        # of epoll"); consumer runs on the runtime like ProcessEvent.
        # Queue wait feeds /latency_breakdown's _runtime/ici_cq row.
        from incubator_brpc_tpu.observability.latency_breakdown import (
            queue_wait_recorder,
        )

        self._cq = ExecutionQueue(
            self._drain_completions,
            wait_recorder=queue_wait_recorder("ici_cq"),
        )
        # receive-window flow control (the RDMA endpoint's sq window /
        # socket _overcrowded analog, rdma_endpoint.h:83-137): bytes
        # delivered but not yet consumed.  A stalled consumer pushes
        # senders into EOVERCROWDED instead of growing the queue
        # without bound.
        self._queued_bytes = 0
        self._qb_lock = threading.Lock()
        self.overcrowded_bytes = 256 << 20
        # per-peer connection sockets (fd-less), keyed by peer coords
        self._conns: Dict[Tuple[int, int], int] = {}
        self._lock = threading.Lock()
        self.closed = False
        # chunk-staging buffer ring for pipelined sends INTO this port
        # (the destination owns the staging memory, like the RDMA
        # endpoint's registered receive blocks)
        self.staging = StagingRing()
        # opt-in inline request dispatch (the usercode_in_dispatcher
        # threading model): a local same-process send may run this
        # server port's handlers on the SENDER's thread, trading the
        # non-blocking send guarantee for two fewer task handoffs per
        # RPC — exactly the tradeoff the TCP path's
        # usercode_in_dispatcher makes
        self.inline_dispatch = bool(
            getattr(getattr(server, "options", None),
                    "usercode_in_dispatcher", False)
        )

    # ---- completion processing ---------------------------------------------
    def _drain_completions(self, batch):
        # window credits release ONCE per batch (the RDMA endpoint's
        # completion-batch accounting): senders blocked at
        # EOVERCROWDED wait at most one batch (batch_max frames) longer
        # than per-frame release, and the steady-state drain pays one
        # lock instead of len(batch)
        released = 0
        try:
            for i, (frame, peer_coords) in enumerate(batch):
                released += len(frame)
                if self.closed:
                    # the finally below releases up to THIS frame; the
                    # undrained rest of the batch would leak its window
                    # bytes (and wedge senders at EOVERCROWDED on a
                    # port reopened at these coords) — count them too
                    released += sum(len(f) for f, _ in batch[i + 1:])
                    return
                sock = self._conn_socket(peer_coords)
                if sock is None or sock.failed:
                    continue
                # rpcz received stamp: the fabric CQ's epoll-IN analog
                sock.last_read_event_us = _time.time_ns() // 1000
                sock.read_buf.append(frame)  # ref move, zero-copy
                try:
                    # the SAME cut/dispatch loop as TCP, auth gate
                    # included; parse sees DeviceRefs untouched
                    self.messenger.cut_and_dispatch(sock)
                except Exception as e:  # noqa: BLE001
                    log_error("ici completion processing failed: %r", e)
        finally:
            if released:
                with self._qb_lock:
                    self._queued_bytes -= released

    def deliver(self, frame: IOBuf, from_coords: Tuple[int, int],
                inline_ok: bool = False, force: bool = False) -> bool:
        """Called by the fabric: enqueue a received frame (a completion).

        Server ports and bridge-delivered frames go through the
        completion queue by default: inline dispatch would run user
        service handlers on the SENDER's thread (breaking the
        non-blocking send contract) or block the DCN bridge reader
        mid-stream.  CLIENT ports on a local same-process send may run
        inline (execute_or_inline): response processing is framework
        code plus the done callback, and skipping the queue handoff
        saves one thread wakeup on the sync RPC turnaround — the
        reference likewise runs response processing on the event thread
        that read it (process_response, input_messenger.cpp).  A server
        that opted into ``usercode_in_dispatcher`` extends the same
        inline treatment to request dispatch (``inline_dispatch``).

        Inside a fabric ``delivery_burst`` (ParallelChannel fan-out,
        ``send_batch``), queued deliveries are captured per-port and
        the completion queue wakes once at burst close — except frames
        ≥ BURST_BYPASS_BYTES, which dispatch immediately so bulk
        receive work overlaps the sender's remaining burst."""
        if self.closed:
            # close raced the fabric's port() lookup: refuse before any
            # credit is reserved (and before a burst could capture a
            # frame that would only be refused — silently — at flush)
            return False
        n = len(frame)
        with self._qb_lock:
            if (
                not force
                and self._queued_bytes + n > self.overcrowded_bytes
            ):
                return False  # receive window full → sender gets
                # EOVERCROWDED (socket.h _overcrowded analog)
            self._queued_bytes += n
        socket_mod.g_in_bytes << n
        if inline_ok and (self.server is None or self.inline_dispatch):
            if not self._cq.execute_or_inline((frame, from_coords)):
                # queue already stopped (close raced the send): the
                # frame will never run — release the reservation and
                # tell the sender, exactly like the queued path below
                with self._qb_lock:
                    self._queued_bytes -= n
                return False
            return True
        pending = getattr(_BURST_TLS, "pending", None)
        if pending is not None and n < BURST_BYPASS_BYTES:
            pending.setdefault(self, []).append((frame, from_coords))
            return True
        if not self._cq.execute((frame, from_coords)):
            # queue already stopped (close raced the send): the frame
            # will never drain — give its window bytes back instead of
            # leaking them against a port reopened at these coords
            with self._qb_lock:
                self._queued_bytes -= n
            return False
        return True

    def _flush_burst(self, items: List) -> None:
        """Enqueue a burst's captured deliveries with ONE consumer wake
        (ExecutionQueue.execute_batch).  A stopped queue refuses the
        batch — release those frames' window credits, same reasoning as
        the single-frame path.  The senders were already told 0 at
        capture time, so this close-between-capture-and-flush race
        resolves through their deadlines (the same way an in-flight
        frame lost to a close does on any transport) — deliver()'s
        ``closed`` pre-check keeps the window microscopic, and the drop
        is LOUD here, never silent."""
        if not self._cq.execute_batch(items):
            n = sum(len(f) for f, _ in items)
            with self._qb_lock:
                self._queued_bytes -= n
            log_error(
                "ici port %s closed mid-burst: %d captured frame(s) "
                "dropped; senders recover via deadline", self.coords,
                len(items),
            )

    # ---- connection sockets -------------------------------------------------
    def _conn_socket(self, peer_coords: Tuple[int, int]) -> Optional[Socket]:
        # the whole check-then-create runs under the lock so concurrent
        # callers can't mint duplicate (and leaked) sockets for one peer
        with self._lock:
            sid = self._conns.get(peer_coords)
            if sid is not None:
                sock = Socket.address(sid)
                if sock is not None and not sock.failed:
                    return sock
            sid = Socket.create(
                SocketOptions(
                    fd=None,
                    remote=EndPoint.ici(*peer_coords),
                    messenger=self.messenger,
                    server=self.server,
                )
            )
            sock = Socket.address(sid)
            sock.ici_port = self
            sock.ici_peer_coords = peer_coords
            self._conns[peer_coords] = sid
            return sock

    def connect(self, peer_coords: Tuple[int, int]):
        """Client-side: SocketId for the connection to peer coords,
        or None (note: 0 is a valid SocketId — the first pool slot)."""
        sock = self._conn_socket(peer_coords)
        return sock.sid if sock is not None else None

    def close(self):
        self.closed = True
        self._cq.stop()
        self.staging.clear()
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sid in conns:
            s = Socket.address(sid)
            if s is not None:
                s.set_failed(errors.ECLOSE, "ici port closed")


class IciFabric:
    """The interconnect: routes frames between registered ports and
    places device payload onto the destination's device (the XLA
    device-to-device transfer; a no-op when src and dst share a chip)."""

    def __init__(self):
        self._ports: Dict[Tuple[int, int], IciPort] = {}
        self._lock = threading.Lock()
        # False (default): same-chip delivery runs every device segment
        # through the Pallas transmit op (ops/transfer.transmit_array) so
        # the payload demonstrably traverses HBM once per hop — the
        # honest model of an ICI transmission. True: move by reference
        # (the in-process fast path; no device bytes move).
        self.zero_copy = False
        # Large-frame chunk policy (shared with the DCN planner via
        # utils/segmentation.py; docs/ici_pipeline.md):
        #   "fused"     — the K-chunk pipeline compiled as ONE program
        #                 (one host dispatch per hop; default — immune
        #                 to per-launch host/tunnel latency),
        #   "pipelined" — one launch per chunk over the destination
        #                 port's StagingRing (chunk k's kernel runs
        #                 while chunk k+1's launch stages; per-chunk
        #                 rpcz stamps show the overlap),
        #   "pallas"    — the whole frame as ONE double-buffered Pallas
        #                 DMA kernel (explicit semaphores: stage k+1
        #                 pulls while stage k checksums and k-2 drains;
        #                 ops/transfer.device_copy_with_checksum_dma);
        #                 multi-segment frames additionally coalesce
        #                 into one stacked per-destination transmit,
        #   "off"       — whole-frame transmit (pre-chunking behavior).
        # bench.py's ici_pipeline_curve sweeps mode x chunk size and
        # applies the best measured config before the headline run.
        self.chunk_mode = "fused"
        self.chunk_bytes = DEVICE_CHUNK_BYTES

    @contextlib.contextmanager
    def delivery_burst(self):
        """Coalesce this thread's queued fabric deliveries: while the
        context is open, each destination port collects frames in a
        pending list and its completion queue wakes ONCE at close
        (engine.cpp flush_pending_burst read-cycle batching).  Inline
        deliveries are unaffected (they never wake anything).  Nested
        bursts join the outermost one.

        Do NOT block on a fabric response inside the burst — the
        request may be sitting in the un-flushed pending list."""
        if getattr(_BURST_TLS, "pending", None) is not None:
            yield  # nested: the outer burst flushes
            return
        pending: Dict[IciPort, List] = {}
        _BURST_TLS.pending = pending
        try:
            yield
        finally:
            _BURST_TLS.pending = None
            for port, items in pending.items():
                port._flush_burst(items)

    def send_batch(
        self,
        frames,
        dst: Tuple[int, int],
        src: Tuple[int, int],
        zero_copy: Optional[bool] = None,
        ignore_eovercrowded: bool = False,
    ) -> List[int]:
        """Ship several frames to one destination with amortized
        window/credit bookkeeping: per-frame placement and admission
        semantics are identical to ``send``, but the destination's
        completion queue wakes once for the whole batch.  Returns one
        rc per frame (a frame that faults mid-batch fails alone — its
        window credits never linger)."""
        with self.delivery_burst():
            return [
                self.send(
                    f, dst, src, zero_copy=zero_copy,
                    ignore_eovercrowded=ignore_eovercrowded,
                )
                for f in frames
            ]

    def register(self, coords: Tuple[int, int], server=None, device=None) -> IciPort:
        with self._lock:
            if coords in self._ports and not self._ports[coords].closed:
                raise ValueError(f"ici coords {coords} already registered")
            port = IciPort(self, coords, server=server, device=device)
            self._ports[coords] = port
            return port

    def unregister(self, coords: Tuple[int, int]):
        with self._lock:
            port = self._ports.pop(coords, None)
        if port is not None:
            port.close()

    def port(self, coords: Tuple[int, int]) -> Optional[IciPort]:
        port = self._ports.get(coords)
        return port if port is not None and not port.closed else None

    def send(
        self,
        frame: IOBuf,
        dst: Tuple[int, int],
        src: Tuple[int, int],
        zero_copy: Optional[bool] = None,
        _local_only: bool = False,
        ignore_eovercrowded: bool = False,
    ) -> int:
        """Ship a frame. Device segments are re-placed onto the dst
        device if it differs (jax.device_put = the ICI/DCN hop);
        same-device segments traverse HBM through the Pallas transmit
        op unless zero_copy — then they move by reference. Coords not
        registered in this process route over the DCN bridge
        (parallel/dcn.py), the RDMA-TCP-bootstrap analog."""
        dst_port = self.port(dst)
        if dst_port is None:
            if not _local_only:
                from incubator_brpc_tpu.parallel.dcn import get_bridge

                route = get_bridge().route(dst)
                if route is not None:
                    # the DCN bridge records its own collective leg span
                    rc = route.send_frame(frame, dst, src)
                    if rc == 0:
                        socket_mod.g_out_bytes << len(frame)
                        socket_mod.g_out_messages << 1
                    return rc
            return errors.EFAILEDSOCKET
        close_after_deliver = False
        if _chaos.armed:
            spec = _chaos.check("ici.send", peer=_LazyPeer(dst))
            if spec is not None:
                act = spec.action
                if act == "drop":
                    # the leg silently vanishes (an in-flight hop lost
                    # on the fabric): callers recover via deadlines
                    return 0
                if act == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif act == "reset":
                    return errors.EFAILEDSOCKET
                elif act == "close_mid_batch":
                    # deliver THIS frame, then close the destination
                    # port so its completion-queue drain observes the
                    # close mid-batch (the receive-window release path)
                    close_after_deliver = True
        # rpcz collective sub-span: one ICI leg (placement + delivery),
        # parented to the active RPC span so fan-out traces show every
        # per-chip hop (skipped entirely outside a traced RPC)
        leg = Span.create_collective("ici", f"{_fmt(src)}->{_fmt(dst)}")
        if leg is not None:
            leg.request_size = len(frame)
        try:
            try:
                if dst_port.device is not None:
                    zc = self.zero_copy if zero_copy is None else zero_copy
                    self._place_segments(frame, dst_port, zc, leg)
            except BaseException as e:
                # close the leg with an error first: the trace must
                # show the hop that failed, not silently lose it
                if leg is not None:
                    leg.end(errors.EINTERNAL)
                if isinstance(e, Exception):
                    # a fault mid-placement (chunk k of a chunked
                    # pipeline, a bad dtype, an injected ici.chunk
                    # reset) happens BEFORE any window credit is
                    # reserved — deliver has not run — so failing the
                    # frame here surfaces ONE ERPC error to the sender
                    # and leaks nothing
                    log_error("ici send %s->%s failed: %r", src, dst, e)
                    return errors.EINTERNAL
                raise
            if not _local_only:
                # bridged inbound frames (_local_only) are RECEIVED
                # traffic; counting them here would inflate the
                # outbound metrics
                socket_mod.g_out_bytes << len(frame)
                socket_mod.g_out_messages << 1
            try:
                delivered = dst_port.deliver(
                    frame, src, inline_ok=not _local_only,
                    force=ignore_eovercrowded,
                )
            except BaseException:
                # deliver may have reserved window credits before the
                # failure (a raising spawn leaves the frame queued for
                # the close-time drain) — do NOT relabel this as a
                # clean per-frame EINTERNAL; propagate so the anomaly
                # stays loud
                if leg is not None:
                    leg.end(errors.EINTERNAL)
                raise
        finally:
            # an injected close must happen however delivery went
            # (success, window-full, raise): the spec's hit budget is
            # already consumed, so skipping here would record a close
            # that never happened
            if close_after_deliver:
                dst_port.close()
        if not delivered:
            # distinguish WHY delivery was refused: a closed port (or
            # its stopped completion queue) is a dead destination and
            # must read as a connection failure, not as transient
            # receive-window backpressure — retry/circuit-breaker
            # accounting keys on the difference
            rc = (
                errors.EFAILEDSOCKET
                if dst_port.closed
                else errors.EOVERCROWDED
            )
            if leg is not None:
                leg.end(rc)
            return rc
        if leg is not None:
            leg.end(0)
        return 0

    def local_server_coords(self):
        """Server ports registered in THIS process (what the DCN hello
        advertises to peers)."""
        with self._lock:
            items = list(self._ports.items())
        return sorted(
            coords
            for coords, port in items
            if not port.closed
            and port.server is not None
            and isinstance(coords[0], int)
            and isinstance(coords[1], int)
        )

    def server_coords(self):
        """Every reachable server port: local ones plus those learned
        over DCN bridges (the tpu:// naming service reads this, so a
        cross-process cluster resolves like a local one)."""
        coords = set(self.local_server_coords())
        from incubator_brpc_tpu.parallel.dcn import _bridge

        if _bridge is not None:
            coords.update(
                c
                for c in _bridge.remote_server_coords()
                if isinstance(c[0], int) and isinstance(c[1], int)
            )
        return sorted(coords)

    def routable(self, coords) -> bool:
        """True if coords are a local port or reachable over a bridge."""
        if self.port(coords) is not None:
            return True
        from incubator_brpc_tpu.parallel.dcn import _bridge

        return _bridge is not None and _bridge.route(coords) is not None

    def _place_segments(self, frame: IOBuf, dst_port: IciPort,
                        zero_copy: bool, leg=None):
        import jax

        device = dst_port.device
        same_chip: List[Tuple] = []  # (ref, arr) headed for transmit
        for ref in frame.device_segments():
            arr = ref.whole_array()
            if arr is None:
                continue  # split segment: materialized as bytes downstream
            src_devs = getattr(arr, "devices", lambda: set())()
            if device not in src_devs:
                with kernel_section("ici.place"):
                    ref.array = jax.device_put(arr, device)
                # in-flight ledger: the placed payload is the frame's
                # HBM until the carrying ref dies (receiver adoption —
                # e.g. the cache store — charges its own tag)
                charged = _INFLIGHT_ACCT.adopt(ref.array)
                if charged:
                    weakref.finalize(ref, _INFLIGHT_ACCT.release, charged)
            elif not zero_copy:
                same_chip.append((ref, arr))
        if len(same_chip) > 1 and self.chunk_mode == "pallas":
            # per-destination stacked transmit: same-shape segments of
            # ONE frame (a DMSET bulk, a fan-out leg's tensor set)
            # coalesce into a single stacked DMA kernel dispatch —
            # the bulk-move collective lowering (docs/ici_pipeline.md)
            same_chip = self._transmit_stacked(same_chip, dst_port, leg)
        for ref, arr in same_chip:
            # same-chip hop: the payload traverses HBM once through
            # the fused copy+checksum kernel — receiver gets a fresh
            # buffer plus a device-resident integrity checksum
            ref.array, ref.csum = self._transmit_segment(
                arr, dst_port, leg
            )

    def _transmit_stacked(self, pairs, dst_port: IciPort, leg):
        """Coalesce a frame's same-(shape, dtype) device segments into
        one stacked Pallas DMA transmit per group — one kernel dispatch
        moves every segment headed to this destination, and each ref
        gets its row back as a lazy device slice.  Integrity is at
        stack granularity: ONE checksum per collective step (the
        bulk-move contract; per-ref ``csum`` stays None).  Segments the
        stack can't take (off-TPU, non-numeric, untileable, singleton
        shapes) return for the per-segment path."""
        import jax.numpy as jnp

        from incubator_brpc_tpu.ops.transfer import (
            _on_tpu,
            chunk_plan_for,
            device_copy_with_checksum_pallas,
        )

        rest: List[Tuple] = []
        groups: Dict[Tuple, List[Tuple]] = {}
        for ref, arr in pairs:
            if _on_tpu(arr) and jnp.issubdtype(arr.dtype, jnp.number):
                key = (tuple(arr.shape), str(arr.dtype))
                groups.setdefault(key, []).append((ref, arr))
            else:
                rest.append((ref, arr))
        for grp in groups.values():
            if len(grp) < 2:
                rest.extend(grp)
                continue
            stacked = jnp.stack([a for _, a in grp])
            plan = chunk_plan_for(stacked, self.chunk_bytes)
            if plan[0] is None:
                rest.extend(grp)
                continue
            if _chaos.armed:
                # same pre-dispatch walk as the fused/pallas frame path
                self._chaos_walk_chunks(len(plan[2] or ()), dst_port)
            with kernel_section("ici.pallas"):
                out, _stack_csum = device_copy_with_checksum_pallas(
                    stacked, self.chunk_bytes, plan=plan
                )
            for i, (ref, _) in enumerate(grp):
                ref.array = out[i]
                ref.csum = None  # integrity rides the stack checksum
            ici_pallas_frames << 1
            ici_pallas_bytes << int(stacked.nbytes)
            ici_pallas_stacked_frames << 1
            ici_pallas_stacked_segments << len(grp)
            if leg is not None:
                leg.annotate(
                    f"pallas stacked transmit: {len(grp)} segments, "
                    f"one dispatch"
                )
        return rest

    def _transmit_segment(self, arr, dst_port: IciPort, leg):
        """One device segment through the transmit op, per the fabric's
        chunk policy (docs/ici_pipeline.md)."""
        from incubator_brpc_tpu.ops.transfer import (
            chunk_plan_for,
            transmit_array,
            transmit_array_chunked,
        )

        mode = self.chunk_mode
        if (
            mode == "off"
            or int(arr.nbytes) < MIN_CHUNKS * self.chunk_bytes
        ):
            return transmit_array(arr)
        if mode == "pipelined":
            return self._transmit_pipelined(arr, dst_port, leg)
        if mode == "pallas":
            return self._transmit_pallas(arr, dst_port, leg)
        plan = None
        if _chaos.armed:
            # the fused pipeline is ONE compiled program, so the
            # per-chunk ici.chunk site is walked over the SAME plan
            # before dispatch: a FaultPlan targeting chunk k faults the
            # frame under either chunk mode, with identical traversal
            # indices (chunk_plan_for is the one plan source)
            plan = chunk_plan_for(arr, self.chunk_bytes)
            self._chaos_walk_chunks(len(plan[2] or ()), dst_port)
        return transmit_array_chunked(arr, self.chunk_bytes, plan=plan)

    @staticmethod
    def _chaos_walk_chunks_step(k: int, total_chunks: int, dst_port: IciPort):
        """One consult of the ici.chunk site (armed plans only).
        reset abandons the frame mid-stream — send() turns it into ONE
        ERPC error, and no window credit was reserved yet, so nothing
        leaks (regression-tested under a seeded FaultPlan); delay_us
        stretches one pipeline stage."""
        spec = _chaos.check("ici.chunk", peer=_LazyPeer(dst_port.coords))
        if spec is not None:
            if spec.action == "delay_us":
                _chaos.sleep_us(spec.arg)
            elif spec.action == "reset":
                raise ConnectionResetError(
                    f"chaos: ici chunk {k}/{total_chunks} reset"
                )

    @staticmethod
    def _chaos_walk_chunks(total_chunks: int, dst_port: IciPort):
        """Walk every planned chunk through the ici.chunk site — the
        fused mode's pre-dispatch equivalent of the pipelined mode's
        inline per-chunk consults (identical traversal indices)."""
        for k in range(total_chunks):
            IciFabric._chaos_walk_chunks_step(k, total_chunks, dst_port)

    def _transmit_pallas(self, arr, dst_port: IciPort, leg):
        """Whole-frame transmit as ONE double-buffered Pallas DMA
        kernel (ops/transfer.device_copy_with_checksum_dma): explicit
        in/out DMA semaphores overlap stage k+1's HBM→VMEM pull with
        stage k's checksum and stage k-2's VMEM→HBM drain — no
        per-chunk launch gap, no emitter round trips.  Rides the same
        segmentation plan as the other modes (chunk_plan_for — chaos
        traversal indices agree), and opportunistically donates a
        frame-shaped StagingRing slot so callers that recycle response
        buffers (``dst_port.staging.release``) get allocation-free
        steady state.  Off-TPU (tests, JAX_PLATFORMS=cpu) the Mosaic
        kernel can't run: the lane falls back to the legacy transmit —
        the interpret flavor exists for tier-1 coverage, not the data
        plane (platform gate, counted in rpc_ici_pallas_fallbacks)."""
        import jax.numpy as jnp

        from incubator_brpc_tpu.ops.transfer import (
            _on_tpu,
            chunk_plan_for,
            device_copy_with_checksum_dma,
            device_copy_with_checksum_dma_into,
            pallas_stage_rows,
            transmit_array,
        )

        shape = arr.shape
        v, block_rows, chunks = chunk_plan_for(arr, self.chunk_bytes)
        if v is None:
            ici_pallas_fallbacks << 1
            return transmit_array(arr)
        total_chunks = len(chunks or ())
        if _chaos.armed:
            # ONE compiled program per frame, so the per-chunk
            # ici.chunk site walks the SAME plan pre-dispatch — the
            # fused-mode discipline, identical traversal indices.
            # Walked BEFORE the platform gate: off-TPU fallback frames
            # stay chaos-covered, exactly like fused/pipelined mode
            self._chaos_walk_chunks(total_chunks, dst_port)
        if not (_on_tpu(arr) and jnp.issubdtype(arr.dtype, jnp.number)):
            ici_pallas_fallbacks << 1
            return transmit_array(arr)
        stage_rows = pallas_stage_rows(v, block_rows)
        slot = dst_port.staging.acquire(v.shape, v.dtype)
        with kernel_section("ici.pallas"):
            if slot is not None:
                try:
                    out, csum = device_copy_with_checksum_dma_into(
                        v, slot, block_rows, stage_rows
                    )
                except Exception:  # noqa: BLE001 — donation quirk:
                    # allocate instead; the slot is consumed either way
                    out, csum = device_copy_with_checksum_dma(
                        v, block_rows, stage_rows
                    )
            else:
                out, csum = device_copy_with_checksum_dma(
                    v, block_rows, stage_rows
                )
        ici_pallas_frames << 1
        ici_pallas_bytes << int(arr.nbytes)
        if leg is not None:
            leg.chunk_mark("ici", 0, 1, int(arr.nbytes))
        return (out.reshape(shape) if out.shape != shape else out), csum

    def _transmit_pipelined(self, arr, dst_port: IciPort, leg):
        """Launch-per-chunk transmit: chunk k's copy+checksum kernel
        runs on device while the host stages chunk k+1's launch and
        chunk k-1's staging slot recycles through the destination
        port's StagingRing.  The lane accumulator chains through the
        chunks, so the receiver still verifies ONE integrity value for
        the whole frame (and it equals the whole-frame checksum
        bit-for-bit).  Falls back to the whole-frame op for shapes the
        kernel doesn't tile."""
        import jax
        import jax.numpy as jnp

        from incubator_brpc_tpu.ops.transfer import (
            _on_tpu,
            chunk_plan_for,
            device_copy_with_checksum_chunk,
            device_copy_with_checksum_chunk_into,
            fold_checksum,
            transmit_array,
        )

        shape = arr.shape
        x, block_rows, chunks = chunk_plan_for(arr, self.chunk_bytes)
        if x is None:
            return transmit_array(arr)  # untileable: whole-frame path
        if len(chunks) < MIN_CHUNKS:
            return transmit_array(arr)
        m, n = x.shape
        row_bytes = n * jnp.dtype(x.dtype).itemsize
        # off-TPU (tests, JAX_PLATFORMS=cpu) the Mosaic kernel can't
        # run: the pipeline orchestration is identical but each chunk
        # is an XLA copy and no checksum accumulates (matching the
        # whole-frame off-TPU behavior)
        use_csum = _on_tpu(x) and jnp.issubdtype(x.dtype, jnp.number)
        acc = jnp.zeros((1, n), jnp.float32) if use_csum else None
        ring = dst_port.staging if use_csum else None
        outs = []
        total_chunks = len(chunks)
        if ring is not None:
            # a frame holds every chunk output until the end-of-frame
            # concat, so zero-alloc steady state needs a slot per chunk
            # in flight — grow the ring to this frame's chunk count
            # (bounded: 2 x the default 64MB/8MB plan)
            ring.depth = max(ring.depth, min(total_chunks, 16))
        for k, (off, rows) in enumerate(chunks):
            if _chaos.armed:
                self._chaos_walk_chunks_step(k, total_chunks, dst_port)
            # device-time attribution: one dispatch window per chunk
            # launch (the pipeline's overlap unit)
            with kernel_section("ici.chunk"):
                xc = jax.lax.slice_in_dim(x, off, off + rows)
                if use_csum:
                    slot = ring.acquire((rows, n), x.dtype)
                    if slot is not None:
                        try:
                            oc, acc = device_copy_with_checksum_chunk_into(
                                xc, acc, slot, block_rows
                            )
                        except Exception:  # noqa: BLE001 — donation quirk:
                            # fall back to the allocating kernel, drop slot
                            oc, acc = device_copy_with_checksum_chunk(
                                xc, acc, block_rows
                            )
                    else:
                        oc, acc = device_copy_with_checksum_chunk(
                            xc, acc, block_rows
                        )
                else:
                    oc = jnp.array(xc, copy=True)
            outs.append(oc)
            if leg is not None:
                leg.chunk_mark("ici", k, total_chunks, rows * row_bytes)
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
        if ring is not None and len(outs) > 1:
            # the concat copied the chunk outputs out of the staging
            # slots — they are free to recycle.  (With a single chunk
            # `out` IS the slot-backed array and now belongs to the
            # receiver: never recycle it.)
            for oc in outs:
                ring.release(oc)
        csum = fold_checksum(acc) if use_csum else None
        return (out.reshape(shape) if out.shape != shape else out), csum


_fabric: Optional[IciFabric] = None
_fabric_lock = threading.Lock()


def get_fabric() -> IciFabric:
    global _fabric
    if _fabric is None:
        with _fabric_lock:
            if _fabric is None:
                _fabric = IciFabric()
    return _fabric


import itertools as _itertools
import os as _os

_client_port_seq = _itertools.count(1)


def acquire_client_port(device=None) -> IciPort:
    """Register a uniquely-keyed client port (shared helper for
    Channel and LoadBalancerWithNaming). Keys carry the pid so client
    ports of DIFFERENT processes bridged to one server can't collide in
    its DCN reply-routing table."""
    return get_fabric().register(
        ("client", f"{_os.getpid()}-{next(_client_port_seq)}"),
        server=None,
        device=device,
    )
