/* _fastcall — CPython extension wrapper over the engine's blocking mux
 * RPC (engine.cpp nc_mux_call).
 *
 * Why not ctypes: the sync Python user API is GIL-throughput-bound.
 * Every microsecond of per-call GIL-held work caps aggregate qps at
 * 1s/that (ctypes argument marshalling + NcResponse bookkeeping is
 * ~3-5us -> ~100k qps hard ceiling before any real work).  This module
 * does the same call in ~0.3us of GIL-held time: METH_FASTCALL (no
 * args tuple), direct PyBytes pointer access, one PyTuple result, and
 * the GIL released across the whole blocking round trip.
 *
 * The engine's entry points are injected as raw addresses at setup()
 * (resolved by ctypes from the already-loaded _engine.so) so this
 * module needs no link-time dependency on the engine.
 *
 * Reference parity: the public CallMethod IS the native hot path in
 * the reference (channel.cpp:407-584); this restores that property for
 * Python callers.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* mirror of engine.cpp's NcResponse (C ABI) */
typedef struct {
  uint8_t *data;
  uint64_t body_len;
  uint64_t attachment_size;
  int32_t error_code;
  int32_t compress_type;
  char error_text[240];
} NcResponse;

/* mirror of engine.cpp's MuxCompletion (C ABI) */
typedef struct {
  uint64_t tag;
  int32_t rc;
  int32_t error_code;
  int32_t compress_type;
  uint32_t attachment_size;
  uint64_t body_len;
  uint8_t *data;
  char error_text[96];
} MuxCompletion;

typedef int (*nc_mux_call_fn)(void *h, const char *service,
                              size_t service_len, const char *method,
                              size_t method_len, uint64_t log_id,
                              const uint8_t *payload, uint64_t payload_len,
                              const uint8_t *attachment,
                              uint64_t attachment_len, int timeout_ms,
                              NcResponse *out);
typedef uint64_t (*nc_mux_submit_fn)(void *h, const char *service,
                                     const char *method, uint64_t log_id,
                                     const uint8_t *payload,
                                     uint64_t payload_len,
                                     const uint8_t *attachment,
                                     uint64_t attachment_len, int timeout_ms,
                                     uint64_t tag);
typedef int (*nc_mux_poll_fn)(void *h, MuxCompletion *out, int max_n,
                              int timeout_ms);
typedef int (*nc_mux_submit_many_fn)(void *h, const char *service,
                                     const char *method, uint64_t log_id,
                                     const uint8_t *const *payloads,
                                     const uint64_t *lens, int n,
                                     int timeout_ms, uint64_t tag_base);
typedef int (*nc_mux_harvest_fn)(void *h, MuxCompletion *out, int max_n,
                                 int timeout_ms);
typedef int (*ns_send_burst_fn)(void *h, uint64_t conn_id,
                                const uint8_t *const *frames,
                                const uint64_t *lens, int n);

static nc_mux_call_fn g_mux_call = NULL;
static nc_mux_submit_fn g_mux_submit = NULL;
static nc_mux_poll_fn g_mux_poll = NULL;
static nc_mux_submit_many_fn g_mux_submit_many = NULL;
static nc_mux_harvest_fn g_mux_harvest = NULL;
static ns_send_burst_fn g_srv_send_burst = NULL;

/* One-deep per-thread freelist for mux_call's 6-tuple result — the
 * same trick CPython's zip()/enumerate() use: if the caller dropped
 * its reference (refcount back to 1, ours), no live reference exists
 * and the tuple can be refilled in place instead of allocated.  The
 * sync fast path calls this once per RPC, so the tuple alloc/free pair
 * is pure per-call overhead when the caller unpacks and discards. */
static _Thread_local PyObject *result_cache;

/* Build (or refill) the result tuple from 6 NEW references. */
static PyObject *result_tuple(PyObject *items[6]) {
  PyObject *t = result_cache;
  int i;
  if (t != NULL && Py_REFCNT(t) == 1) {
    for (i = 0; i < 6; i++) {
      PyObject *old = PyTuple_GET_ITEM(t, i);
      PyTuple_SET_ITEM(t, i, items[i]);
      Py_XDECREF(old);
    }
    Py_INCREF(t);
    return t;
  }
  t = PyTuple_New(6);
  if (t == NULL) {
    for (i = 0; i < 6; i++) Py_DECREF(items[i]);
    return NULL;
  }
  for (i = 0; i < 6; i++) PyTuple_SET_ITEM(t, i, items[i]);
  Py_XDECREF(result_cache);
  result_cache = t;
  Py_INCREF(t);
  return t;
}

static PyObject *setup(PyObject *self, PyObject *args) {
  unsigned long long a_call, a_submit, a_poll;
  unsigned long long a_submit_many = 0, a_harvest = 0, a_srv_burst = 0;
  if (!PyArg_ParseTuple(args, "KKK|KKK", &a_call, &a_submit, &a_poll,
                        &a_submit_many, &a_harvest, &a_srv_burst))
    return NULL;
  g_mux_call = (nc_mux_call_fn)(uintptr_t)a_call;
  g_mux_submit = (nc_mux_submit_fn)(uintptr_t)a_submit;
  g_mux_poll = (nc_mux_poll_fn)(uintptr_t)a_poll;
  g_mux_submit_many = (nc_mux_submit_many_fn)(uintptr_t)a_submit_many;
  g_mux_harvest = (nc_mux_harvest_fn)(uintptr_t)a_harvest;
  g_srv_send_burst = (ns_send_burst_fn)(uintptr_t)a_srv_burst;
  Py_RETURN_NONE;
}

/* mux_call(handle, service, method, payload, attachment, timeout_ms,
 *          log_id) -> (rc, body|None, att_size, error_code,
 *                      error_text|None, compress_type)
 * handle: int (MuxClient*); service/method/payload/attachment: bytes.
 */
static PyObject *mux_call(PyObject *self, PyObject *const *args,
                          Py_ssize_t nargs) {
  if (nargs != 7) {
    PyErr_SetString(PyExc_TypeError, "mux_call expects 7 args");
    return NULL;
  }
  if (g_mux_call == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  PyObject *svc = args[1], *meth = args[2], *pay = args[3], *att = args[4];
  if (!PyBytes_CheckExact(svc) || !PyBytes_CheckExact(meth) ||
      !PyBytes_CheckExact(pay) || !PyBytes_CheckExact(att)) {
    PyErr_SetString(PyExc_TypeError,
                    "service/method/payload/attachment must be bytes");
    return NULL;
  }
  long timeout_ms = PyLong_AsLong(args[5]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  unsigned long long log_id = PyLong_AsUnsignedLongLong(args[6]);
  if (log_id == (unsigned long long)-1 && PyErr_Occurred()) return NULL;

  NcResponse resp;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = g_mux_call(
      h, PyBytes_AS_STRING(svc), (size_t)PyBytes_GET_SIZE(svc),
      PyBytes_AS_STRING(meth), (size_t)PyBytes_GET_SIZE(meth),
      (uint64_t)log_id, (const uint8_t *)PyBytes_AS_STRING(pay),
      (uint64_t)PyBytes_GET_SIZE(pay),
      (const uint8_t *)PyBytes_AS_STRING(att),
      (uint64_t)PyBytes_GET_SIZE(att), (int)timeout_ms, &resp);
  Py_END_ALLOW_THREADS

  if (rc != 0) {
    /* transport error: no body */
    PyObject *items[6];
    items[0] = PyLong_FromLong(rc);
    Py_INCREF(Py_None);
    items[1] = Py_None;
    items[2] = PyLong_FromLong(0);
    items[3] = PyLong_FromLong(0);
    Py_INCREF(Py_None);
    items[4] = Py_None;
    items[5] = PyLong_FromLong(0);
    return result_tuple(items);
  }
  PyObject *body =
      PyBytes_FromStringAndSize((const char *)resp.data, (Py_ssize_t)resp.body_len);
  if (resp.data) free(resp.data); /* same-process heap: plain free */
  if (body == NULL) return NULL;
  PyObject *etext;
  if (resp.error_code != 0) {
    etext = PyUnicode_DecodeUTF8(resp.error_text, strlen(resp.error_text),
                                 "replace");
    if (etext == NULL) {
      Py_DECREF(body);
      return NULL;
    }
  } else {
    etext = Py_None;
    Py_INCREF(etext);
  }
  PyObject *items[6];
  items[0] = PyLong_FromLong(0);
  items[1] = body;
  items[2] = PyLong_FromUnsignedLongLong(resp.attachment_size);
  items[3] = PyLong_FromLong(resp.error_code);
  items[4] = etext;
  items[5] = PyLong_FromLong(resp.compress_type);
  return result_tuple(items);
}

/* mux_submit(handle, service, method, payload, attachment, timeout_ms,
 *            log_id, tag) -> cid (0 = shutdown/backlogged)
 * Enqueue one async RPC; the C reactor batches staged submissions from
 * all threads into single writes. */
static PyObject *mux_submit(PyObject *self, PyObject *const *args,
                            Py_ssize_t nargs) {
  if (nargs != 8) {
    PyErr_SetString(PyExc_TypeError, "mux_submit expects 8 args");
    return NULL;
  }
  if (g_mux_submit == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  PyObject *svc = args[1], *meth = args[2], *pay = args[3], *att = args[4];
  if (!PyBytes_CheckExact(svc) || !PyBytes_CheckExact(meth) ||
      !PyBytes_CheckExact(pay) || !PyBytes_CheckExact(att)) {
    PyErr_SetString(PyExc_TypeError,
                    "service/method/payload/attachment must be bytes");
    return NULL;
  }
  long timeout_ms = PyLong_AsLong(args[5]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  unsigned long long log_id = PyLong_AsUnsignedLongLong(args[6]);
  if (log_id == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
  unsigned long long tag = PyLong_AsUnsignedLongLong(args[7]);
  if (tag == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
  /* Deliberately KEEP the GIL: the submit is ~1us of staging, and a
   * release here invites an OS switch to the harvester thread and back
   * on every call — two context switches per RPC on a single core.
   * Holding through keeps the submitter's timeslice intact so the GIL
   * changes hands per completion BATCH instead. */
  uint64_t cid = g_mux_submit(
      h, PyBytes_AS_STRING(svc), PyBytes_AS_STRING(meth), (uint64_t)log_id,
      (const uint8_t *)PyBytes_AS_STRING(pay),
      (uint64_t)PyBytes_GET_SIZE(pay),
      (const uint8_t *)PyBytes_AS_STRING(att),
      (uint64_t)PyBytes_GET_SIZE(att), (int)timeout_ms, (uint64_t)tag);
  return PyLong_FromUnsignedLongLong(cid);
}

#define POLL_BATCH 128

/* ---- submission/completion ring (io_uring-style vectorized calls) ---- */

#define RING_WINDOW_MAX 1024

/* mux_submit_many(handle, service, method, payloads, timeout_ms, log_id,
 *                 tag_base) -> staged count (k < len(payloads) means
 * slots k.. were NOT staged; the caller fails them)
 * payloads: list of bytes, one same-method request body per slot.  ONE
 * Python→C crossing stages the whole window (engine nc_mux_submit_many:
 * one lock pass, one staging append, one reactor wake).  The GIL is
 * RELEASED across the staging copy — a 128×64KB window is ~8MB of
 * memcpy, far past the keep-the-GIL threshold mux_submit sits under.
 * Each payload is INCREF'd across the release so a concurrent list
 * mutation cannot free a body mid-copy. */
static PyObject *mux_submit_many(PyObject *self, PyObject *const *args,
                                 Py_ssize_t nargs) {
  if (nargs != 7) {
    PyErr_SetString(PyExc_TypeError, "mux_submit_many expects 7 args");
    return NULL;
  }
  if (g_mux_submit_many == NULL) {
    PyErr_SetString(PyExc_RuntimeError,
                    "fastcall.setup() missing submit_many address");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  PyObject *svc = args[1], *meth = args[2], *payloads = args[3];
  if (!PyBytes_CheckExact(svc) || !PyBytes_CheckExact(meth)) {
    PyErr_SetString(PyExc_TypeError, "service/method must be bytes");
    return NULL;
  }
  if (!PyList_CheckExact(payloads)) {
    PyErr_SetString(PyExc_TypeError, "payloads must be a list of bytes");
    return NULL;
  }
  long timeout_ms = PyLong_AsLong(args[4]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  unsigned long long log_id = PyLong_AsUnsignedLongLong(args[5]);
  if (log_id == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
  unsigned long long tag_base = PyLong_AsUnsignedLongLong(args[6]);
  if (tag_base == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
  Py_ssize_t n = PyList_GET_SIZE(payloads);
  if (n <= 0) return PyLong_FromLong(0);
  if (n > RING_WINDOW_MAX) {
    PyErr_SetString(PyExc_ValueError, "window exceeds RING_WINDOW_MAX");
    return NULL;
  }
  static _Thread_local const uint8_t *ptrs[RING_WINDOW_MAX];
  static _Thread_local uint64_t lens[RING_WINDOW_MAX];
  static _Thread_local PyObject *held[RING_WINDOW_MAX];
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *b = PyList_GET_ITEM(payloads, i);
    if (!PyBytes_CheckExact(b)) {
      for (Py_ssize_t j = 0; j < i; j++) Py_DECREF(held[j]);
      PyErr_SetString(PyExc_TypeError, "payloads must be a list of bytes");
      return NULL;
    }
    Py_INCREF(b);
    held[i] = b;
    ptrs[i] = (const uint8_t *)PyBytes_AS_STRING(b);
    lens[i] = (uint64_t)PyBytes_GET_SIZE(b);
  }
  int staged;
  Py_BEGIN_ALLOW_THREADS
  staged = g_mux_submit_many(h, PyBytes_AS_STRING(svc),
                             PyBytes_AS_STRING(meth), (uint64_t)log_id, ptrs,
                             lens, (int)n, (int)timeout_ms,
                             (uint64_t)tag_base);
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; i++) Py_DECREF(held[i]);
  return PyLong_FromLong(staged);
}

/* srv_send_burst(handle, conn_id, frames) -> rc
 * Server response ring: flush one harvested window of response frames
 * for a native connection as ONE writev burst (engine ns_send_burst —
 * the server half of mux_submit_many).  frames: list of bytes, one
 * serialized tpu_std response frame per slot.  Each frame is INCREF'd
 * across the GIL release so a concurrent mutation cannot free bytes
 * the engine is still reading (the engine copies any unsent remainder
 * before returning, so nothing is borrowed past the call). */
static PyObject *srv_send_burst(PyObject *self, PyObject *const *args,
                                Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError,
                    "srv_send_burst expects (handle, conn_id, frames)");
    return NULL;
  }
  if (g_srv_send_burst == NULL) {
    PyErr_SetString(PyExc_RuntimeError,
                    "fastcall.setup() missing srv_send_burst address");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  unsigned long long conn_id = PyLong_AsUnsignedLongLong(args[1]);
  if (conn_id == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
  PyObject *frames = args[2];
  if (!PyList_CheckExact(frames)) {
    PyErr_SetString(PyExc_TypeError, "frames must be a list of bytes");
    return NULL;
  }
  Py_ssize_t n = PyList_GET_SIZE(frames);
  if (n <= 0) return PyLong_FromLong(0);
  if (n > RING_WINDOW_MAX) {
    PyErr_SetString(PyExc_ValueError, "window exceeds RING_WINDOW_MAX");
    return NULL;
  }
  static _Thread_local const uint8_t *ptrs[RING_WINDOW_MAX];
  static _Thread_local uint64_t lens[RING_WINDOW_MAX];
  static _Thread_local PyObject *held[RING_WINDOW_MAX];
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *b = PyList_GET_ITEM(frames, i);
    if (!PyBytes_CheckExact(b)) {
      for (Py_ssize_t j = 0; j < i; j++) Py_DECREF(held[j]);
      PyErr_SetString(PyExc_TypeError, "frames must be a list of bytes");
      return NULL;
    }
    Py_INCREF(b);
    held[i] = b;
    ptrs[i] = (const uint8_t *)PyBytes_AS_STRING(b);
    lens[i] = (uint64_t)PyBytes_GET_SIZE(b);
  }
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = g_srv_send_burst(h, (uint64_t)conn_id, ptrs, lens, (int)n);
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; i++) Py_DECREF(held[i]);
  return PyLong_FromLong(rc);
}

/* mux_harvest(handle, timeout_ms, ring) -> n
 * Harvest up to min(len(ring), 128) RING-lane completions into the
 * PREALLOCATED completion ring: ring is a list of 7-slot lists the
 * caller reuses across harvests, so the steady state allocates only
 * the per-field ints/bytes, never the containers.  Slot layout matches
 * mux_poll's tuples: [tag, rc, body|None, att_size, error_code,
 * error_text|None, compress_type]. */
static PyObject *mux_harvest(PyObject *self, PyObject *const *args,
                             Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError,
                    "mux_harvest expects (handle, timeout_ms, ring)");
    return NULL;
  }
  if (g_mux_harvest == NULL) {
    PyErr_SetString(PyExc_RuntimeError,
                    "fastcall.setup() missing harvest address");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  long timeout_ms = PyLong_AsLong(args[1]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  PyObject *ring = args[2];
  if (!PyList_CheckExact(ring)) {
    PyErr_SetString(PyExc_TypeError, "ring must be a list of 7-slot lists");
    return NULL;
  }
  Py_ssize_t depth = PyList_GET_SIZE(ring);
  int max_n = depth < POLL_BATCH ? (int)depth : POLL_BATCH;
  static _Thread_local MuxCompletion comps[POLL_BATCH];
  int n;
  Py_BEGIN_ALLOW_THREADS
  n = g_mux_harvest(h, comps, max_n, (int)timeout_ms);
  Py_END_ALLOW_THREADS
  for (int i = 0; i < n; i++) {
    MuxCompletion *c = &comps[i];
    PyObject *slot = PyList_GET_ITEM(ring, i);
    if (!PyList_CheckExact(slot) || PyList_GET_SIZE(slot) < 7) {
      PyErr_SetString(PyExc_TypeError, "ring slots must be 7-slot lists");
      goto fail;
    }
    PyObject *body, *etext;
    if (c->rc == 0) {
      body = PyBytes_FromStringAndSize((const char *)c->data,
                                       (Py_ssize_t)c->body_len);
    } else {
      body = Py_None;
      Py_INCREF(body);
    }
    if (c->data) {
      free(c->data);
      c->data = NULL;
    }
    if (body == NULL) goto fail;
    if (c->error_code != 0) {
      etext = PyUnicode_DecodeUTF8(c->error_text, strlen(c->error_text),
                                   "replace");
      if (etext == NULL) {
        Py_DECREF(body);
        goto fail;
      }
    } else {
      etext = Py_None;
      Py_INCREF(etext);
    }
    /* PyList_SetItem steals the new ref and releases the old slot */
    PyList_SetItem(slot, 0, PyLong_FromUnsignedLongLong(c->tag));
    PyList_SetItem(slot, 1, PyLong_FromLong(c->rc));
    PyList_SetItem(slot, 2, body);
    PyList_SetItem(slot, 3, PyLong_FromUnsignedLong(c->attachment_size));
    PyList_SetItem(slot, 4, PyLong_FromLong(c->error_code));
    PyList_SetItem(slot, 5, etext);
    PyList_SetItem(slot, 6, PyLong_FromLong(c->compress_type));
  }
  return PyLong_FromLong(n);
fail:
  for (int i = 0; i < n; i++) {
    if (comps[i].data) {
      free(comps[i].data);
      comps[i].data = NULL;
    }
  }
  return NULL;
}

/* mux_poll(handle, timeout_ms) -> list of
 *   (tag, rc, body|None, att_size, error_code, error_text|None, ctype)
 * Harvest up to 128 completions in one GIL-held pass: the tuples are
 * built in C, bodies become bytes and are freed inline. */
static PyObject *mux_poll(PyObject *self, PyObject *const *args,
                          Py_ssize_t nargs) {
  if (nargs != 2) {
    PyErr_SetString(PyExc_TypeError, "mux_poll expects (handle, timeout_ms)");
    return NULL;
  }
  if (g_mux_poll == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  long timeout_ms = PyLong_AsLong(args[1]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  static _Thread_local MuxCompletion comps[POLL_BATCH];
  int n;
  Py_BEGIN_ALLOW_THREADS
  n = g_mux_poll(h, comps, POLL_BATCH, (int)timeout_ms);
  Py_END_ALLOW_THREADS
  PyObject *list = PyList_New(n > 0 ? n : 0);
  if (list == NULL) goto fail;
  for (int i = 0; i < n; i++) {
    MuxCompletion *c = &comps[i];
    PyObject *body, *etext;
    if (c->rc == 0) {
      body = PyBytes_FromStringAndSize((const char *)c->data,
                                       (Py_ssize_t)c->body_len);
    } else {
      body = Py_None;
      Py_INCREF(body);
    }
    if (c->data) {
      free(c->data);
      c->data = NULL;
    }
    if (body == NULL) goto fail;
    if (c->error_code != 0) {
      etext = PyUnicode_DecodeUTF8(c->error_text, strlen(c->error_text),
                                   "replace");
      if (etext == NULL) {
        Py_DECREF(body);
        goto fail;
      }
    } else {
      etext = Py_None;
      Py_INCREF(etext);
    }
    PyObject *t = PyTuple_New(7);
    if (t == NULL) {
      Py_DECREF(body);
      Py_DECREF(etext);
      goto fail;
    }
    PyTuple_SET_ITEM(t, 0, PyLong_FromUnsignedLongLong(c->tag));
    PyTuple_SET_ITEM(t, 1, PyLong_FromLong(c->rc));
    PyTuple_SET_ITEM(t, 2, body);
    PyTuple_SET_ITEM(t, 3, PyLong_FromUnsignedLong(c->attachment_size));
    PyTuple_SET_ITEM(t, 4, PyLong_FromLong(c->error_code));
    PyTuple_SET_ITEM(t, 5, etext);
    PyTuple_SET_ITEM(t, 6, PyLong_FromLong(c->compress_type));
    PyList_SET_ITEM(list, i, t);
  }
  return list;
fail:
  /* free any bodies not yet converted so the malloc'd responses can't
   * leak on an allocation failure mid-batch */
  for (int i = 0; i < n; i++) {
    if (comps[i].data) {
      free(comps[i].data);
      comps[i].data = NULL;
    }
  }
  Py_XDECREF(list);
  return NULL;
}

/* mux_call_fast — same wire call as mux_call, leaner result contract:
 * the common shape (transport ok, no app error, no attachment, no
 * compression) returns the body BYTES directly — no 6-tuple to build,
 * refill, or unpack per call.  Anything else returns the same 6-tuple
 * as mux_call so the caller's slow path stays shared. */
static PyObject *mux_call_fast(PyObject *self, PyObject *const *args,
                               Py_ssize_t nargs) {
  if (nargs != 7) {
    PyErr_SetString(PyExc_TypeError, "mux_call_fast expects 7 args");
    return NULL;
  }
  if (g_mux_call == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  PyObject *svc = args[1], *meth = args[2], *pay = args[3], *att = args[4];
  if (!PyBytes_CheckExact(svc) || !PyBytes_CheckExact(meth) ||
      !PyBytes_CheckExact(pay) || !PyBytes_CheckExact(att)) {
    PyErr_SetString(PyExc_TypeError,
                    "service/method/payload/attachment must be bytes");
    return NULL;
  }
  long timeout_ms = PyLong_AsLong(args[5]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  unsigned long long log_id = PyLong_AsUnsignedLongLong(args[6]);
  if (log_id == (unsigned long long)-1 && PyErr_Occurred()) return NULL;

  NcResponse resp;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = g_mux_call(
      h, PyBytes_AS_STRING(svc), (size_t)PyBytes_GET_SIZE(svc),
      PyBytes_AS_STRING(meth), (size_t)PyBytes_GET_SIZE(meth),
      (uint64_t)log_id, (const uint8_t *)PyBytes_AS_STRING(pay),
      (uint64_t)PyBytes_GET_SIZE(pay),
      (const uint8_t *)PyBytes_AS_STRING(att),
      (uint64_t)PyBytes_GET_SIZE(att), (int)timeout_ms, &resp);
  Py_END_ALLOW_THREADS

  if (rc == 0 && resp.error_code == 0 && resp.attachment_size == 0 &&
      resp.compress_type == 0) {
    PyObject *body = PyBytes_FromStringAndSize((const char *)resp.data,
                                               (Py_ssize_t)resp.body_len);
    if (resp.data) free(resp.data);
    return body;
  }
  if (rc != 0) {
    PyObject *items[6];
    items[0] = PyLong_FromLong(rc);
    Py_INCREF(Py_None);
    items[1] = Py_None;
    items[2] = PyLong_FromLong(0);
    items[3] = PyLong_FromLong(0);
    Py_INCREF(Py_None);
    items[4] = Py_None;
    items[5] = PyLong_FromLong(0);
    return result_tuple(items);
  }
  PyObject *body = PyBytes_FromStringAndSize((const char *)resp.data,
                                             (Py_ssize_t)resp.body_len);
  if (resp.data) free(resp.data);
  if (body == NULL) return NULL;
  PyObject *etext;
  if (resp.error_code != 0) {
    etext = PyUnicode_DecodeUTF8(resp.error_text, strlen(resp.error_text),
                                 "replace");
    if (etext == NULL) {
      Py_DECREF(body);
      return NULL;
    }
  } else {
    etext = Py_None;
    Py_INCREF(etext);
  }
  PyObject *items[6];
  items[0] = PyLong_FromLong(0);
  items[1] = body;
  items[2] = PyLong_FromUnsignedLongLong(resp.attachment_size);
  items[3] = PyLong_FromLong(resp.error_code);
  items[4] = etext;
  items[5] = PyLong_FromLong(resp.compress_type);
  return result_tuple(items);
}

/* mux_poll_dispatch(handle, timeout_ms, cb) -> n
 * Harvest one batch and dispatch each completion from C:
 *   cb(tag, rc, body|None, att_size, error_code, error_text|None, ctype)
 * The per-completion list/tuple of mux_poll disappears — Python is
 * entered once per completion, for the dispatch itself (the user done
 * code).  A raising cb is reported via sys.unraisablehook and the
 * batch continues: one bad done() must not kill the harvester. */
static PyObject *mux_poll_dispatch(PyObject *self, PyObject *const *args,
                                   Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError,
                    "mux_poll_dispatch expects (handle, timeout_ms, cb)");
    return NULL;
  }
  if (g_mux_poll == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  if (h == NULL && PyErr_Occurred()) return NULL;
  long timeout_ms = PyLong_AsLong(args[1]);
  if (timeout_ms == -1 && PyErr_Occurred()) return NULL;
  PyObject *cb = args[2];
  static _Thread_local MuxCompletion comps[POLL_BATCH];
  int n;
  Py_BEGIN_ALLOW_THREADS
  n = g_mux_poll(h, comps, POLL_BATCH, (int)timeout_ms);
  Py_END_ALLOW_THREADS
  for (int i = 0; i < n; i++) {
    MuxCompletion *c = &comps[i];
    PyObject *argv[7];
    argv[0] = PyLong_FromUnsignedLongLong(c->tag);
    argv[1] = PyLong_FromLong(c->rc);
    if (c->rc == 0) {
      argv[2] = PyBytes_FromStringAndSize((const char *)c->data,
                                          (Py_ssize_t)c->body_len);
    } else {
      argv[2] = Py_None;
      Py_INCREF(Py_None);
    }
    if (c->data) {
      free(c->data);
      c->data = NULL;
    }
    argv[3] = PyLong_FromUnsignedLong(c->attachment_size);
    argv[4] = PyLong_FromLong(c->error_code);
    if (c->error_code != 0) {
      argv[5] = PyUnicode_DecodeUTF8(c->error_text, strlen(c->error_text),
                                     "replace");
    } else {
      argv[5] = Py_None;
      Py_INCREF(Py_None);
    }
    argv[6] = PyLong_FromLong(c->compress_type);
    int bad = 0;
    for (int j = 0; j < 7; j++) bad |= argv[j] == NULL;
    if (bad) {
      for (int j = 0; j < 7; j++) Py_XDECREF(argv[j]);
      for (int k = i + 1; k < n; k++) {
        if (comps[k].data) {
          free(comps[k].data);
          comps[k].data = NULL;
        }
      }
      return NULL;
    }
    PyObject *r = PyObject_Vectorcall(cb, argv, 7, NULL);
    if (r == NULL) {
      PyErr_WriteUnraisable(cb);
    } else {
      Py_DECREF(r);
    }
    for (int j = 0; j < 7; j++) Py_DECREF(argv[j]);
  }
  return PyLong_FromLong(n);
}

static PyMethodDef methods[] = {
    {"setup", setup, METH_VARARGS,
     "setup(nc_mux_call_addr) — inject the engine entry point"},
    {"mux_call", (PyCFunction)mux_call, METH_FASTCALL,
     "blocking mux RPC, GIL released for the round trip"},
    {"mux_call_fast", (PyCFunction)mux_call_fast, METH_FASTCALL,
     "blocking mux RPC; common shape returns body bytes directly"},
    {"mux_submit", (PyCFunction)mux_submit, METH_FASTCALL,
     "enqueue one async RPC on the mux reactor"},
    {"mux_poll", (PyCFunction)mux_poll, METH_FASTCALL,
     "harvest a batch of completions as tuples"},
    {"mux_poll_dispatch", (PyCFunction)mux_poll_dispatch, METH_FASTCALL,
     "harvest a batch and invoke cb per completion from C"},
    {"mux_submit_many", (PyCFunction)mux_submit_many, METH_FASTCALL,
     "stage a window of same-method RPCs in one crossing"},
    {"mux_harvest", (PyCFunction)mux_harvest, METH_FASTCALL,
     "harvest ring-lane completions into a preallocated ring"},
    {"srv_send_burst", (PyCFunction)srv_send_burst, METH_FASTCALL,
     "flush one window of server response frames as one writev burst"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastcall",
    "low-overhead blocking RPC over the native mux reactor", -1, methods};

PyMODINIT_FUNC PyInit__fastcall(void) { return PyModule_Create(&moduledef); }
