"""Native transport engine bindings (ctypes over engine.cpp).

The engine is the C++ analog of the reference's core IO loops
(input_messenger.cpp:317-382, socket.cpp:1584-1790): an epoll server
whose framing/dispatch cycle never touches the GIL, with a built-in
native echo fast path and a Python callback for everything else, plus a
pooled-connection client whose round trips run with the GIL released.

Compiled on demand with g++ (cached as _engine.so next to this file);
``available()`` gates every caller so environments without a toolchain
degrade to the pure-Python transport.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "engine.cpp")
_FC_SRC = os.path.join(_HERE, "fastcall.c")

# Sanitizer build modes (tools/sanitize.sh drives these): the env var
# selects instrumented flags AND a distinct .so name, so sanitized and
# plain artifacts cache side by side.  Loading an ASan/TSan .so into a
# stock CPython additionally needs the runtime preloaded — see
# sanitizer_preload(); without it dlopen fails and available() degrades
# to the pure-Python transport exactly like a missing toolchain.
SANITIZE = os.environ.get("BRPC_NATIVE_SANITIZE", "").strip().lower()
_SAN_FLAGS = {
    "": [],
    # O1 keeps stacks honest; no-recover makes every UBSan hit fatal so
    # the test lane cannot pass over a diagnosed issue
    "asan": [
        "-fsanitize=address,undefined",
        "-fno-sanitize-recover=undefined",
        "-fno-omit-frame-pointer",
        "-g",
        "-O1",
    ],
    "tsan": ["-fsanitize=thread", "-fno-omit-frame-pointer", "-g", "-O1"],
}
if SANITIZE not in _SAN_FLAGS:
    raise RuntimeError(
        f"BRPC_NATIVE_SANITIZE={SANITIZE!r}: expected one of "
        f"{sorted(k for k in _SAN_FLAGS if k)} or unset"
    )
_SUFFIX = f".{SANITIZE}" if SANITIZE else ""
_SO = os.path.join(_HERE, f"_engine{_SUFFIX}.so")
_FC_SO = os.path.join(_HERE, f"_fastcall{_SUFFIX}.so")


def sanitizer_preload(mode: Optional[str] = None) -> Optional[str]:
    """The LD_PRELOAD value a subprocess needs to load the engine
    sanitized under `mode` (defaults to this process's SANITIZE):
    colon-separated runtime libs, or None when not sanitizing or the
    toolchain lacks ANY of the required runtimes — every component is
    existence-checked so a toolchain with libasan but no libubsan is a
    loud None, not a lane that silently loses its native coverage.
    tools/sanitize.sh and the tier-1 ASan smoke both resolve their
    preload through here (single source of truth)."""
    mode = SANITIZE if mode is None else mode
    if not mode:
        return None
    libs = ["libasan.so", "libubsan.so"] if mode == "asan" else ["libtsan.so"]
    out = []
    for lib in libs:
        try:
            proc = subprocess.run(
                ["g++", f"-print-file-name={lib}"],
                capture_output=True, text=True, timeout=10,
            )
            path = proc.stdout.strip()
            if not path or os.path.sep not in path or not os.path.exists(path):
                return None  # this runtime is missing: the mode can't run
            out.append(path)
        except Exception:  # noqa: BLE001
            return None
    return ":".join(out)

_lib = None
_lib_err: Optional[str] = None
_fastcall = None  # CPython extension module (fastcall.c), or None
_build_lock = threading.Lock()

# Tag bit that routes a mux completion to the RING lane (engine.cpp
# kRingTagBit): ring windows harvest via nc_mux_harvest and must never
# be drained by the channel's background nc_mux_poll harvester.
RING_TAG_BIT = 1 << 63

# Hard per-window cap (fastcall.c RING_WINDOW_MAX / POLL_BATCH): the
# client ring chunks larger windows itself.
RING_WINDOW_MAX = 1024
RING_HARVEST_MAX = 128


class NcResponse(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("body_len", ctypes.c_uint64),
        ("attachment_size", ctypes.c_uint64),
        ("error_code", ctypes.c_int32),
        ("compress_type", ctypes.c_int32),
        ("error_text", ctypes.c_char * 240),
    ]


class MuxCompletion(ctypes.Structure):
    _fields_ = [
        ("tag", ctypes.c_uint64),
        ("rc", ctypes.c_int32),
        ("error_code", ctypes.c_int32),
        ("compress_type", ctypes.c_int32),
        ("attachment_size", ctypes.c_uint32),
        ("body_len", ctypes.c_uint64),
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("error_text", ctypes.c_char * 96),
    ]


class NcBenchResult(ctypes.Structure):
    _fields_ = [
        ("ok", ctypes.c_uint64),
        ("failed", ctypes.c_uint64),
        ("qps", ctypes.c_double),
        ("p50_us", ctypes.c_double),
        ("p99_us", ctypes.c_double),
        ("p999_us", ctypes.c_double),
        ("avg_us", ctypes.c_double),
    ]


DISPATCH_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint64
)

# ConnProto values (engine.cpp): which wire protocol a fallback frame
# arrived on — sniffed per connection from its first bytes
PROTO_TPU_STD = 1
PROTO_HTTP = 2
PROTO_REDIS = 3

# Generic native-method handler ABI (engine.cpp NativeMethodFn): return
# <0 declines the frame to the Python fallback, >=0 is the response
# error_code.  Response bytes go through resp_append_payload/attachment
# on the opaque resp_ctx.  Handlers may be real native pointers (zero
# GIL) or ctypes callbacks (generic but GIL-bound).
NATIVE_METHOD_FN = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,                 # user_data
    ctypes.POINTER(ctypes.c_uint8),  # req
    ctypes.c_uint64,                 # req_len
    ctypes.POINTER(ctypes.c_uint8),  # att
    ctypes.c_uint64,                 # att_len
    ctypes.c_void_p,                 # resp_ctx
)


def bench_echo(
    host: str,
    port: int,
    payload_len: int = 4096,
    concurrency: int = 8,
    duration_ms: int = 3000,
    depth: int = 1,
    conns: int = 1,
    service: str = "EchoService",
    method: str = "Echo",
) -> dict:
    """Native load generator (the rpc_press engine; the reference's
    tools/rpc_press is likewise native). depth>1 pipelines that many
    in-flight RPCs per worker over a mux client with `conns`
    connections."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native engine unavailable: {_lib_err}")
    res = NcBenchResult()
    _lib.nc_bench_echo(
        host.encode(), port, service.encode(), method.encode(),
        payload_len, concurrency, duration_ms, depth, conns,
        ctypes.byref(res),
    )
    return {
        "ok": res.ok,
        "failed": res.failed,
        "qps": round(res.qps, 1),
        "p50_us": res.p50_us,
        "p99_us": res.p99_us,
        "p999_us": res.p999_us,
        "avg_us": round(res.avg_us, 1),
    }


def bench_http(
    host: str,
    port: int,
    path: str = "/echo",
    payload_len: int = 4096,
    concurrency: int = 2,
    duration_ms: int = 2000,
    depth: int = 16,
) -> dict:
    """Native pipelined HTTP/1.1 load generator (keep-alive POSTs)."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native engine unavailable: {_lib_err}")
    res = NcBenchResult()
    _lib.nc_bench_http(
        host.encode(), port, path.encode(), payload_len, concurrency,
        duration_ms, depth, ctypes.byref(res),
    )
    return {
        "ok": res.ok, "failed": res.failed, "qps": round(res.qps, 1),
        "p50_us": res.p50_us, "p99_us": res.p99_us, "p999_us": res.p999_us,
        "avg_us": round(res.avg_us, 1),
    }


def bench_redis(
    host: str,
    port: int,
    value_len: int = 64,
    concurrency: int = 2,
    duration_ms: int = 2000,
    depth: int = 16,
) -> dict:
    """Native pipelined redis load generator (alternating SET/GET;
    each command counts as one op)."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native engine unavailable: {_lib_err}")
    res = NcBenchResult()
    _lib.nc_bench_redis(
        host.encode(), port, value_len, concurrency, duration_ms, depth,
        ctypes.byref(res),
    )
    return {
        "ok": res.ok, "failed": res.failed, "qps": round(res.qps, 1),
        "p50_us": res.p50_us, "p99_us": res.p99_us, "p999_us": res.p999_us,
        "avg_us": round(res.avg_us, 1),
    }


def _build() -> Optional[str]:
    """Compile engine.cpp → _engine.so if stale/missing; returns error."""
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(
            _SRC
        ):
            return None
        tmp = _SO + ".tmp"
        proc = subprocess.run(
            [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                *_SAN_FLAGS[SANITIZE],
                _SRC, "-o", tmp,
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        if proc.returncode != 0:
            return f"g++ failed: {proc.stderr[-800:]}"
        os.replace(tmp, _SO)
        return None
    except Exception as e:  # noqa: BLE001
        return f"build error: {e!r}"


def _build_fastcall() -> Optional[str]:
    """Compile fastcall.c → _fastcall.so (CPython extension).  Optional:
    callers fall back to ctypes when it's missing, so any failure just
    means the slower boundary."""
    try:
        if os.path.exists(_FC_SO) and os.path.getmtime(
            _FC_SO
        ) >= os.path.getmtime(_FC_SRC):
            return None
        import sysconfig

        inc = sysconfig.get_paths()["include"]
        tmp = _FC_SO + ".tmp"
        proc = subprocess.run(
            [
                "gcc", "-O2", "-shared", "-fPIC", f"-I{inc}",
                *_SAN_FLAGS[SANITIZE],
                _FC_SRC, "-o", tmp,
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        if proc.returncode != 0:
            return f"gcc failed: {proc.stderr[-400:]}"
        os.replace(tmp, _FC_SO)
        return None
    except Exception as e:  # noqa: BLE001
        return f"build error: {e!r}"


def _load_fastcall(lib) -> None:
    """Import the extension and inject the engine's nc_mux_call address
    (resolved from the already-loaded _engine.so — no link dependency)."""
    global _fastcall
    if _build_fastcall() is not None:
        return
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("_fastcall", _FC_SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.setup(
            ctypes.cast(lib.nc_mux_call, ctypes.c_void_p).value,
            ctypes.cast(lib.nc_mux_submit, ctypes.c_void_p).value,
            ctypes.cast(lib.nc_mux_poll, ctypes.c_void_p).value,
            ctypes.cast(lib.nc_mux_submit_many, ctypes.c_void_p).value,
            ctypes.cast(lib.nc_mux_harvest, ctypes.c_void_p).value,
            ctypes.cast(lib.ns_send_burst, ctypes.c_void_p).value,
        )
        _fastcall = mod
    except Exception:  # noqa: BLE001 — ctypes fallback covers it
        _fastcall = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return
        err = _build()
        if err is not None:
            _lib_err = err
            return
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _lib_err = f"dlopen failed: {e}"
            return
        lib.ns_create.restype = ctypes.c_void_p
        lib.ns_set_dispatch.argtypes = [ctypes.c_void_p, DISPATCH_CB]
        lib.ns_register_native_echo.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.ns_register_native_method.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            NATIVE_METHOD_FN, ctypes.c_void_p,
        ]
        lib.ns_resp_append_payload.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.ns_resp_append_attachment.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.ns_set_method_max_concurrency.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32,
        ]
        lib.ns_method_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.ns_method_stats.restype = ctypes.c_int
        lib.ns_listen.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.ns_listen.restype = ctypes.c_int
        lib.ns_set_fault.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_longlong,
        ]
        lib.ns_clear_faults.argtypes = []
        lib.ns_fault_hits.argtypes = [ctypes.c_int]
        lib.ns_fault_hits.restype = ctypes.c_uint64
        lib.ns_enable_protocols.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.ns_register_native_http.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, NATIVE_METHOD_FN,
            ctypes.c_void_p,
        ]
        lib.ns_register_native_http_echo.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
        ]
        lib.ns_redis_enable_native_kv.argtypes = [ctypes.c_void_p]
        lib.nc_bench_http.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(NcBenchResult),
        ]
        lib.nc_bench_http.restype = ctypes.c_int
        lib.nc_bench_redis.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(NcBenchResult),
        ]
        lib.nc_bench_redis.restype = ctypes.c_int
        lib.ns_send.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.ns_send.restype = ctypes.c_int
        lib.ns_send_burst.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        lib.ns_send_burst.restype = ctypes.c_int
        lib.ns_ring_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.ns_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ns_py_done.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ns_stop.argtypes = [ctypes.c_void_p]
        lib.ns_destroy.argtypes = [ctypes.c_void_p]
        lib.nc_pool_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.nc_pool_create.restype = ctypes.c_void_p
        lib.nc_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.nc_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.nc_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
            ctypes.POINTER(NcResponse),
        ]
        lib.nc_call.restype = ctypes.c_int
        lib.nc_mux_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.nc_mux_create.restype = ctypes.c_void_p
        lib.nc_mux_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
        ]
        lib.nc_mux_submit.restype = ctypes.c_uint64
        lib.nc_mux_poll.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MuxCompletion), ctypes.c_int,
            ctypes.c_int,
        ]
        lib.nc_mux_poll.restype = ctypes.c_int
        lib.nc_mux_submit_many.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64,
        ]
        lib.nc_mux_submit_many.restype = ctypes.c_int
        lib.nc_mux_harvest.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MuxCompletion), ctypes.c_int,
            ctypes.c_int,
        ]
        lib.nc_mux_harvest.restype = ctypes.c_int
        lib.nc_mux_ring_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.nc_mux_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.nc_mux_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_int, ctypes.POINTER(NcResponse),
        ]
        lib.nc_mux_call.restype = ctypes.c_int
        lib.nc_mux_destroy.argtypes = [ctypes.c_void_p]
        lib.nc_bench_echo.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(NcBenchResult),
        ]
        lib.nc_bench_echo.restype = ctypes.c_int
        _load_fastcall(lib)
        _lib = lib


def available() -> bool:
    _load()
    return _lib is not None


# ---- fault injection (chaos/), process-wide engine knobs ----
# Site ids / action codes mirror engine.cpp FaultSite / FaultAction;
# chaos/injector.py owns the name → id mapping.

def set_fault(site: int, action: int, arg: int, prob_u32: int, seed: int,
              max_hits: int = -1) -> None:
    """Program one native injection site (engine.cpp ns_set_fault).
    The decision is deterministic: fmix64(seed + n*golden) per traversal
    n, firing when the high 32 bits fall under prob_u32."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native engine unavailable: {_lib_err}")
    _lib.ns_set_fault(site, action, arg, prob_u32, seed, max_hits)


def clear_faults() -> None:
    _load()
    if _lib is not None:
        _lib.ns_clear_faults()


def fault_hits(site: int) -> int:
    _load()
    if _lib is None:
        return 0
    return int(_lib.ns_fault_hits(site))


def unavailable_reason() -> Optional[str]:
    _load()
    return _lib_err


class NativeServerEngine:
    """Owns one C++ server instance: listener + worker threads."""

    def __init__(self, nworkers: int = 4):
        _load()
        if _lib is None:
            raise RuntimeError(f"native engine unavailable: {_lib_err}")
        self._h = _lib.ns_create()
        self._nworkers = nworkers
        self._cb_ref = None  # keep the CFUNCTYPE alive
        self.port = 0
        self._stopped = False

    def set_dispatch(self, fn: Callable[[int, int, bytes], None]):
        """fn(conn_id, proto, frame_bytes) — called from engine worker
        threads for frames the native fast path doesn't handle.  proto
        is PROTO_TPU_STD / PROTO_HTTP / PROTO_REDIS."""

        def _trampoline(conn_id, proto, data, length):
            try:
                fn(conn_id, proto, ctypes.string_at(data, length))
            except Exception:  # noqa: BLE001 — never unwind into C
                pass

        self._cb_ref = DISPATCH_CB(_trampoline)
        _lib.ns_set_dispatch(self._h, self._cb_ref)

    def register_native_echo(self, service: str, method: str, attach_echo: bool):
        _lib.ns_register_native_echo(
            self._h, service.encode(), method.encode(), 1 if attach_echo else 0
        )

    def register_native_method(self, service: str, method: str, handler):
        """Generic native dispatch: `handler(user_data, req, req_len,
        att, att_len, resp_ctx)` returns <0 to decline (frame falls to
        the Python dispatch) or the response error_code (0 = ok).
        Accepts a raw C function pointer (zero-GIL) or a Python callable
        (wrapped in a ctypes callback: generic, GIL-bound).  Use
        resp_append_payload/resp_append_attachment to build the
        response.  Must be called before listen()."""
        if not isinstance(handler, NATIVE_METHOD_FN):
            py_handler = handler

            def _safe(ud, req, rl, att, al, ctx, _h=py_handler):
                # A raising Python handler must NOT look like success
                # (ctypes would return 0 and the engine would ship a
                # partial payload as ok): decline to the Python fallback
                try:
                    return _h(ud, req, rl, att, al, ctx)
                except Exception:  # noqa: BLE001 — never unwind into C
                    return -1

            handler = NATIVE_METHOD_FN(_safe)
        # keep callback objects alive for the engine's lifetime
        if not hasattr(self, "_method_refs"):
            self._method_refs = []
        self._method_refs.append(handler)
        _lib.ns_register_native_method(
            self._h, service.encode(), method.encode(), handler, None
        )

    @staticmethod
    def resp_append_payload(resp_ctx, data: bytes):
        _lib.ns_resp_append_payload(resp_ctx, data, len(data))

    @staticmethod
    def resp_append_attachment(resp_ctx, data: bytes):
        _lib.ns_resp_append_attachment(resp_ctx, data, len(data))

    def set_method_max_concurrency(self, service: str, method: str, limit: int):
        _lib.ns_set_method_max_concurrency(
            self._h, service.encode(), method.encode(), int(limit)
        )

    def method_stats(self, service: str, method: str):
        """Cumulative fast-path counters for a registered native method:
        {count, latency_ns_sum, rejected, errors}, or None if the method
        isn't native.  The server harvests deltas into MethodStatus so
        /status includes fast-path traffic."""
        out = (ctypes.c_uint64 * 4)()
        rc = _lib.ns_method_stats(
            self._h, service.encode(), method.encode(), out
        )
        if rc != 0:
            return None
        return {
            "count": out[0],
            "latency_ns_sum": out[1],
            "rejected": out[2],
            "errors": out[3],
        }

    def enable_protocols(self, *, http: bool = False, redis: bool = False):
        """Allow extra wire protocols on this port (sniffed per
        connection; tpu_std always on).  Call before listen()."""
        mask = 0
        if http:
            mask |= 1 << PROTO_HTTP
        if redis:
            mask |= 1 << PROTO_REDIS
        if mask:
            _lib.ns_enable_protocols(self._h, mask)

    def register_native_http_echo(self, path: str):
        """Serve `path` natively: response body = request body (the
        reference http_server example's trivial echo handler, in C)."""
        _lib.ns_register_native_http_echo(self._h, path.encode())

    def redis_enable_native_kv(self):
        """Answer GET/SET/DEL/EXISTS/INCR/PING from the engine's
        sharded in-memory KV; other commands still reach the Python
        RedisService.  The KV store lives in C — Python handlers do
        not see natively-stored keys."""
        _lib.ns_redis_enable_native_kv(self._h)

    def listen(self, port: int = 0, host: str = "0.0.0.0") -> int:
        rc = _lib.ns_listen(self._h, host.encode(), port, self._nworkers)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        self.port = rc
        return rc

    def send(self, conn_id: int, frame: bytes) -> int:
        if self._h is None or self._stopped:
            return -1
        return _lib.ns_send(self._h, conn_id, frame, len(frame))

    def send_burst(self, conn_id: int, frames) -> int:
        """Flush one harvested window of response frames for a
        connection as ONE writev burst (server response ring,
        ns_send_burst).  frames is a sequence of bytes objects; they
        are only borrowed for the duration of the call."""
        if self._h is None or self._stopped:
            return -1
        n = len(frames)
        if n == 0:
            return 0
        if n == 1:
            return _lib.ns_send(self._h, conn_id, frames[0], len(frames[0]))
        fc = _fastcall
        if fc is not None:
            burst = getattr(fc, "srv_send_burst", None)
            if burst is not None:
                if not isinstance(frames, list):
                    frames = list(frames)
                return burst(self._h, conn_id, frames)
        ptrs = (ctypes.c_char_p * n)(*frames)
        lens = (ctypes.c_uint64 * n)(*[len(f) for f in frames])
        return _lib.ns_send_burst(self._h, conn_id, ptrs, lens, n)

    def ring_stats(self):
        """Server response-ring step log: {windows, responses,
        flush_bursts}.  Counts, never timing — windows counts
        send_burst flushes, flush_bursts counts writev bursts (native
        read cycles + ring flushes)."""
        out = (ctypes.c_uint64 * 3)()
        _lib.ns_ring_stats(self._h, out)
        return {
            "windows": out[0],
            "responses": out[1],
            "flush_bursts": out[2],
        }

    def close_conn(self, conn_id: int):
        if self._h is None or self._stopped:
            return
        _lib.ns_close_conn(self._h, conn_id)

    def py_done(self, conn_id: int):
        """Signal that Python answered one dispatched http/redis
        frame: the engine resumes cutting/reading the connection.
        MUST be called exactly once per PROTO_HTTP/PROTO_REDIS
        dispatch, or the connection stays paused forever."""
        if self._h is None or self._stopped:
            return
        _lib.ns_py_done(self._h, conn_id)

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        _lib.ns_stop(self._h)

    def destroy(self):
        # stop only — the C object is deliberately NOT freed: late
        # Python fallback tasks may still hold this engine and call
        # send()/close_conn() concurrently, and ns_stop already released
        # every heavy resource (threads, epoll fds, connections). The
        # handful of bytes left per server lifetime is the safe trade.
        self.stop()


class NativeClientPool:
    """Pooled-connection client: one in-flight RPC per fd, GIL released
    for the whole round trip (the pooled connection_type of
    channel.h:84-89, natively).

    Channel's sync path now rides NativeMuxClient.call_blocking (many
    callers multiplexed over few connections); this pool remains the
    exclusive-fd primitive — simpler isolation semantics, used by tests
    and available to tools that want one-request-per-connection."""

    def __init__(self, host: str, port: int, connect_timeout_ms: int = 3000):
        _load()
        if _lib is None:
            raise RuntimeError(f"native engine unavailable: {_lib_err}")
        self._h = _lib.nc_pool_create(host.encode(), port, connect_timeout_ms)
        self.host = host
        self.port = port
        self._tls = threading.local()  # per-thread NcResponse reuse
        self._call = _lib.nc_call
        self._free = _lib.nc_free

    def call(
        self,
        service,
        method,
        payload: bytes,
        attachment: bytes = b"",
        timeout_ms: int = -1,
        log_id: int = 0,
    ):
        """→ (rc, body_bytes, attachment_size, error_code, error_text).
        rc 0 = transport ok (error_code may still be an app error).
        service/method accept str or pre-encoded bytes (hot path)."""
        tls = self._tls
        resp = getattr(tls, "resp", None)
        if resp is None:
            resp = tls.resp = NcResponse()
            tls.ref = ctypes.byref(resp)
        rc = self._call(
            self._h,
            service if isinstance(service, bytes) else service.encode(),
            method if isinstance(method, bytes) else method.encode(),
            log_id,
            payload,
            len(payload),
            attachment,
            len(attachment),
            timeout_ms,
            tls.ref,
        )
        if rc != 0:
            return rc, b"", 0, 0, "", 0
        try:
            body = ctypes.string_at(resp.data, resp.body_len)
        finally:
            if resp.data:
                self._free(resp.data)
        ec = resp.error_code
        return (
            0,
            body,
            resp.attachment_size,
            ec,
            resp.error_text.decode("utf-8", "replace") if ec else "",
            resp.compress_type,
        )

    def destroy(self):
        if self._h:
            _lib.nc_pool_destroy(self._h)
            self._h = None


class NativeMuxClient:
    """Multiplexed async client: many in-flight RPCs over a few
    connections, submissions batched into single writes by a C++
    reactor, completions harvested in batches by one Python thread.
    The async-CallMethod data path (reference: done!=NULL CallMethod)."""

    def __init__(self, host: str, port: int, nconns: int = 2):
        _load()
        if _lib is None:
            raise RuntimeError(f"native engine unavailable: {_lib_err}")
        self._h = _lib.nc_mux_create(host.encode(), port, nconns)
        # tag allocation + pending registry are lock-free: itertools
        # .count's __next__ and single dict ops are atomic under the
        # GIL, and registration strictly precedes submission so the
        # harvester's pop always finds its entry
        import itertools

        self._pending = {}  # tag -> (handler, ctx) | legacy closure
        self._tag_iter = itertools.count(1)
        # ring tags need BLOCK reservation (tag_base..tag_base+n-1), so
        # unlike _tag_iter they take a small lock; the lock is per
        # window, not per call
        self._ring_lock = threading.Lock()
        self._ring_next = 1
        # cross-ring routing: all SubmissionRings on this mux share ONE
        # C-side completion lane, so a ring harvesting the lane may pull
        # a sibling ring's completion — it parks the tuple here (under
        # _ring_lock) for the owner's next harvest instead of dropping
        # it.  _ring_zombie holds tags whose slot a drain backstop
        # already failed: their late completions are discarded.
        self._ring_stash = {}
        self._ring_zombie = set()
        # leader/follower harvest: only ONE ring blocks in the C lane
        # at a time (holder of _ring_harvest_lock); the others wait on
        # _ring_stash_cv, which the leader notifies whenever it parks a
        # sibling's completion — without this, a follower would sit out
        # the leader's full harvest timeout with its results already in
        # the stash
        self._ring_harvest_lock = threading.Lock()
        self._ring_stash_cv = threading.Condition(self._ring_lock)
        self._stop = False
        # fast paths: the C extension's entry points if built (≈0.3us
        # GIL-held per call), else prebound ctypes fallbacks
        self._fc_call = _fastcall.mux_call if _fastcall is not None else None
        self._fc_submit = (
            _fastcall.mux_submit if _fastcall is not None else None
        )
        self._ct_call = _lib.nc_mux_call
        self._tls = threading.local()  # per-thread NcResponse (ctypes path)
        self._harvester = threading.Thread(
            target=self._harvest_loop, daemon=True, name="nc-mux-harvest"
        )
        self._harvester.start()

    def fast_call_entry(self):
        """The leanest callable for one sync RPC — signature
        (service, method, payload, attachment, timeout_ms, log_id).
        With the extension built this is mux_call_fast pre-bound to the
        reactor handle via functools.partial (C-level __call__, no
        Python frame): it returns the response body BYTES directly for
        the common shape and the 6-tuple otherwise.  Without the
        extension it is the ctypes call_blocking wrapper (tuple only —
        callers type-check for bytes, so both contracts compose)."""
        if self._fc_call is not None:
            import functools

            fast = getattr(_fastcall, "mux_call_fast", None)
            return functools.partial(
                fast if fast is not None else self._fc_call, self._h
            )
        return self.call_blocking

    def call_blocking(
        self,
        service: bytes,
        method: bytes,
        payload: bytes,
        attachment: bytes = b"",
        timeout_ms: int = -1,
        log_id: int = 0,
    ):
        """One SYNC RPC multiplexed over the reactor: the calling thread
        parks in C on a per-call waiter with the GIL released, so many
        sync callers share a few connections and their submissions batch
        into single writes.  → (rc, body|None, att_size, error_code,
        error_text|None, compress_type)."""
        fc = self._fc_call
        if fc is not None:
            return fc(
                self._h, service, method, payload, attachment, timeout_ms,
                log_id,
            )
        tls = self._tls
        resp = getattr(tls, "resp", None)
        if resp is None:
            resp = tls.resp = NcResponse()
            tls.ref = ctypes.byref(resp)
        rc = self._ct_call(
            self._h, service, len(service), method, len(method), log_id,
            payload, len(payload), attachment, len(attachment), timeout_ms,
            tls.ref,
        )
        if rc != 0:
            return rc, None, 0, 0, None, 0
        try:
            body = ctypes.string_at(resp.data, resp.body_len)
        finally:
            if resp.data:
                _lib.nc_free(resp.data)
        ec = resp.error_code
        return (
            0,
            body,
            resp.attachment_size,
            ec,
            resp.error_text.decode("utf-8", "replace") if ec else None,
            resp.compress_type,
        )

    def submit(
        self,
        service,
        method,
        payload: bytes,
        attachment: bytes,
        timeout_ms: int,
        on_complete,
        log_id: int = 0,
    ) -> bool:
        """on_complete(rc, body, att_size, error_code, error_text,
        compress_type) runs on the harvester thread."""
        tag = next(self._tag_iter)
        self._pending[tag] = on_complete
        cid = _lib.nc_mux_submit(
            self._h,
            service if isinstance(service, bytes) else service.encode(),
            method if isinstance(method, bytes) else method.encode(),
            log_id,
            payload,
            len(payload),
            attachment,
            len(attachment),
            timeout_ms,
            tag,
        )
        if not cid:
            self._pending.pop(tag, None)
            return False
        return True

    def submit_ctx(
        self,
        service: bytes,
        method: bytes,
        payload: bytes,
        attachment: bytes,
        timeout_ms: int,
        log_id: int,
        handler,
        ctx,
    ) -> bool:
        """Closure-free async submit: on completion the harvester calls
        ``handler(ctx, rc, body, att_size, ec, etext, ctype)``.  handler
        should be a stable bound method; ctx carries the per-call state
        (one tuple/list instead of two closures — the per-call GIL cost
        is what bounds aggregate qps)."""
        tag = next(self._tag_iter)
        self._pending[tag] = (handler, ctx)
        fc = self._fc_submit
        if fc is not None:
            cid = fc(
                self._h, service, method, payload, attachment, timeout_ms,
                log_id, tag,
            )
        else:
            cid = _lib.nc_mux_submit(
                self._h, service, method, log_id, payload, len(payload),
                attachment, len(attachment), timeout_ms, tag,
            )
        if not cid:
            self._pending.pop(tag, None)
            return False
        return True

    def _poll_batch_ctypes(self):
        """ctypes fallback for the extension's mux_poll: one batch of
        completions normalized to the SAME tuple shape, so the harvest
        loop has exactly one dispatch implementation."""
        batch = getattr(self, "_ct_batch", None)
        if batch is None:
            batch = self._ct_batch = (MuxCompletion * 128)()
        n = _lib.nc_mux_poll(self._h, batch, 128, 200)
        out = []
        for i in range(n):
            c = batch[i]
            body = None
            if c.data:
                try:
                    if c.rc == 0:
                        body = ctypes.string_at(c.data, c.body_len)
                finally:
                    _lib.nc_free(c.data)
            etext = (
                c.error_text.decode("utf-8", "replace")
                if c.error_code
                else None
            )
            out.append(
                (c.tag, c.rc, body, c.attachment_size, c.error_code,
                 etext, c.compress_type)
            )
        return out

    # ---- submission/completion ring (io_uring-style windows) ----

    def reserve_ring_tags(self, n: int) -> int:
        """Reserve a contiguous block of n ring-lane tags; returns
        tag_base (RING_TAG_BIT set — the engine routes these completions
        to the ring queue, invisible to the background harvester)."""
        with self._ring_lock:
            base = self._ring_next
            self._ring_next += n
        return RING_TAG_BIT | base

    def submit_window(
        self,
        service: bytes,
        method: bytes,
        payloads,
        timeout_ms: int,
        log_id: int,
        tag_base: int,
    ) -> int:
        """Stage a window of same-method calls in ONE boundary crossing
        (extension mux_submit_many; ctypes array fallback).  Returns the
        number staged — k < len(payloads) means slots k.. were NOT
        staged and the caller must fail them."""
        fc = _fastcall
        if fc is not None and hasattr(fc, "mux_submit_many"):
            return fc.mux_submit_many(
                self._h, service, method, payloads, timeout_ms, log_id,
                tag_base,
            )
        n = len(payloads)
        ptrs = (ctypes.c_char_p * n)(*payloads)
        lens = (ctypes.c_uint64 * n)(*[len(p) for p in payloads])
        return _lib.nc_mux_submit_many(
            self._h, service, method, log_id, ptrs, lens, n, timeout_ms,
            tag_base,
        )

    def harvest_window(self, timeout_ms: int, ring) -> int:
        """Harvest up to min(len(ring), 128) ring-lane completions into
        the caller's PREALLOCATED ring (list of 7-slot lists), blocking
        up to timeout_ms for the first.  Slot layout: [tag, rc,
        body|None, att_size, error_code, error_text|None, ctype]."""
        fc = _fastcall
        if fc is not None and hasattr(fc, "mux_harvest"):
            return fc.mux_harvest(self._h, timeout_ms, ring)
        batch = getattr(self, "_ct_ring_batch", None)
        if batch is None:
            batch = self._ct_ring_batch = (MuxCompletion * RING_HARVEST_MAX)()
        max_n = min(len(ring), RING_HARVEST_MAX)
        n = _lib.nc_mux_harvest(self._h, batch, max_n, timeout_ms)
        for i in range(n):
            c = batch[i]
            body = None
            if c.data:
                try:
                    if c.rc == 0:
                        body = ctypes.string_at(c.data, c.body_len)
                finally:
                    _lib.nc_free(c.data)
            slot = ring[i]
            slot[0] = c.tag
            slot[1] = c.rc
            slot[2] = body
            slot[3] = c.attachment_size
            slot[4] = c.error_code
            slot[5] = (
                c.error_text.decode("utf-8", "replace")
                if c.error_code
                else None
            )
            slot[6] = c.compress_type
        return n

    def ring_stats(self):
        """C-side ring step-log counters: {windows, calls, harvests,
        completions}.  A degraded ring (one crossing per call) shows as
        windows ≈ calls — the bench smoke guard asserts on these."""
        out = (ctypes.c_uint64 * 4)()
        _lib.nc_mux_ring_stats(self._h, out)
        return {
            "windows": out[0],
            "calls": out[1],
            "harvests": out[2],
            "completions": out[3],
        }

    def stats(self):
        """Cumulative sync-call stats kept by the C reactor client:
        {ok, latency_us_sum, latency_us_max, fail}.  latency_us_max is
        windowed — reading it resets the C-side max to 0.  The channel's
        LatencyRecorder harvests deltas of these lazily so the sync
        fast path does zero per-call recorder work in Python."""
        out = (ctypes.c_uint64 * 4)()
        _lib.nc_mux_stats(self._h, out)
        return {
            "ok": out[0],
            "latency_us_sum": out[1],
            "latency_us_max": out[2],
            "fail": out[3],
        }

    def _dispatch_completion(self, tag, rc, body, att_size, ec, etext,
                             ctype):
        """One completion, called from C (mux_poll_dispatch) or from the
        ctypes poll loop.  Exceptions are contained by the caller."""
        cb = self._pending.pop(tag, None)
        if cb is None:
            return
        if type(cb) is tuple:  # (handler, ctx) submit_ctx
            cb[0](cb[1], rc, body, att_size, ec, etext, ctype)
        else:  # legacy closure from submit()
            cb(rc, body if body is not None else b"", att_size, ec,
               etext if etext is not None else "", ctype)

    def _harvest_loop(self):
        fc = _fastcall
        if fc is not None and hasattr(fc, "mux_poll_dispatch"):
            # completion dispatch stays in C: one Python entry per
            # completion (the dispatch itself), no per-batch list and
            # no per-completion tuple.  A raising done() is reported
            # via sys.unraisablehook by the extension and the batch
            # continues.
            h = self._h
            _poll = fc.mux_poll_dispatch
            dispatch = self._dispatch_completion
            while not self._stop:
                _poll(h, 200, dispatch)
            return
        poll = self._poll_batch_ctypes
        while not self._stop:
            for comp in poll():
                try:
                    self._dispatch_completion(*comp)
                except Exception:  # noqa: BLE001 — user done() must
                    pass  # not kill the harvester

    def destroy(self):
        if self._stop:
            return
        self._stop = True
        if threading.current_thread() is self._harvester:
            # called from a done callback: joining ourselves would raise
            # and leak the C reactor — hand cleanup to a helper thread
            threading.Thread(
                target=self._destroy_from_outside, daemon=True
            ).start()
            return
        self._destroy_from_outside()

    def _destroy_from_outside(self):
        self._harvester.join(timeout=2)
        if self._h:
            _lib.nc_mux_destroy(self._h)
            self._h = None
