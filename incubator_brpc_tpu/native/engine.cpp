// Native transport engine — the C++ hot path for the tpu_std wire.
//
// Analog of the reference's C++ core loops: InputMessenger::OnNewMessages
// (input_messenger.cpp:317-382, read+cut+dispatch) and Socket::StartWrite/
// KeepWrite (socket.cpp:1584-1790).  The reference is C++ end to end; this
// engine restores that property for the framing/IO cycle so the Python
// layer above (services, combos, observability) rides a native data path:
//
//   * server: N worker threads, each owning an epoll set; connections are
//     assigned round-robin at accept.  Frames are cut and, for methods
//     registered as native-echo, answered entirely in C++ (no GIL).  All
//     other frames are handed to a Python dispatch callback (the ctypes
//     layer re-acquires the GIL only for those).
//   * client: a connection pool with blocking call/response round trips;
//     the meta protobuf is packed/parsed here so Python touches only the
//     user payload bytes.  One in-flight RPC per pooled fd — the pooled
//     connection type (channel.h:84-89, GetPooledSocket analog).
//
// Wire format (protocols/tpu_std.py): b"TRPC" u32(meta_size) u32(body_size)
// then RpcMeta pb then body (payload + attachment).  The tiny subset of
// protobuf needed for RpcMeta/Echo is hand-encoded below — schema in
// protos/rpc_meta.proto; field numbers are load-bearing.
//
// Build: g++ -O2 -shared -fPIC -pthread engine.cpp -o _engine.so

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#ifdef __GLIBC__
#include <malloc.h>
#endif

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// ThreadSanitizer soundness shim (tools/sanitize.sh tsan lane): on
// Linux std::mutex is trivially destructible — ~mutex() never calls
// pthread_mutex_destroy — so TSan keeps per-ADDRESS mutex state alive
// after the object dies.  MuxWaiter lives on the caller's stack and
// MuxClient/MuxConn on the heap; both get reused at identical
// addresses (next call frame / next allocation), and the stale state
// yields bogus "double lock" + data-race reports against the reborn
// mutex.  Destructors below tell TSan the mutex is really gone.  Plain
// builds compile this away entirely.
#if defined(__SANITIZE_THREAD__)
// pthread_mutex_destroy is intercepted by TSan and wipes its per-
// address mutex state — the exact signal ~mutex() omits.  (glibc's
// destroy on an unlocked mutex is an O(1) bookkeeping call.)
#define NS_TSAN_MUTEX_DESTROY(m) pthread_mutex_destroy((m)->native_handle())
#else
#define NS_TSAN_MUTEX_DESTROY(m) ((void)0)
#endif

namespace {

constexpr uint8_t kMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kHeader = 12;
constexpr uint64_t kMaxBody = 2ull << 30;

// Timed condvar wait that stays VISIBLE to ThreadSanitizer.  libstdc++
// lowers condition_variable::wait_for to pthread_cond_clockwait (glibc
// 2.30+), which this toolchain's libtsan does not intercept — the
// wait's internal unlock/relock then never reaches TSan, which keeps
// believing the waiter holds the mutex across the whole wait and
// reports phantom "double lock" + data races against the reactor's
// legitimate acquisitions.  Under TSan we call the intercepted
// pthread_cond_timedwait on the native handles instead; plain builds
// keep the std:: fast path.
template <typename Pred>
bool ns_cv_wait_for_ms(std::condition_variable& cv,
                       std::unique_lock<std::mutex>& lk, int64_t ms,
                       Pred pred) {
#if defined(__SANITIZE_THREAD__)
  timespec abs;
  clock_gettime(CLOCK_REALTIME, &abs);
  abs.tv_sec += ms / 1000;
  abs.tv_nsec += (ms % 1000) * 1000000L;
  if (abs.tv_nsec >= 1000000000L) {
    abs.tv_sec++;
    abs.tv_nsec -= 1000000000L;
  }
  while (!pred()) {
    int rc = pthread_cond_timedwait(cv.native_handle(),
                                    lk.mutex()->native_handle(), &abs);
    if (rc == ETIMEDOUT) return pred();
  }
  return true;
#else
  return cv.wait_for(lk, std::chrono::milliseconds(ms), pred);
#endif
}

#ifdef __GLIBC__
// Per-call response bodies at or above glibc's default mmap threshold
// (128KB) would otherwise cost one mmap+munmap — plus a page fault per
// touched page — per RPC: measured as an 8x qps crater on the
// 128KB-256KB points of the echo size curve (glibc's dynamic threshold
// only self-heals after freeing an mmapped chunk, which is why 256KB+
// partially recovered).  Keep multi-MB call allocations on the
// freelist-managed heap.
struct MallocTuning {
  MallocTuning() {
    mallopt(M_MMAP_THRESHOLD, 16 << 20);
    mallopt(M_TRIM_THRESHOLD, 32 << 20);
  }
} g_malloc_tuning;
#endif

// ---------------------------------------------------------------------------
// deterministic fault injection (chaos/): process-wide per-site knobs
// programmed from Python via ns_set_fault.  The disarmed hot-path cost
// is ONE relaxed atomic load (g_faults_armed).  Armed decisions are a
// pure function of (seed, traversal counter) — murmur3 fmix64 in counter
// mode — so a replayed plan fires on the identical traversal indices.
// ---------------------------------------------------------------------------

enum FaultAction : uint32_t {
  FA_NONE = 0,
  FA_SHORT = 1,   // cap read()/write() size to `arg` bytes (partial IO)
  FA_EAGAIN = 2,  // pretend the fd returned EAGAIN this round
  FA_RESET = 3,   // kill the connection
  FA_DELAY = 4,   // sleep `arg` microseconds
};

// site ids (mirrored by chaos/injector.py _NATIVE_SITE_IDS)
enum FaultSite : int {
  FS_SRV_READ = 0,
  FS_SRV_WRITE = 1,
  FS_COUNT = 2,
};

struct FaultState {
  std::atomic<uint32_t> action{0};
  std::atomic<uint64_t> arg{0};
  std::atomic<uint32_t> prob{0};  // fire when hash_hi32 < prob
  std::atomic<uint64_t> seed{0};
  std::atomic<int64_t> max_hits{-1};  // <0 = unlimited
  std::atomic<uint64_t> evals{0};
  std::atomic<uint64_t> hits{0};
};

FaultState g_faults[FS_COUNT];
std::atomic<uint32_t> g_faults_armed{0};

inline uint64_t fault_mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdull;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ull;
  x ^= x >> 33;
  return x;
}

// Returns the action to apply at `site` this traversal (FA_NONE = no
// fault).  `*arg` receives the action argument.
inline uint32_t fault_check(int site, uint64_t* arg) {
  if (g_faults_armed.load(std::memory_order_relaxed) == 0) return FA_NONE;
  FaultState& f = g_faults[site];
  // acquire pairs with ns_set_fault's release store: arg/prob/seed
  // written before the action publish must be visible once the action
  // is observed (relaxed here could apply a new action with a stale
  // arg/seed on a weakly ordered CPU)
  uint32_t act = f.action.load(std::memory_order_acquire);
  if (act == FA_NONE) return FA_NONE;
  uint64_t n = f.evals.fetch_add(1, std::memory_order_relaxed);
  uint32_t prob = f.prob.load(std::memory_order_relaxed);
  if (prob != 0xFFFFFFFFu) {  // saturated prob = 1.0: ALWAYS fire —
    // the high-32 compare alone would skip ~1-in-4e9 traversals
    uint64_t h = fault_mix64(f.seed.load(std::memory_order_relaxed) +
                             n * 0x9e3779b97f4a7c15ull);
    if (static_cast<uint32_t>(h >> 32) >= prob) return FA_NONE;
  }
  int64_t mh = f.max_hits.load(std::memory_order_relaxed);
  if (mh >= 0) {
    // CAS claim: hits must never transiently exceed the budget — a
    // concurrent ns_fault_hits read during a fetch_add/fetch_sub
    // window would fold a phantom hit into chaos_injected_total
    uint64_t cur = f.hits.load(std::memory_order_relaxed);
    do {
      if (static_cast<int64_t>(cur) >= mh) return FA_NONE;
    } while (!f.hits.compare_exchange_weak(cur, cur + 1,
                                           std::memory_order_relaxed));
  } else {
    f.hits.fetch_add(1, std::memory_order_relaxed);
  }
  *arg = f.arg.load(std::memory_order_relaxed);
  return act;
}

inline void fault_sleep_us(uint64_t us) {
  if (us > 200000) us = 200000;  // bounded: chaos delays, never wedges
  std::this_thread::sleep_for(std::chrono::microseconds(us));
}

// Growable byte buffer WITHOUT zero-fill.  Frames larger than one
// read() chunk are completed by reading straight into the tail;
// std::vector would either memset the tail on resize or force the old
// stage-into-vector path that copied every byte of a large frame twice
// once a connection fell behind a frame boundary (the large-payload
// half of the size-curve crater).
struct ByteBuf {
  uint8_t* p = nullptr;
  size_t len = 0, cap = 0;
  ~ByteBuf() { free(p); }
  ByteBuf() = default;
  ByteBuf(const ByteBuf&) = delete;
  ByteBuf& operator=(const ByteBuf&) = delete;
  bool empty() const { return len == 0; }
  size_t size() const { return len; }
  uint8_t* data() { return p; }
  const uint8_t* data() const { return p; }
  void reserve(size_t n) {
    if (n <= cap) return;
    size_t ncap = cap ? cap * 2 : 4096;
    if (ncap < n) ncap = n;
    p = static_cast<uint8_t*>(realloc(p, ncap));
    cap = ncap;
  }
  // `n` writable bytes past the end; pair with advance() after the read
  uint8_t* tail(size_t n) {
    reserve(len + n);
    return p + len;
  }
  void advance(size_t n) { len += n; }
  void append(const uint8_t* src, size_t n) {
    memcpy(tail(n), src, n);
    len += n;
  }
  void assign(const uint8_t* src, size_t n) {
    len = 0;
    append(src, n);
  }
  void erase_front(size_t n) {
    if (n >= len) {
      len = 0;
      // a burst of large frames can balloon the stash; hand the pages
      // back once it drains
      if (cap > (1u << 20)) {
        free(p);
        p = nullptr;
        cap = 0;
      }
      return;
    }
    memmove(p, p + n, len - n);
    len -= n;
  }
  void clear() { len = 0; }
  void swap_storage(ByteBuf& o) {
    std::swap(p, o.p);
    std::swap(len, o.len);
    std::swap(cap, o.cap);
  }
};

// Stash the uncut remainder of a DIRECT read (one that cut frames
// straight out of the shared read buffer) into the connection's own
// buffer.  When nothing was cut and the remainder is large — the first
// chunk of a frame bigger than one read() — the read buffer is STOLEN
// wholesale (pointer swap) instead of copied: a 1MB+ frame would
// otherwise pay a full extra copy of its first megabyte every request.
constexpr size_t kStealThreshold = 64 * 1024;

void stash_direct_remainder(ByteBuf* in, ByteBuf* rdbuf, size_t off,
                            size_t dlen) {
  size_t rest = dlen - off;
  if (off == 0 && rest >= kStealThreshold) {
    in->swap_storage(*rdbuf);
    in->len = dlen;
    rdbuf->len = 0;
    return;
  }
  in->assign(rdbuf->p + off, rest);
}

// ---------------------------------------------------------------------------
// minimal protobuf
// ---------------------------------------------------------------------------

struct PbWriter {
  std::string own;
  std::string& out;
  PbWriter() : out(own) {}
  // write into an external buffer (skips one copy on hot paths)
  explicit PbWriter(std::string& ext) : out(ext) {}
  void varint(uint64_t v) {
    while (v >= 0x80) {
      out.push_back(static_cast<char>(v | 0x80));
      v >>= 7;
    }
    out.push_back(static_cast<char>(v));
  }
  void tag(uint32_t field, uint32_t wire) { varint((field << 3) | wire); }
  void field_varint(uint32_t f, uint64_t v) {
    if (v) {
      tag(f, 0);
      varint(v);
    }
  }
  void field_bytes(uint32_t f, const char* p, size_t n) {
    tag(f, 2);
    varint(n);
    out.append(p, n);
  }
};

struct PbReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }
  // returns field number, 0 at end/error; wire type in *wire
  uint32_t next(uint32_t* wire) {
    if (p >= end || !ok) return 0;
    uint64_t key = varint();
    if (!ok) return 0;
    *wire = key & 7;
    return static_cast<uint32_t>(key >> 3);
  }
  bool bytes(const uint8_t** out, size_t* n) {
    uint64_t len = varint();
    if (!ok || len > static_cast<uint64_t>(end - p)) {
      ok = false;
      return false;
    }
    *out = p;
    *n = len;
    p += len;
    return true;
  }
  void skip(uint32_t wire) {
    switch (wire) {
      case 0:
        varint();
        break;
      case 1:
        if (end - p >= 8)
          p += 8;
        else
          ok = false;
        break;
      case 2: {
        const uint8_t* d;
        size_t n;
        bytes(&d, &n);
        break;
      }
      case 5:
        if (end - p >= 4)
          p += 4;
        else
          ok = false;
        break;
      default:
        ok = false;
    }
  }
};

// Parsed RpcMeta subset (protos/rpc_meta.proto)
struct MetaView {
  std::string service, method;   // request.service_name/.method_name
  uint64_t correlation_id = 0;   // field 4
  uint64_t attachment_size = 0;  // field 5
  uint64_t compress_type = 0;    // field 3
  int32_t error_code = 0;        // response.error_code
  std::string error_text;        // response.error_text
  bool has_request = false, has_response = false;
  bool has_stream = false, has_auth = false, has_device_segs = false;
};

bool parse_meta(const uint8_t* data, size_t len, MetaView* m) {
  PbReader r{data, data + len};
  uint32_t wire;
  while (uint32_t f = r.next(&wire)) {
    if (f == 1 && wire == 2) {  // RpcRequestMeta
      const uint8_t* d;
      size_t n;
      if (!r.bytes(&d, &n)) return false;
      m->has_request = true;
      PbReader rr{d, d + n};
      uint32_t w2;
      while (uint32_t f2 = rr.next(&w2)) {
        if (f2 == 1 && w2 == 2) {
          const uint8_t* s;
          size_t sn;
          if (!rr.bytes(&s, &sn)) return false;
          m->service.assign(reinterpret_cast<const char*>(s), sn);
        } else if (f2 == 2 && w2 == 2) {
          const uint8_t* s;
          size_t sn;
          if (!rr.bytes(&s, &sn)) return false;
          m->method.assign(reinterpret_cast<const char*>(s), sn);
        } else {
          rr.skip(w2);
        }
      }
      if (!rr.ok) return false;
    } else if (f == 2 && wire == 2) {  // RpcResponseMeta
      const uint8_t* d;
      size_t n;
      if (!r.bytes(&d, &n)) return false;
      m->has_response = true;
      PbReader rr{d, d + n};
      uint32_t w2;
      while (uint32_t f2 = rr.next(&w2)) {
        if (f2 == 1 && w2 == 0) {
          m->error_code = static_cast<int32_t>(rr.varint());
        } else if (f2 == 2 && w2 == 2) {
          const uint8_t* s;
          size_t sn;
          if (!rr.bytes(&s, &sn)) return false;
          m->error_text.assign(reinterpret_cast<const char*>(s), sn);
        } else {
          rr.skip(w2);
        }
      }
      if (!rr.ok) return false;
    } else if (f == 3 && wire == 0) {
      m->compress_type = r.varint();
    } else if (f == 4 && wire == 0) {
      m->correlation_id = r.varint();
    } else if (f == 5 && wire == 0) {
      m->attachment_size = r.varint();
    } else if (f == 6) {
      m->has_stream = true;
      r.skip(wire);
    } else if (f == 7) {
      m->has_device_segs = true;
      r.skip(wire);
    } else if (f == 8) {
      m->has_auth = true;
      r.skip(wire);
    } else {
      r.skip(wire);
    }
  }
  return r.ok;
}

std::string pack_request_meta(const char* service, size_t service_len,
                              const char* method, size_t method_len,
                              uint64_t cid, uint64_t att_size,
                              uint64_t log_id) {
  PbWriter req;
  req.field_bytes(1, service, service_len);
  req.field_bytes(2, method, method_len);
  req.field_varint(3, log_id);
  PbWriter meta;
  meta.field_bytes(1, req.out.data(), req.out.size());
  meta.field_varint(4, cid);
  meta.field_varint(5, att_size);
  return std::move(meta.out);
}

std::string pack_response_meta(uint64_t cid, uint64_t att_size,
                               int32_t error_code = 0,
                               const char* error_text = nullptr) {
  PbWriter meta;
  if (error_code != 0 || error_text) {
    PbWriter resp;
    resp.field_varint(1, static_cast<uint64_t>(error_code));
    if (error_text) resp.field_bytes(2, error_text, strlen(error_text));
    meta.field_bytes(2, resp.out.data(), resp.out.size());
  }
  meta.field_varint(4, cid);
  meta.field_varint(5, att_size);
  return std::move(meta.own);
}

void put_header(char* dst, uint32_t meta_size, uint32_t body_size) {
  memcpy(dst, kMagic, 4);
  uint32_t m = htonl(meta_size), b = htonl(body_size);
  memcpy(dst + 4, &m, 4);
  memcpy(dst + 8, &b, 4);
}

// ---------------------------------------------------------------------------
// IO helpers
// ---------------------------------------------------------------------------

int set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// write fully (blocking fd)
bool write_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// write an iovec array fully (blocking fd), advancing across partials
bool writev_all(int fd, iovec* iov, int cnt) {
  int idx = 0;
  while (idx < cnt) {
    ssize_t n = ::writev(fd, iov + idx, cnt - idx);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    size_t left = static_cast<size_t>(n);
    while (idx < cnt && left >= iov[idx].iov_len) {
      left -= iov[idx].iov_len;
      idx++;
    }
    if (idx < cnt && left) {
      iov[idx].iov_base = static_cast<char*>(iov[idx].iov_base) + left;
      iov[idx].iov_len -= left;
    }
  }
  return true;
}

bool read_exact(int fd, char* p, size_t n, int timeout_ms) {
  while (n) {
    if (timeout_ms >= 0) {
      struct pollfd pfd {fd, POLLIN, 0};
      int rc = ::poll(&pfd, 1, timeout_ms);
      if (rc == 0) {
        errno = ETIMEDOUT;
        return false;
      }
      if (rc < 0) {
        if (errno == EINTR) continue;
        return false;
      }
    }
    ssize_t r = ::read(fd, p, n);
    if (r == 0) {
      errno = ECONNRESET;
      return false;
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// ---------------------------------------------------------------------------
// server
// ---------------------------------------------------------------------------

using PyDispatch = void (*)(uint64_t conn_id, uint32_t proto,
                            const uint8_t* frame, uint64_t len);

// ---------------------------------------------------------------------------
// generic native method registry
//
// The dispatch mechanism is generic (reference: any C++ service runs on
// the C++ path); a handler is a C function pointer so services written
// in any native language — or ctypes callbacks, at GIL cost — plug into
// the same frame cycle.  The built-in echo fast path is just the first
// registered NativeMethod.  Returning <0 declines the frame (falls to
// the Python dispatch for full framework semantics); >=0 is the
// response error_code (0 = ok).
// ---------------------------------------------------------------------------

// Response builder: an ordered list of parts, each either owned bytes
// (stored in the arena; recorded as offsets since the arena reallocs)
// or a borrowed view into the request frame (valid until the frame is
// consumed — burst_append_response copies synchronously).  Views let
// echo-style handlers move the payload frame→burst with ONE memcpy.
struct RespPart {
  bool is_view;
  size_t off_or_ptr;  // arena offset, or the view pointer
  size_t len;
};

struct NativeRespCtx {
  std::string arena;
  std::vector<RespPart> payload_parts;
  std::string attachment;
  const uint8_t* att_view = nullptr;
  size_t att_view_len = 0;

  void clear() {
    arena.clear();
    payload_parts.clear();
    attachment.clear();
    att_view = nullptr;
    att_view_len = 0;
  }
  void payload_owned(const char* p, size_t n) {
    payload_parts.push_back({false, arena.size(), n});
    arena.append(p, n);
  }
  void payload_view(const uint8_t* p, size_t n) {
    payload_parts.push_back({true, reinterpret_cast<size_t>(p), n});
  }
  size_t payload_size() const {
    size_t n = 0;
    for (const RespPart& part : payload_parts) n += part.len;
    return n;
  }
  size_t att_size() const { return attachment.size() + att_view_len; }
};

using NativeMethodFn = int32_t (*)(void* user_data, const uint8_t* req,
                                   uint64_t req_len, const uint8_t* att,
                                   uint64_t att_len, void* resp_ctx);

struct NativeMethod {
  NativeMethodFn fn = nullptr;
  void* user_data = nullptr;
  std::atomic<int32_t> inflight{0};
  std::atomic<int32_t> max_concurrency{0};  // 0 = unlimited
  // fast-path completions bypass Python MethodStatus; these counters
  // are harvested into it (ns_method_stats) so /status stays correct
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> latency_ns_sum{0};
  std::atomic<uint64_t> rejected{0};
  std::atomic<uint64_t> errors{0};
};

// EchoRequest view (protos/echo.proto): message=1 code=2 server_fail=3
// close_fd=4 sleep_us=5.  Any fault-injection field present → decline.
struct EchoView {
  const uint8_t* msg = nullptr;
  size_t msg_len = 0;
  uint64_t code = 0;
  bool plain = true;  // no fault-injection fields
};

bool parse_echo(const uint8_t* data, size_t len, EchoView* e) {
  PbReader r{data, data + len};
  uint32_t wire;
  while (uint32_t f = r.next(&wire)) {
    if (f == 1 && wire == 2) {
      if (!r.bytes(&e->msg, &e->msg_len)) return false;
    } else if (f == 2 && wire == 0) {
      e->code = r.varint();
    } else if (f == 3 || f == 4 || f == 5) {
      e->plain = false;
      r.skip(wire);
    } else {
      r.skip(wire);
    }
  }
  return r.ok;
}

// built-in echo handler; user_data bit 0 = attach_echo
int32_t builtin_echo_method(void* user_data, const uint8_t* req,
                            uint64_t req_len, const uint8_t* att,
                            uint64_t att_len, void* resp_ctx) {
  EchoView e;
  if (!parse_echo(req, req_len, &e) || !e.plain) return -1;
  NativeRespCtx* ctx = static_cast<NativeRespCtx*>(resp_ctx);
  // response pb = field1 header + message VIEW (borrowed from the
  // request frame: frame→burst is the only copy) + field2 tail
  if (e.msg_len) {
    PbWriter hdr;
    hdr.tag(1, 2);
    hdr.varint(e.msg_len);
    ctx->payload_owned(hdr.own.data(), hdr.own.size());
    ctx->payload_view(e.msg, e.msg_len);
  }
  PbWriter tail;
  tail.field_varint(2, e.code);
  if (!tail.own.empty()) ctx->payload_owned(tail.own.data(), tail.own.size());
  if ((reinterpret_cast<intptr_t>(user_data) & 1) && att_len) {
    ctx->att_view = att;  // borrow: frame outlives the burst append
    ctx->att_view_len = att_len;
  }
  return 0;
}

// per-connection protocol, sniffed from the first bytes (reference
// InputMessenger tries protocols in order on every new connection,
// input_messenger.cpp:317-382; here the port speaks tpu_std plus any
// protocol the server enabled via ns_enable_protocols)
enum ConnProto : int {
  P_UNKNOWN = 0,
  P_TPU = 1,
  P_HTTP = 2,
  P_REDIS = 3,
};

struct Conn {
  int fd = -1;
  uint64_t id = 0;
  int proto = P_UNKNOWN;
  bool close_after = false;  // HTTP Connection: close — after flush
  // frames handed to Python and not yet answered (http/redis only):
  // while >0 the engine neither reads nor cuts this connection, so
  // pipelined replies cannot overtake the Python one (RESP and
  // HTTP/1.1 have no correlation ids — order IS the protocol).
  // tpu_std is exempt: its frames carry correlation ids.
  std::atomic<int> py_pending{0};
  ByteBuf in;                // partial-frame accumulation
  std::deque<std::string> outq;  // pending writes (epoll-out driven)
  size_t out_off = 0;        // offset into outq.front()
  std::mutex out_mu;
  bool want_out = false;     // EPOLLOUT armed
  std::atomic<bool> dead{false};
  ~Conn() { NS_TSAN_MUTEX_DESTROY(&out_mu); }
};

struct Worker;

struct NativeServer {
  std::vector<std::thread> threads;
  std::vector<Worker*> workers;
  int listen_fd = -1;
  std::thread acceptor;
  std::atomic<bool> running{false};
  std::atomic<uint64_t> next_conn_id{1};
  std::atomic<uint32_t> rr{0};
  PyDispatch dispatch = nullptr;
  // native method registry: "service\0method" → handler + stats.
  // Methods are registered before listen() and never erased, so
  // workers read the map without reg_mu after start (values are
  // pointers; the atomics inside are the only mutated state).
  std::unordered_map<std::string, NativeMethod*> methods;
  // native HTTP registry: request path → handler (req = body bytes).
  // Registered before listen(), read lock-free by workers.
  std::unordered_map<std::string, NativeMethod*> http_methods;
  // which ConnProto bits this port answers (tpu_std always on)
  uint32_t proto_mask = 1u << P_TPU;
  // native redis KV: sharded map answering GET/SET/DEL/EXISTS/INCR/
  // PING entirely in C (the reference's redis_server example is a C++
  // RedisService; this is its native analog).  Other commands fall to
  // the Python RedisService dispatch.
  bool redis_native_kv = false;
  static constexpr int kKvShards = 16;
  std::mutex kv_mu[kKvShards];
  std::unordered_map<std::string, std::string> kv[kKvShards];
  std::mutex reg_mu;
  std::mutex conns_mu;
  std::unordered_map<uint64_t, std::pair<Worker*, Conn*>> conns;
  // server response-ring step log (ns_ring_stats): windows = reply burst
  // flushes — flush_pending_burst on the native fast-path lane plus
  // ns_send_burst on the Python-dispatch lane, one per harvested window
  // per conn either way; responses = frames those windows carried;
  // flush_bursts = conn_write_parts invocations (ring-lane traffic
  // shows bursts ≈ windows, a per-call reply path would not).
  std::atomic<uint64_t> ring_windows{0};
  std::atomic<uint64_t> ring_responses{0};
  std::atomic<uint64_t> flush_bursts{0};

  ~NativeServer() {
    for (auto& kv : methods) delete kv.second;
    NS_TSAN_MUTEX_DESTROY(&reg_mu);
    NS_TSAN_MUTEX_DESTROY(&conns_mu);
    for (int i = 0; i < kKvShards; i++) NS_TSAN_MUTEX_DESTROY(&kv_mu[i]);
  }

  NativeMethod* method_lookup(const std::string& svc, const std::string& m) {
    thread_local std::string key;  // reused: no per-frame allocation
    key.assign(svc);
    key.push_back('\0');
    key.append(m);
    auto it = methods.find(key);
    return it == methods.end() ? nullptr : it->second;
  }

  NativeMethod* method_get_or_create(const char* svc, const char* m) {
    std::lock_guard<std::mutex> g(reg_mu);
    std::string key = std::string(svc) + '\0' + m;
    auto it = methods.find(key);
    if (it != methods.end()) return it->second;
    NativeMethod* nm = new NativeMethod();
    methods[key] = nm;
    return nm;
  }
};

struct Worker {
  NativeServer* srv;
  int epfd = -1;
  int wake_fd = -1;  // eventfd: new conns / pending writes / stop
  std::mutex mu;
  std::vector<Conn*> incoming;
  std::vector<Conn*> writable;  // conns with queued output to arm
  std::vector<Conn*> resume;    // py_done'd conns: re-cut + re-arm
  std::atomic<bool> stop{false};

  void notify() {
    uint64_t one = 1;
    ssize_t n = ::write(wake_fd, &one, sizeof(one));
    (void)n;
  }
  ~Worker() { NS_TSAN_MUTEX_DESTROY(&mu); }
};

void conn_queue_write(Worker* w, Conn* c, std::string&& data) {
  bool need_arm = false;
  {
    std::lock_guard<std::mutex> g(c->out_mu);
    if (c->dead.load()) return;
    if (c->outq.empty()) {
      // try inline write first (StartWrite analog: first writer writes)
      size_t off = 0;
      while (off < data.size()) {
        ssize_t n = ::write(c->fd, data.data() + off, data.size() - off);
        if (n > 0) {
          off += static_cast<size_t>(n);
          continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
        if (n < 0 && errno == EINTR) continue;
        c->dead.store(true);
        return;
      }
      if (off == data.size()) return;  // fully written inline
      c->outq.emplace_back(data.substr(off));
      need_arm = !c->want_out;
    } else {
      c->outq.emplace_back(std::move(data));
      need_arm = !c->want_out;
    }
  }
  if (need_arm) {
    std::lock_guard<std::mutex> g(w->mu);
    w->writable.push_back(c);
    w->notify();
  }
}

// drain queued output on EPOLLOUT; returns false on fatal error
bool conn_flush(Conn* c) {
  std::lock_guard<std::mutex> g(c->out_mu);
  while (!c->outq.empty()) {
    std::string& front = c->outq.front();
    while (c->out_off < front.size()) {
      size_t wmax = front.size() - c->out_off;
      bool short_after = false;
      uint64_t farg = 0;
      uint32_t fact = fault_check(FS_SRV_WRITE, &farg);
      if (fact == FA_EAGAIN) return true;  // EPOLLOUT (LT) refires
      if (fact == FA_RESET) return false;
      if (fact == FA_DELAY) fault_sleep_us(farg);
      if (fact == FA_SHORT) {
        size_t cap = farg ? static_cast<size_t>(farg) : 1;
        if (cap < wmax) wmax = cap;
        short_after = true;
      }
      ssize_t n = ::write(c->fd, front.data() + c->out_off, wmax);
      if (n > 0) {
        c->out_off += static_cast<size_t>(n);
        if (short_after) return true;  // remainder drains on the next
        continue;                      // level-triggered EPOLLOUT
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    c->out_off = 0;
    c->outq.pop_front();
  }
  return true;
}

void close_conn(NativeServer* srv, Worker* w, Conn* c) {
  epoll_ctl(w->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  {
    // dead + close move together UNDER out_mu: a sender inside
    // conn_queue_write (it checked dead, it is mid-::write) must fully
    // leave the fd before the close, or a recycled fd NUMBER would
    // receive the tail of its write (caught by the TSan lane).  The
    // fds are non-blocking, so the wait here is bounded by one write.
    std::lock_guard<std::mutex> g(c->out_mu);
    c->dead.store(true);
    ::close(c->fd);
    c->fd = -1;
  }
  // ns_send holds conns_mu while touching a Conn, so erasing under the
  // same lock before delete makes the free safe against sender threads
  {
    std::lock_guard<std::mutex> g(srv->conns_mu);
    srv->conns.erase(c->id);
  }
  // purge any stale pointers queued for this worker (we ARE the worker
  // thread, the only consumer of these lists)
  {
    std::lock_guard<std::mutex> g(w->mu);
    for (auto it = w->writable.begin(); it != w->writable.end();) {
      it = (*it == c) ? w->writable.erase(it) : it + 1;
    }
    for (auto it = w->incoming.begin(); it != w->incoming.end();) {
      it = (*it == c) ? w->incoming.erase(it) : it + 1;
    }
    for (auto it = w->resume.begin(); it != w->resume.end();) {
      it = (*it == c) ? w->resume.erase(it) : it + 1;
    }
  }
  delete c;
}

// One entry of a scatter-gather response burst: either a [off,len)
// range of the burst string (owned bytes) or a borrowed view into the
// request frame.  Views let large echoed payloads reach the kernel via
// writev with ZERO user-space copies (reference Socket::DoWrite writev,
// socket.cpp:1584-1790) — the burst copy was why throughput FELL with
// payload size instead of rising.
struct OutPart {
  bool is_view;
  size_t off_or_ptr;  // burst offset, or the view pointer
  size_t len;
};

// views at or above this size ride writev; smaller ones are cheaper to
// memcpy into the burst than to spend an iovec entry on
constexpr size_t kViewThreshold = 16 * 1024;

void parts_add_burst_range(std::vector<OutPart>* parts, size_t off,
                           size_t len) {
  if (!len) return;
  if (!parts->empty() && !parts->back().is_view &&
      parts->back().off_or_ptr + parts->back().len == off) {
    parts->back().len += len;  // coalesce adjacent burst ranges
    return;
  }
  parts->push_back({false, off, len});
}

void burst_append_response(std::string* burst, std::vector<OutPart>* parts,
                           const std::string& meta_out,
                           const NativeRespCtx& ctx) {
  size_t base = burst->size();
  burst->resize(base + kHeader);
  put_header(&(*burst)[base], meta_out.size(),
             ctx.payload_size() + ctx.att_size());
  *burst += meta_out;
  for (const RespPart& part : ctx.payload_parts) {
    const char* p = part.is_view
                        ? reinterpret_cast<const char*>(part.off_or_ptr)
                        : ctx.arena.data() + part.off_or_ptr;
    if (part.is_view && part.len >= kViewThreshold) {
      parts_add_burst_range(parts, base, burst->size() - base);
      base = burst->size();
      parts->push_back({true, part.off_or_ptr, part.len});
    } else {
      burst->append(p, part.len);
    }
  }
  *burst += ctx.attachment;
  if (ctx.att_view_len) {
    if (ctx.att_view_len >= kViewThreshold) {
      parts_add_burst_range(parts, base, burst->size() - base);
      base = burst->size();
      parts->push_back(
          {true, reinterpret_cast<size_t>(ctx.att_view), ctx.att_view_len});
    } else {
      burst->append(reinterpret_cast<const char*>(ctx.att_view),
                    ctx.att_view_len);
    }
  }
  parts_add_burst_range(parts, base, burst->size() - base);
}

// Flush one read-cycle's scatter-gather burst on the worker thread that
// owns the connection.  Inline writev first; whatever the kernel won't
// take is COPIED into the ordered outq (views must not outlive the read
// buffer) and EPOLLOUT drains it.
void conn_write_parts(Worker* w, Conn* c, const std::string& burst,
                      const std::vector<OutPart>& parts) {
  w->srv->flush_bursts.fetch_add(1, std::memory_order_relaxed);
  std::lock_guard<std::mutex> g(c->out_mu);
  if (c->dead.load()) return;
  size_t idx = 0, part_off = 0;
  if (c->outq.empty()) {
    while (idx < parts.size()) {
      iovec iov[64];
      int cnt = 0;
      size_t j = idx, joff = part_off;
      while (j < parts.size() && cnt < 64) {
        const OutPart& p = parts[j];
        const char* base = p.is_view
                               ? reinterpret_cast<const char*>(p.off_or_ptr)
                               : burst.data() + p.off_or_ptr;
        iov[cnt].iov_base = const_cast<char*>(base + joff);
        iov[cnt].iov_len = p.len - joff;
        cnt++;
        j++;
        joff = 0;
      }
      // chaos srv_write site: an injected partial write diverts the
      // burst remainder through the outq + EPOLLOUT drain, which is
      // exactly the reply-ordering machinery the invariant suite
      // exercises (HTTP/RESP order survives partial flushes).
      bool short_after = false;
      uint64_t farg = 0;
      uint32_t fact = fault_check(FS_SRV_WRITE, &farg);
      if (fact == FA_EAGAIN) break;
      if (fact == FA_RESET) {
        c->dead.store(true);
        return;
      }
      if (fact == FA_DELAY) fault_sleep_us(farg);
      if (fact == FA_SHORT) {
        cnt = 1;  // one iovec, capped: a genuine short writev
        size_t cap = farg ? static_cast<size_t>(farg) : 1;
        if (cap < iov[0].iov_len) iov[0].iov_len = cap;
        short_after = true;
      }
      ssize_t n = ::writev(c->fd, iov, cnt);
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        c->dead.store(true);
        return;
      }
      size_t left = static_cast<size_t>(n);
      while (left) {
        size_t avail = parts[idx].len - part_off;
        if (left >= avail) {
          left -= avail;
          idx++;
          part_off = 0;
        } else {
          part_off += left;
          left = 0;
        }
      }
      if (short_after && idx < parts.size()) break;
    }
    if (idx >= parts.size()) return;  // fully written inline
  }
  // copy the unsent remainder (ordered after any existing outq)
  std::string rest;
  size_t total = 0;
  for (size_t i = idx; i < parts.size(); i++)
    total += parts[i].len - (i == idx ? part_off : 0);
  rest.reserve(total);
  for (size_t i = idx; i < parts.size(); i++) {
    const OutPart& p = parts[i];
    const char* base = p.is_view
                           ? reinterpret_cast<const char*>(p.off_or_ptr)
                           : burst.data() + p.off_or_ptr;
    size_t skip = (i == idx) ? part_off : 0;
    rest.append(base + skip, p.len - skip);
  }
  c->outq.emplace_back(std::move(rest));
  if (!c->want_out) {
    // we ARE the owning worker thread: arm EPOLLOUT directly
    c->want_out = true;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.ptr = c;
    epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

// Reply ordering: native replies accumulated in this read cycle's burst
// must reach the connection's write path BEFORE a frame is dispatched
// to Python.  ns_send replies write straight to the socket (inline when
// outq is empty) and would otherwise overtake the unflushed burst —
// HTTP/1.x and RESP carry no correlation ids, so order IS the protocol.
// Flushing here (inside the cut, before srv->dispatch) also covers the
// conn_resume path, which re-cuts buffered bytes after ns_py_done.
void flush_pending_burst(Worker* w, Conn* c, std::string* burst,
                         std::vector<OutPart>* parts) {
  if (!parts->empty()) {
    // the native-lane half of the server response ring's step log:
    // one window per non-empty read-cycle flush, same contract as
    // ns_send_burst on the Python-dispatch lane
    w->srv->ring_windows.fetch_add(1, std::memory_order_relaxed);
    conn_write_parts(w, c, *burst, *parts);
    parts->clear();
  }
  burst->clear();
}

// handle one complete frame; returns false → close connection.
// Fast-path responses append to *burst (ONE write per read burst — the
// NOSIGNAL batching analog, input_messenger.cpp:169-190); Python
// fallback frames dispatch out-of-band as before.
bool server_on_frame(NativeServer* srv, Worker* w, Conn* c,
                     const uint8_t* frame, size_t len, std::string* burst,
                     std::vector<OutPart>* parts, std::string* py_burst) {
  uint32_t meta_size, body_size;
  memcpy(&meta_size, frame + 4, 4);
  memcpy(&body_size, frame + 8, 4);
  meta_size = ntohl(meta_size);
  body_size = ntohl(body_size);
  const uint8_t* meta_p = frame + kHeader;
  const uint8_t* body_p = meta_p + meta_size;

  MetaView m;
  if (parse_meta(meta_p, meta_size, &m) && m.has_request && !m.has_response &&
      !m.compress_type && !m.has_stream && !m.has_auth && !m.has_device_segs &&
      m.attachment_size <= body_size) {
    NativeMethod* nm = srv->method_lookup(m.service, m.method);
    if (nm != nullptr) {
      // concurrency gate: fast-path rejection mirrors the Python
      // transport's admission shed (server/admission.py): EOVERCROWDED
      // = "this server is overloaded, retry elsewhere" (docs/overload.md)
      int32_t limit = nm->max_concurrency.load(std::memory_order_relaxed);
      int32_t cur = nm->inflight.fetch_add(1, std::memory_order_relaxed) + 1;
      if (limit > 0 && cur > limit) {
        nm->inflight.fetch_sub(1, std::memory_order_relaxed);
        nm->rejected.fetch_add(1, std::memory_order_relaxed);
        NativeRespCtx empty;
        srv->ring_responses.fetch_add(1, std::memory_order_relaxed);
        burst_append_response(
            burst, parts,
            pack_response_meta(m.correlation_id, 0, 1011,  // EOVERCROWDED
                               "method concurrency limit reached "
                               "(retry elsewhere)"),
            empty);
        return true;
      }
      struct timespec t0, t1;
      clock_gettime(CLOCK_MONOTONIC, &t0);
      thread_local NativeRespCtx ctx;  // reuse arena capacity
      ctx.clear();
      size_t req_len = body_size - m.attachment_size;
      int32_t ec = nm->fn(nm->user_data, body_p, req_len, body_p + req_len,
                          m.attachment_size, &ctx);
      nm->inflight.fetch_sub(1, std::memory_order_relaxed);
      if (ec >= 0) {
        clock_gettime(CLOCK_MONOTONIC, &t1);
        uint64_t dt = (t1.tv_sec - t0.tv_sec) * 1000000000ull +
                      (t1.tv_nsec - t0.tv_nsec);
        nm->count.fetch_add(1, std::memory_order_relaxed);
        nm->latency_ns_sum.fetch_add(dt, std::memory_order_relaxed);
        if (ec != 0) nm->errors.fetch_add(1, std::memory_order_relaxed);
        srv->ring_responses.fetch_add(1, std::memory_order_relaxed);
        burst_append_response(
            burst, parts,
            pack_response_meta(m.correlation_id, ctx.att_size(), ec),
            ctx);
        return true;
      }
      // ec < 0: handler declined → full Python semantics below
    }
  }
  // ---- Python fallback: full framework semantics ----
  // Frames accumulate into *py_burst and dispatch ONCE per read burst
  // after the cut loop (cut_frames): a client ring window of N calls
  // (nc_mux_submit_many) that lands in one read then crosses into
  // Python as ONE dispatch, and the server-side micro-batcher sees it
  // as one accumulation.  Safe for tpu_std only: frames carry
  // correlation ids, so replies need no ordering against the native
  // burst flush (unlike HTTP/RESP, which never reach this path).
  if (srv->dispatch) {
    py_burst->append(reinterpret_cast<const char*>(frame), len);
    return !c->dead.load();
  }
  return false;
}

// Cut complete frames out of [data, data+len); appends fast-path
// responses to *burst.  Returns bytes consumed; sets *fatal.
size_t cut_frames(NativeServer* srv, Worker* w, Conn* c, const uint8_t* data,
                  size_t len, std::string* burst,
                  std::vector<OutPart>* parts, bool* fatal) {
  size_t off = 0;
  // Python-fallback frames from this read burst, dispatched as ONE
  // crossing after the loop (see server_on_frame).  thread_local keeps
  // the capacity warm across bursts; the worker never re-enters
  // cut_frames while dispatch runs (conn_resume is re-queued, not
  // recursive), so a single buffer per worker thread is safe.
  static thread_local std::string py_burst;
  py_burst.clear();
  while (!*fatal) {
    size_t avail = len - off;
    if (avail < kHeader) break;
    const uint8_t* p = data + off;
    if (memcmp(p, kMagic, 4) != 0) {
      *fatal = true;  // non-tpu_std traffic: native port speaks one
      break;
    }
    uint32_t ms, bs;
    memcpy(&ms, p + 4, 4);
    memcpy(&bs, p + 8, 4);
    ms = ntohl(ms);
    bs = ntohl(bs);
    if (static_cast<uint64_t>(ms) + bs > kMaxBody) {
      *fatal = true;
      break;
    }
    size_t total = kHeader + ms + bs;
    if (avail < total) break;
    if (!server_on_frame(srv, w, c, p, total, burst, parts, &py_burst))
      *fatal = true;
    off += total;
  }
  if (!py_burst.empty() && srv->dispatch) {
    srv->dispatch(c->id, P_TPU,
                  reinterpret_cast<const uint8_t*>(py_burst.data()),
                  py_burst.size());
    py_burst.clear();
    if (c->dead.load()) *fatal = true;
  }
  return off;
}

// ---------------------------------------------------------------------------
// HTTP/1.1 server framer (native fast path for registered paths;
// reference http parsing lives in details/http_message.cpp — this is a
// purpose-built cut for the hot server loop, full semantics fall back
// to the Python http stack)
// ---------------------------------------------------------------------------

bool ascii_ieq(const char* a, const char* b, size_t n) {
  for (size_t i = 0; i < n; i++) {
    char ca = a[i], cb = b[i];
    if (ca >= 'A' && ca <= 'Z') ca += 32;
    if (cb >= 'A' && cb <= 'Z') cb += 32;
    if (ca != cb) return false;
  }
  return true;
}

// find a header's value inside [hdrs, hdrs+len); returns false if absent
bool http_find_header(const char* hdrs, size_t len, const char* name,
                      size_t name_len, const char** val, size_t* val_len) {
  size_t i = 0;
  while (i < len) {
    // line start at i
    size_t eol = i;
    while (eol < len && hdrs[eol] != '\n') eol++;
    size_t line_end = (eol > i && hdrs[eol - 1] == '\r') ? eol - 1 : eol;
    if (line_end - i > name_len && hdrs[i + name_len] == ':' &&
        ascii_ieq(hdrs + i, name, name_len)) {
      size_t v = i + name_len + 1;
      while (v < line_end && (hdrs[v] == ' ' || hdrs[v] == '\t')) v++;
      *val = hdrs + v;
      *val_len = line_end - v;
      return true;
    }
    i = eol + 1;
  }
  return false;
}

constexpr size_t kMaxHttpHeader = 64 * 1024;

// emit a simple HTTP/1.1 response with scatter-gather body parts
void http_emit_response(std::string* burst, std::vector<OutPart>* parts,
                        int status, const char* reason,
                        const NativeRespCtx& ctx, bool keep_alive) {
  char head[256];
  size_t blen = ctx.payload_size() + ctx.att_size();
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\nContent-Type: "
                   "application/octet-stream\r\nContent-Length: %zu\r\n%s\r\n",
                   status, reason, blen,
                   keep_alive ? "" : "Connection: close\r\n");
  size_t base = burst->size();
  burst->append(head, n);
  for (const RespPart& part : ctx.payload_parts) {
    const char* p = part.is_view
                        ? reinterpret_cast<const char*>(part.off_or_ptr)
                        : ctx.arena.data() + part.off_or_ptr;
    if (part.is_view && part.len >= kViewThreshold) {
      parts_add_burst_range(parts, base, burst->size() - base);
      base = burst->size();
      parts->push_back({true, part.off_or_ptr, part.len});
    } else {
      burst->append(p, part.len);
    }
  }
  burst->append(ctx.attachment);
  if (ctx.att_view_len) {
    if (ctx.att_view_len >= kViewThreshold) {
      parts_add_burst_range(parts, base, burst->size() - base);
      base = burst->size();
      parts->push_back(
          {true, reinterpret_cast<size_t>(ctx.att_view), ctx.att_view_len});
    } else {
      burst->append(reinterpret_cast<const char*>(ctx.att_view),
                    ctx.att_view_len);
    }
  }
  parts_add_burst_range(parts, base, burst->size() - base);
}

// echo handler for the native http registry: response body = request body
int32_t builtin_http_echo(void*, const uint8_t* req, uint64_t req_len,
                          const uint8_t*, uint64_t, void* resp_ctx) {
  NativeRespCtx* ctx = static_cast<NativeRespCtx*>(resp_ctx);
  if (req_len) ctx->payload_view(req, req_len);
  return 0;
}

// cut complete HTTP/1.1 requests; native-registered paths answer in C,
// everything else (and chunked bodies) dispatches raw to Python
size_t http_cut(NativeServer* srv, Worker* w, Conn* c, const uint8_t* data,
                size_t len, std::string* burst, std::vector<OutPart>* parts,
                bool* fatal) {
  size_t off = 0;
  while (!*fatal && !c->close_after &&
         c->py_pending.load(std::memory_order_acquire) == 0) {
    const char* p = reinterpret_cast<const char*>(data) + off;
    size_t avail = len - off;
    if (avail < 16) break;
    // find end of headers
    const char* hdr_end = nullptr;
    size_t scan = avail < kMaxHttpHeader ? avail : kMaxHttpHeader;
    for (size_t i = 3; i < scan; i++) {
      if (p[i] == '\n' && p[i - 1] == '\r' && p[i - 2] == '\n' &&
          p[i - 3] == '\r') {
        hdr_end = p + i + 1;
        break;
      }
    }
    if (hdr_end == nullptr) {
      if (avail >= kMaxHttpHeader) *fatal = true;
      break;
    }
    size_t hdrs_len = static_cast<size_t>(hdr_end - p);
    // request line: METHOD SP PATH SP VERSION
    const char* sp1 = static_cast<const char*>(memchr(p, ' ', hdrs_len));
    if (!sp1) {
      *fatal = true;
      break;
    }
    const char* sp2 = static_cast<const char*>(
        memchr(sp1 + 1, ' ', hdrs_len - (sp1 + 1 - p)));
    if (!sp2) {
      *fatal = true;
      break;
    }
    const char* val;
    size_t val_len;
    bool chunked = false;
    uint64_t content_len = 0;
    if (http_find_header(p, hdrs_len, "transfer-encoding", 17, &val,
                         &val_len)) {
      chunked = true;  // any transfer-encoding → Python semantics
    } else if (http_find_header(p, hdrs_len, "content-length", 14, &val,
                                &val_len)) {
      for (size_t i = 0; i < val_len; i++) {
        if (val[i] < '0' || val[i] > '9') {
          *fatal = true;
          return off;
        }
        content_len = content_len * 10 + (val[i] - '0');
        if (content_len > kMaxBody) {  // in-loop: a 20-digit value
          *fatal = true;               // would wrap uint64 past the
          return off;                  // single post-loop check
        }
      }
    }
    size_t total;
    if (chunked) {
      // scan chunk framing to find the request's full extent
      size_t i = hdrs_len;
      bool complete = false;
      while (i + 2 <= avail) {
        uint64_t csize = 0;
        size_t j = i;
        while (j < avail && p[j] != '\r' && p[j] != ';') {
          char ch = p[j];
          uint64_t d;
          if (ch >= '0' && ch <= '9') d = ch - '0';
          else if (ch >= 'a' && ch <= 'f') d = ch - 'a' + 10;
          else if (ch >= 'A' && ch <= 'F') d = ch - 'A' + 10;
          else { *fatal = true; return off; }
          csize = csize * 16 + d;
          if (csize > kMaxBody) { *fatal = true; return off; }
          j++;
        }
        // skip to end of chunk-size line
        while (j < avail && p[j] != '\n') j++;
        if (j >= avail) break;
        j++;  // past \n
        if (csize == 0) {
          // trailer: expect CRLF (no trailer headers support)
          if (j + 2 > avail) break;
          if (p[j] == '\r' && p[j + 1] == '\n') {
            i = j + 2;
            complete = true;
          } else {
            *fatal = true;
            return off;
          }
          break;
        }
        if (j + csize + 2 > avail) { i = avail; break; }
        j += csize;
        if (p[j] != '\r' || p[j + 1] != '\n') { *fatal = true; return off; }
        i = j + 2;
      }
      if (!complete) break;  // need more bytes
      total = i;
    } else {
      total = hdrs_len + content_len;
      if (avail < total) break;
    }
    // keep-alive: HTTP/1.1 defaults to keep unless "Connection: close";
    // HTTP/1.0 defaults to CLOSE unless the client opts in with
    // "Connection: keep-alive" (RFC 7230 §6.3 / RFC 1945 appendix) —
    // holding a 1.0 connection open would wedge clients that detect
    // end-of-body by EOF.
    size_t rl_end = hdrs_len;  // end of request line, before CRLF
    {
      const char* nl = static_cast<const char*>(memchr(p, '\n', hdrs_len));
      if (nl) rl_end = static_cast<size_t>(nl - p);
      if (rl_end && p[rl_end - 1] == '\r') rl_end--;
    }
    const char* ver = sp2 + 1;
    bool http10 = static_cast<size_t>(ver - p) + 8 <= rl_end &&
                  memcmp(ver, "HTTP/1.0", 8) == 0;
    bool keep_alive = !http10;
    if (http_find_header(p, hdrs_len, "connection", 10, &val, &val_len)) {
      if (val_len == 5 && ascii_ieq(val, "close", 5)) {
        keep_alive = false;
      } else if (val_len == 10 && ascii_ieq(val, "keep-alive", 10)) {
        keep_alive = true;
      }
    }
    NativeMethod* nm = nullptr;
    if (!chunked && !srv->http_methods.empty()) {
      thread_local std::string pkey;
      pkey.assign(sp1 + 1, sp2 - sp1 - 1);
      // strip query string: registry keys are bare paths
      size_t q = pkey.find('?');
      if (q != std::string::npos) pkey.resize(q);
      auto it = srv->http_methods.find(pkey);
      if (it != srv->http_methods.end()) nm = it->second;
    }
    if (nm != nullptr) {
      int32_t limit = nm->max_concurrency.load(std::memory_order_relaxed);
      int32_t cur = nm->inflight.fetch_add(1, std::memory_order_relaxed) + 1;
      if (limit > 0 && cur > limit) {
        nm->inflight.fetch_sub(1, std::memory_order_relaxed);
        nm->rejected.fetch_add(1, std::memory_order_relaxed);
        NativeRespCtx empty;
        http_emit_response(burst, parts, 503, "Service Unavailable", empty,
                           keep_alive);
      } else {
        struct timespec t0, t1;
        clock_gettime(CLOCK_MONOTONIC, &t0);
        thread_local NativeRespCtx hctx;
        hctx.clear();
        int32_t ec = nm->fn(
            nm->user_data, reinterpret_cast<const uint8_t*>(p) + hdrs_len,
            total - hdrs_len, nullptr, 0, &hctx);
        nm->inflight.fetch_sub(1, std::memory_order_relaxed);
        clock_gettime(CLOCK_MONOTONIC, &t1);
        uint64_t dt = (t1.tv_sec - t0.tv_sec) * 1000000000ull +
                      (t1.tv_nsec - t0.tv_nsec);
        nm->count.fetch_add(1, std::memory_order_relaxed);
        nm->latency_ns_sum.fetch_add(dt, std::memory_order_relaxed);
        if (ec > 0) nm->errors.fetch_add(1, std::memory_order_relaxed);
        if (ec == 0) {
          http_emit_response(burst, parts, 200, "OK", hctx, keep_alive);
        } else if (ec < 0) {
          // declined → full Python semantics (Python owns the close
          // decision and the reply ORDER: pause cutting until py_done)
          if (srv->dispatch) {
            flush_pending_burst(w, c, burst, parts);
            c->py_pending.fetch_add(1, std::memory_order_release);
            srv->dispatch(c->id, P_HTTP,
                          reinterpret_cast<const uint8_t*>(p), total);
            keep_alive = true;
            off += total;
            return off;
          }
          *fatal = true;
        } else {
          NativeRespCtx empty;
          http_emit_response(burst, parts, 500, "Internal Server Error",
                             empty, keep_alive);
        }
      }
    } else if (srv->dispatch) {
      // Python owns the close decision for dispatched requests AND the
      // reply order: no further frame is cut (and no byte read) on
      // this connection until ns_py_done
      flush_pending_burst(w, c, burst, parts);
      c->py_pending.fetch_add(1, std::memory_order_release);
      srv->dispatch(c->id, P_HTTP, reinterpret_cast<const uint8_t*>(p),
                    total);
      off += total;
      return off;
    } else {
      *fatal = true;
      break;
    }
    if (!keep_alive) c->close_after = true;
    off += total;
  }
  return off;
}

// ---------------------------------------------------------------------------
// RESP (redis) server framer — native sharded KV for the hot commands,
// Python RedisService dispatch for the rest (reference redis.h
// RedisService / redis_protocol.cpp)
// ---------------------------------------------------------------------------

void resp_bulk(std::string* out, const char* p, size_t n) {
  char h[24];
  out->append(h, snprintf(h, sizeof(h), "$%zu\r\n", n));
  out->append(p, n);
  out->append("\r\n", 2);
}

// parse one client RESP array of bulk strings; returns bytes consumed
// (0 = incomplete), argv filled with (ptr,len) views; *bad on garbage
size_t resp_parse(const uint8_t* data, size_t len,
                  std::vector<std::pair<const char*, size_t>>* argv,
                  bool* bad) {
  argv->clear();
  const char* p = reinterpret_cast<const char*>(data);
  if (len < 4) return 0;
  if (p[0] != '*') {
    *bad = true;
    return 0;
  }
  size_t i = 1;
  int64_t nelem = 0;
  while (i < len && p[i] != '\r') {
    if (p[i] < '0' || p[i] > '9' || nelem > 1024 * 1024) {
      *bad = true;
      return 0;
    }
    nelem = nelem * 10 + (p[i] - '0');
    i++;
  }
  if (i + 2 > len) return 0;
  i += 2;  // \r\n
  for (int64_t e = 0; e < nelem; e++) {
    if (i >= len) return 0;
    if (p[i] != '$') {
      *bad = true;
      return 0;
    }
    i++;
    int64_t blen = 0;
    while (i < len && p[i] != '\r') {
      if (p[i] < '0' || p[i] > '9' || blen > (1 << 30)) {
        *bad = true;
        return 0;
      }
      blen = blen * 10 + (p[i] - '0');
      i++;
    }
    if (i + 2 > len) return 0;
    i += 2;
    if (i + static_cast<size_t>(blen) + 2 > len) return 0;
    argv->push_back({p + i, static_cast<size_t>(blen)});
    i += blen;
    if (p[i] != '\r' || p[i + 1] != '\n') {
      *bad = true;
      return 0;
    }
    i += 2;
  }
  return i;
}

size_t resp_cut(NativeServer* srv, Worker* w, Conn* c, const uint8_t* data,
                size_t len, std::string* burst,
                std::vector<OutPart>* parts, bool* fatal) {
  thread_local std::vector<std::pair<const char*, size_t>> argv;
  std::hash<std::string> hasher;
  size_t off = 0;
  // resp replies are all small owned bytes: cover everything appended
  // here with one burst-range part so the shared flush path picks it up
  size_t b0 = burst->size();
  while (!*fatal && c->py_pending.load(std::memory_order_acquire) == 0) {
    bool bad = false;
    size_t used = resp_parse(data + off, len - off, &argv, &bad);
    if (bad) {
      *fatal = true;
      break;
    }
    if (!used) break;
    bool handled = false;
    if (srv->redis_native_kv && !argv.empty()) {
      thread_local std::string cmd;
      cmd.assign(argv[0].first, argv[0].second);
      for (char& ch : cmd)
        if (ch >= 'a' && ch <= 'z') ch -= 32;
      handled = true;
      if (cmd == "PING" && argv.size() == 1) {
        burst->append("+PONG\r\n", 7);
      } else if (cmd == "SET" && argv.size() == 3) {
        // option-bearing SET (NX/XX/EX/PX/GET…) falls through to the
        // Python RedisService: silently ignoring options would ack
        // writes with semantics the client never got
        std::string key(argv[1].first, argv[1].second);
        int shard = hasher(key) & (NativeServer::kKvShards - 1);
        {
          std::lock_guard<std::mutex> g(srv->kv_mu[shard]);
          srv->kv[shard][std::move(key)].assign(argv[2].first,
                                                argv[2].second);
        }
        burst->append("+OK\r\n", 5);
      } else if (cmd == "GET" && argv.size() == 2) {
        std::string key(argv[1].first, argv[1].second);
        int shard = hasher(key) & (NativeServer::kKvShards - 1);
        std::lock_guard<std::mutex> g(srv->kv_mu[shard]);
        auto it = srv->kv[shard].find(key);
        if (it == srv->kv[shard].end())
          burst->append("$-1\r\n", 5);
        else
          resp_bulk(burst, it->second.data(), it->second.size());
      } else if (cmd == "DEL" && argv.size() >= 2) {
        int64_t removed = 0;
        for (size_t a = 1; a < argv.size(); a++) {
          std::string key(argv[a].first, argv[a].second);
          int shard = hasher(key) & (NativeServer::kKvShards - 1);
          std::lock_guard<std::mutex> g(srv->kv_mu[shard]);
          removed += srv->kv[shard].erase(key);
        }
        char h[24];
        burst->append(h, snprintf(h, sizeof(h), ":%lld\r\n",
                                  static_cast<long long>(removed)));
      } else if (cmd == "EXISTS" && argv.size() == 2) {
        std::string key(argv[1].first, argv[1].second);
        int shard = hasher(key) & (NativeServer::kKvShards - 1);
        std::lock_guard<std::mutex> g(srv->kv_mu[shard]);
        burst->append(srv->kv[shard].count(key) ? ":1\r\n" : ":0\r\n", 4);
      } else if (cmd == "INCR" && argv.size() == 2) {
        std::string key(argv[1].first, argv[1].second);
        int shard = hasher(key) & (NativeServer::kKvShards - 1);
        std::lock_guard<std::mutex> g(srv->kv_mu[shard]);
        std::string& v = srv->kv[shard][key];
        long long cur = 0;
        bool numeric = true;
        if (!v.empty()) {
          char* endp = nullptr;
          cur = strtoll(v.c_str(), &endp, 10);
          numeric = endp != nullptr && *endp == 0;
        }
        if (!numeric) {
          burst->append("-ERR value is not an integer or out of range\r\n");
        } else {
          cur += 1;
          char num[24];
          v.assign(num, snprintf(num, sizeof(num), "%lld", cur));
          char h[28];
          burst->append(h, snprintf(h, sizeof(h), ":%lld\r\n", cur));
        }
      } else {
        handled = false;  // unknown command → Python RedisService
      }
    }
    if (!handled) {
      if (srv->dispatch) {
        // pause: RESP replies must stay in command order, so no later
        // command may be answered (natively or otherwise) until Python
        // finishes this one (ns_py_done resumes the cut) — and the
        // native replies already accumulated must hit the wire first
        if (burst->size() > b0)
          parts_add_burst_range(parts, b0, burst->size() - b0);
        flush_pending_burst(w, c, burst, parts);
        c->py_pending.fetch_add(1, std::memory_order_release);
        srv->dispatch(c->id, P_REDIS, data + off, used);
        off += used;
        return off;
      }
      *fatal = true;
      break;
    }
    off += used;
  }
  if (burst->size() > b0)
    parts_add_burst_range(parts, b0, burst->size() - b0);
  return off;
}

// sniff + route one read chunk through the connection's protocol
size_t proto_cut(NativeServer* srv, Worker* w, Conn* c, const uint8_t* data,
                 size_t len, std::string* burst,
                 std::vector<OutPart>* parts, bool* fatal) {
  if (c->proto == P_UNKNOWN) {
    if (len >= 4 && memcmp(data, kMagic, 4) == 0) {
      c->proto = P_TPU;
    } else if ((srv->proto_mask & (1u << P_REDIS)) && data[0] == '*') {
      c->proto = P_REDIS;
    } else {
      bool is_http = false, maybe_http = false;
      if (srv->proto_mask & (1u << P_HTTP)) {
        static const char* kMethods[] = {"GET ",  "POST ",   "PUT ",
                                         "HEAD ", "DELETE ", "OPTIONS ",
                                         "PATCH "};
        for (const char* m : kMethods) {
          size_t ml = strlen(m);
          if (len >= ml) {
            if (memcmp(data, m, ml) == 0) {
              is_http = true;
              break;
            }
          } else if (memcmp(data, m, len) == 0) {
            maybe_http = true;
          }
        }
      }
      if (is_http) {
        c->proto = P_HTTP;
      } else {
        // a short first read may still grow into TRPC magic or an
        // HTTP method — only kill once no enabled protocol can match
        bool maybe_tpu =
            len < 4 && memcmp(data, kMagic, len) == 0;
        if (maybe_tpu || maybe_http) return 0;
        *fatal = true;
        return 0;
      }
    }
  }
  switch (c->proto) {
    case P_TPU:
      return cut_frames(srv, w, c, data, len, burst, parts, fatal);
    case P_HTTP:
      return http_cut(srv, w, c, data, len, burst, parts, fatal);
    case P_REDIS:
      return resp_cut(srv, w, c, data, len, burst, parts, fatal);
  }
  *fatal = true;
  return 0;
}

// Re-cut a connection's buffered bytes after Python answered its
// dispatched frame (ns_py_done), then re-arm EPOLLIN.  Runs on the
// owning worker thread.
void conn_resume(NativeServer* srv, Worker* w, Conn* c) {
  if (c->dead.load()) {
    close_conn(srv, w, c);
    return;
  }
  static thread_local std::string burst;
  static thread_local std::vector<OutPart> oparts;
  burst.clear();
  oparts.clear();
  bool fatal = false;
  if (!c->in.empty()) {
    size_t off = proto_cut(srv, w, c, c->in.data(), c->in.size(), &burst,
                           &oparts, &fatal);
    if (!fatal && !oparts.empty()) {
      srv->ring_windows.fetch_add(1, std::memory_order_relaxed);
      conn_write_parts(w, c, burst, oparts);
    }
    if (c->dead.load()) fatal = true;
    if (!fatal && off) c->in.erase_front(off);
  }
  if (fatal) {
    close_conn(srv, w, c);
    return;
  }
  if (c->close_after) {
    std::lock_guard<std::mutex> g(c->out_mu);
    if (c->outq.empty()) {
      fatal = true;
    }
  }
  if (fatal) {
    close_conn(srv, w, c);
    return;
  }
  if (c->py_pending.load(std::memory_order_acquire) == 0) {
    std::lock_guard<std::mutex> g(c->out_mu);
    epoll_event ev{};
    ev.events = EPOLLIN | (c->want_out ? EPOLLOUT : 0);
    ev.data.ptr = c;
    epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

void worker_loop(NativeServer* srv, Worker* w) {
  epoll_event evs[128];
  std::vector<Conn*> res_pending;  // resumes deferred past the batch
  while (!w->stop.load()) {
    int n = epoll_wait(w->epfd, evs, 128, 500);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    res_pending.clear();
    for (int i = 0; i < n; i++) {
      if (evs[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t junk;
        while (::read(w->wake_fd, &junk, sizeof(junk)) > 0) {
        }
        std::vector<Conn*> add, arm, res;
        {
          std::lock_guard<std::mutex> g(w->mu);
          add.swap(w->incoming);
          arm.swap(w->writable);
          res.swap(w->resume);
        }
        // resumes may CLOSE (delete) a conn, and a later event in THIS
        // batch may still reference it — defer them past the loop
        res_pending.insert(res_pending.end(), res.begin(), res.end());
        for (Conn* c : add) {
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          if (epoll_ctl(w->epfd, EPOLL_CTL_ADD, c->fd, &ev) < 0) {
            close_conn(srv, w, c);
          }
        }
        for (Conn* c : arm) {
          if (c->dead.load()) continue;
          std::lock_guard<std::mutex> g(c->out_mu);
          if (!c->outq.empty() && !c->want_out) {
            c->want_out = true;
            epoll_event ev{};
            ev.events = EPOLLIN | EPOLLOUT;
            ev.data.ptr = c;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          }
        }
        continue;
      }
      Conn* c = static_cast<Conn*>(evs[i].data.ptr);
      bool fatal = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) fatal = true;
      if (!fatal && (evs[i].events & EPOLLOUT)) {
        if (!conn_flush(c)) {
          fatal = true;
        } else {
          std::lock_guard<std::mutex> g(c->out_mu);
          if (c->outq.empty() && c->close_after) fatal = true;
          if (!fatal && c->outq.empty() && c->want_out) {
            c->want_out = false;
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.ptr = c;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          }
        }
      }
      if (!fatal && (evs[i].events & EPOLLIN)) {
        // level-triggered read: pull what's there, cut complete frames.
        // When no partial frame is pending, frames are cut DIRECTLY
        // from the read buffer (no staging copy); only the trailing
        // partial frame is stashed in c->in — and once a frame IS
        // pending, later reads land straight in c->in's tail (ByteBuf:
        // no zero-fill, no stage-then-copy), so a large frame costs
        // ONE kernel→user copy however many reads deliver it.
        // Responses from one read chunk coalesce into one writev whose
        // large payload views point STRAIGHT into the buffer that was
        // cut — flushed before the next read() can clobber/realloc
        // what they reference.
        constexpr size_t kReadChunk = 1024 * 1024;
        static thread_local ByteBuf rdbuf;
        static thread_local std::string burst;
        static thread_local std::vector<OutPart> oparts;
        rdbuf.reserve(kReadChunk);
        for (;;) {
          burst.clear();
          oparts.clear();
          bool direct = c->in.empty();
          char* dst =
              direct ? reinterpret_cast<char*>(rdbuf.data())
                     : reinterpret_cast<char*>(c->in.tail(kReadChunk));
          // chaos srv_read site: short reads force the in-place
          // partial-frame completion path; EAGAIN/reset/delay model a
          // flaky peer.  Disarmed cost: one relaxed atomic load.
          size_t want = kReadChunk;
          uint64_t farg = 0;
          uint32_t fact = fault_check(FS_SRV_READ, &farg);
          if (fact == FA_SHORT) {
            // min(arg, kReadChunk); arg==0 degenerates to 1 byte
            want = farg == 0 ? 1
                   : farg < kReadChunk ? static_cast<size_t>(farg)
                                       : kReadChunk;
          } else if (fact == FA_EAGAIN) {
            break;  // level-triggered epoll re-delivers the event
          } else if (fact == FA_RESET) {
            fatal = true;
            break;
          } else if (fact == FA_DELAY) {
            fault_sleep_us(farg);
          }
          ssize_t r = ::read(c->fd, dst, want);
          if (r > 0) {
            const uint8_t* data;
            size_t dlen;
            if (direct) {
              data = rdbuf.data();
              dlen = static_cast<size_t>(r);
            } else {
              c->in.advance(static_cast<size_t>(r));
              data = c->in.data();
              dlen = c->in.size();
            }
            size_t off =
                proto_cut(srv, w, c, data, dlen, &burst, &oparts, &fatal);
            if (fatal) break;
            if (!oparts.empty()) {
              // one response-ring window per harvested read cycle —
              // the native-lane half of the ns_ring_stats step log
              srv->ring_windows.fetch_add(1, std::memory_order_relaxed);
              conn_write_parts(w, c, burst, oparts);
            }
            if (c->dead.load()) {
              fatal = true;
              break;
            }
            if (c->close_after) {
              // HTTP "Connection: close": close once the response has
              // fully left (immediately if it went out inline, else
              // when EPOLLOUT drains the queue)
              std::lock_guard<std::mutex> g(c->out_mu);
              if (c->outq.empty()) fatal = true;
              break;
            }
            if (c->py_pending.load(std::memory_order_acquire) > 0) {
              // Python owns the next reply: stop reading (replies must
              // stay ordered) and disarm EPOLLIN — level-triggered
              // epoll would spin otherwise.  ns_py_done re-arms.
              std::lock_guard<std::mutex> g(c->out_mu);
              epoll_event ev{};
              ev.events = c->want_out ? EPOLLOUT : 0;
              ev.data.ptr = c;
              epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
              // stash any uncut remainder before leaving the loop
              if (direct && off < dlen) {
                stash_direct_remainder(&c->in, &rdbuf, off, dlen);
                rdbuf.reserve(kReadChunk);
              } else if (!direct && off) {
                c->in.erase_front(off);
              }
              break;
            }
            if (direct) {
              if (off < dlen) {
                size_t rest = dlen - off;
                if (rest >= kHeader && memcmp(data + off, kMagic, 4) == 0) {
                  uint32_t ms2, bs2;
                  memcpy(&ms2, data + off + 4, 4);
                  memcpy(&bs2, data + off + 8, 4);
                  uint64_t tot =
                      kHeader + (uint64_t)ntohl(ms2) + ntohl(bs2);
                  if (tot <= kMaxBody && (off || rest < kStealThreshold))
                    c->in.reserve(tot);
                }
                stash_direct_remainder(&c->in, &rdbuf, off, dlen);
                rdbuf.reserve(kReadChunk);
              }
            } else if (off) {
              c->in.erase_front(off);
            }
            if (static_cast<size_t>(r) < kReadChunk) break;
            continue;
          }
          if (r == 0) {
            fatal = true;
            break;
          }
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          if (errno == EINTR) continue;
          fatal = true;
          break;
        }
        if (c->dead.load()) fatal = true;
      }
      if (fatal) {
        close_conn(srv, w, c);
        // close purges any deferred resume for this conn (it runs
        // under w->mu against the queue, but our local list was
        // already swapped) — drop it here too
        for (auto it = res_pending.begin(); it != res_pending.end();) {
          it = (*it == c) ? res_pending.erase(it) : it + 1;
        }
      }
    }
    for (Conn* c : res_pending) conn_resume(srv, w, c);
  }
}

void acceptor_loop(NativeServer* srv) {
  while (srv->running.load()) {
    struct pollfd pfd {srv->listen_fd, POLLIN, 0};
    int rc = ::poll(&pfd, 1, 300);
    if (rc <= 0) continue;
    int fd = ::accept4(srv->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) continue;
    set_nodelay(fd);
    Conn* c = new Conn();
    c->fd = fd;
    c->id = srv->next_conn_id.fetch_add(1);
    Worker* w =
        srv->workers[srv->rr.fetch_add(1) % srv->workers.size()];
    {
      std::lock_guard<std::mutex> g(srv->conns_mu);
      srv->conns[c->id] = {w, c};
    }
    {
      std::lock_guard<std::mutex> g(w->mu);
      w->incoming.push_back(c);
    }
    w->notify();
  }
}

// ---------------------------------------------------------------------------
// client pool
// ---------------------------------------------------------------------------

struct PooledFd {
  int fd;
  int rcvtimeo_ms;  // currently-set SO_RCVTIMEO (avoid per-call setsockopt)
};

struct ClientPool {
  std::string host;
  int port;
  int connect_timeout_ms;
  std::mutex mu;
  std::vector<PooledFd> free_fds;
  std::atomic<uint64_t> next_cid{1};
  ~ClientPool() { NS_TSAN_MUTEX_DESTROY(&mu); }
};

void fd_set_timeout(PooledFd* pf, int timeout_ms) {
  if (pf->rcvtimeo_ms == timeout_ms) return;
  struct timeval tv;
  if (timeout_ms < 0) {
    tv.tv_sec = 0;
    tv.tv_usec = 0;  // 0 = block forever
  } else {
    tv.tv_sec = timeout_ms / 1000;
    tv.tv_usec = (timeout_ms % 1000) * 1000;
  }
  setsockopt(pf->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  pf->rcvtimeo_ms = timeout_ms;
}

int pool_connect(ClientPool* p) {
  // host starting with '/' = unix domain socket path (UDS is
  // first-class in the reference's EndPoint too)
  if (!p->host.empty() && p->host[0] == '/') {
    if (p->host.size() >= sizeof(sockaddr_un{}.sun_path)) return -1;
    int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_un ua{};
    ua.sun_family = AF_UNIX;
    snprintf(ua.sun_path, sizeof(ua.sun_path), "%s", p->host.c_str());
    if (::connect(fd, reinterpret_cast<sockaddr*>(&ua), sizeof(ua)) < 0) {
      ::close(fd);
      return -1;
    }
    return fd;
  }
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(p->port));
  if (inet_pton(AF_INET, p->host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  set_nodelay(fd);
  return fd;
}

bool pool_acquire(ClientPool* p, PooledFd* out) {
  {
    std::lock_guard<std::mutex> g(p->mu);
    if (!p->free_fds.empty()) {
      *out = p->free_fds.back();
      p->free_fds.pop_back();
      return true;
    }
  }
  int fd = pool_connect(p);
  if (fd < 0) return false;
  *out = PooledFd{fd, 0};
  return true;
}

void pool_release(ClientPool* p, PooledFd pf) {
  std::lock_guard<std::mutex> g(p->mu);
  p->free_fds.push_back(pf);
}

// ---------------------------------------------------------------------------
// multiplexed async client (reactor): many in-flight RPCs over a few
// connections, submissions batched into single writes, completions
// harvested in batches.  This is the async-CallMethod data path — and
// on a single shared core it is the only honest way past the
// syscall-per-RPC qps ceiling (requests/responses amortize syscalls).
// ---------------------------------------------------------------------------

struct MuxCompletion {
  uint64_t tag;
  int32_t rc;  // 0 | -ETIMEDOUT | -EPIPE
  int32_t error_code;
  int32_t compress_type;
  uint32_t attachment_size;
  uint64_t body_len;
  uint8_t* data;  // malloc'd; consumer calls nc_free
  char error_text[96];  // response meta error_text (truncated)
};

struct MuxConn {
  // atomic: only the reactor writes it (connect/reset), but submitter
  // threads read the `fd < 0` staging-backpressure hint concurrently
  std::atomic<int> fd{-1};
  std::mutex stage_mu;      // guards staged only: submitters vs flush
  std::string staged;       // submitters append under stage_mu
  std::string outbuf;       // reactor-owned write backlog
  size_t out_off = 0;
  ByteBuf in;
  bool want_out = false;
  std::unordered_map<uint64_t, uint64_t> inflight;  // cid → tag (m->mu)
  std::unordered_map<uint64_t, int64_t> deadlines;  // cid → ms clock
  ~MuxConn() { NS_TSAN_MUTEX_DESTROY(&stage_mu); }
};

// One blocking caller parked on its own completion (nc_mux_call): the
// reactor routes the completion straight to the waiter instead of the
// shared done queue, so N sync caller threads multiplex over the same
// few connections with per-call wakeups — no pooled-fd exclusivity and
// no shared-queue thundering herd.  This is how Python sync stubs ride
// the mux reactor (reference: the public CallMethod IS the pipelined
// hot path, channel.cpp:407-584).
struct MuxWaiter {
  std::mutex mu;
  std::condition_variable cv;
  bool ready = false;
  MuxCompletion comp{};
  // stack-allocated: successive call frames reuse the address
  ~MuxWaiter() { NS_TSAN_MUTEX_DESTROY(&mu); }
};

struct MuxClient {
  std::string host;
  int port = 0;
  std::vector<MuxConn*> conns;
  std::mutex mu;  // guards staged buffers, inflight maps, done queue
  std::deque<MuxCompletion> done;
  std::condition_variable done_cv;
  // tag → parked sync caller; tags for waiter calls are the pointer
  // value itself (unique while the call frame lives)
  std::unordered_map<uint64_t, MuxWaiter*> waiters;
  int epfd = -1, wake_fd = -1;
  std::thread reactor;
  std::atomic<uint64_t> next_cid{1};
  std::atomic<bool> stopping{false};
  // suppress redundant wake syscalls: set by submitters, cleared by the
  // reactor right before it flushes (a pipelined submitter stream then
  // pays ~one eventfd write per reactor wake, not one per RPC)
  std::atomic<bool> wake_pending{false};
  // sync-call stats, maintained here so the Python fast path does ZERO
  // per-call recorder work: nc_mux_stats hands these to the channel's
  // LatencyRecorder, which harvests deltas lazily (~1 Hz / on read)
  std::atomic<uint64_t> stat_ok{0};
  std::atomic<uint64_t> stat_fail{0};
  std::atomic<uint64_t> stat_lat_us_sum{0};
  std::atomic<uint64_t> stat_lat_us_max{0};
  // ---- submission/completion ring lane (nc_mux_submit_many /
  // nc_mux_harvest) ----
  // Completions whose tag has kRingTagBit set route to their own queue:
  // the channel's always-running background harvester drains m->done
  // via nc_mux_poll and drops tags it doesn't know, so ring windows
  // need a lane that harvester can never steal from.
  std::deque<MuxCompletion> ring_done;
  std::condition_variable ring_cv;
  // ring step-log counters (nc_mux_ring_stats): a silently-degraded
  // ring — one crossing per call instead of per window — shows up here
  // as windows ≈ calls, and the bench smoke guard fails loudly.
  std::atomic<uint64_t> stat_ring_windows{0};
  std::atomic<uint64_t> stat_ring_calls{0};
  std::atomic<uint64_t> stat_ring_harvests{0};
  std::atomic<uint64_t> stat_ring_completions{0};
  ~MuxClient() { NS_TSAN_MUTEX_DESTROY(&mu); }
};

// Tag bit that routes a completion to the ring lane instead of the
// shared done queue (set by the Python side when reserving ring tags).
constexpr uint64_t kRingTagBit = 1ull << 63;

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000ll + ts.tv_nsec / 1000000;
}

void mux_complete_locked(MuxClient* m, uint64_t tag, int rc, MetaView* mv,
                         uint8_t* body, uint64_t blen) {
  MuxCompletion c{};
  c.tag = tag;
  c.rc = rc;
  if (mv) {
    c.error_code = mv->error_code;
    c.compress_type = static_cast<int32_t>(mv->compress_type);
    c.attachment_size = static_cast<uint32_t>(mv->attachment_size);
    if (!mv->error_text.empty())
      snprintf(c.error_text, sizeof(c.error_text), "%s",
               mv->error_text.c_str());
  }
  c.data = body;
  c.body_len = blen;
  // a parked sync caller gets its completion directly (and its own
  // wakeup); everything else goes to the shared done queue
  auto wit = m->waiters.find(tag);
  if (wit != m->waiters.end()) {
    MuxWaiter* wtr = wit->second;
    m->waiters.erase(wit);
    {
      std::lock_guard<std::mutex> wg(wtr->mu);
      wtr->comp = c;
      wtr->ready = true;
      // notify UNDER wtr->mu: the waiter lives on nc_mux_call's STACK,
      // and the instant it can observe ready=true unlocked it may
      // return and destroy the frame — a notify after releasing the
      // lock races the condvar's destruction (caught by the TSan lane).
      // Held, the waiter cannot leave pthread_cond_wait until we drop
      // the mutex, and we touch nothing of *wtr after this scope.
      wtr->cv.notify_one();
    }
    return;
  }
  if (tag & kRingTagBit) {
    m->ring_done.push_back(c);
    return;
  }
  m->done.push_back(c);
}

// Non-blocking connect with a BOUNDED wait (200ms): the reactor thread
// calls this, and an unbounded kernel connect timeout (~2min) would
// stall every other connection's IO and the timeout sweep.
bool mux_connect(MuxClient* m, MuxConn* c) {
  // host starting with '/' = unix-domain path, like pool_connect
  sockaddr_storage ss{};
  socklen_t slen;
  int fd;
  if (!m->host.empty() && m->host[0] == '/') {
    if (m->host.size() >= sizeof(sockaddr_un{}.sun_path)) return false;
    sockaddr_un* ua = reinterpret_cast<sockaddr_un*>(&ss);
    ua->sun_family = AF_UNIX;
    snprintf(ua->sun_path, sizeof(ua->sun_path), "%s", m->host.c_str());
    slen = sizeof(sockaddr_un);
    fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  } else {
    sockaddr_in* addr = reinterpret_cast<sockaddr_in*>(&ss);
    addr->sin_family = AF_INET;
    addr->sin_port = htons(static_cast<uint16_t>(m->port));
    if (inet_pton(AF_INET, m->host.c_str(), &addr->sin_addr) != 1)
      return false;
    slen = sizeof(sockaddr_in);
    fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  }
  if (fd < 0) return false;
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&ss), slen);
  if (rc < 0 && errno == EINPROGRESS) {
    struct pollfd pfd {fd, POLLOUT, 0};
    if (::poll(&pfd, 1, 200) <= 0) {
      ::close(fd);
      return false;
    }
    int err = 0;
    socklen_t elen = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen);
    if (err != 0) {
      ::close(fd);
      return false;
    }
  } else if (rc < 0) {
    ::close(fd);
    return false;
  }
  set_nodelay(fd);
  c->fd = fd;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = c;
  epoll_ctl(m->epfd, EPOLL_CTL_ADD, fd, &ev);
  return true;
}

// fail everything in flight on this conn and reconnect
void mux_conn_reset(MuxClient* m, MuxConn* c) {
  std::vector<std::pair<uint64_t, uint64_t>> dead;
  // order matters against a concurrent submitter (which registers its
  // cid under m->mu FIRST, then stages under stage_mu): clearing
  // staged before inflight means any call whose frame we wipe still
  // has its cid in inflight when we sweep it below → it gets -EPIPE.
  // The opposite order could wipe a frame while keeping its cid,
  // leaving a deadline-less call parked forever.
  {
    std::lock_guard<std::mutex> g(c->stage_mu);
    c->staged.clear();
  }
  {
    std::lock_guard<std::mutex> g(m->mu);
    for (auto& kv : c->inflight) dead.push_back({kv.first, kv.second});
    c->inflight.clear();
    c->deadlines.clear();
  }
  c->outbuf.clear();
  c->out_off = 0;
  c->in.clear();
  c->want_out = false;
  if (c->fd >= 0) {
    epoll_ctl(m->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    ::close(c->fd);
    c->fd = -1;
  }
  {
    std::lock_guard<std::mutex> g(m->mu);
    for (auto& d : dead) mux_complete_locked(m, d.second, -EPIPE, nullptr,
                                             nullptr, 0);
  }
  if (!dead.empty()) {
    m->done_cv.notify_all();
    m->ring_cv.notify_all();
  }
  if (!m->stopping.load()) mux_connect(m, c);
}

void mux_flush(MuxClient* m, MuxConn* c) {
  {
    std::lock_guard<std::mutex> g(c->stage_mu);
    if (!c->staged.empty()) {
      if (c->outbuf.empty()) {
        c->outbuf.swap(c->staged);
        c->out_off = 0;
      } else {
        c->outbuf += c->staged;
        c->staged.clear();
      }
    }
  }
  if (c->fd < 0) return;
  while (c->out_off < c->outbuf.size()) {
    ssize_t n = ::write(c->fd, c->outbuf.data() + c->out_off,
                        c->outbuf.size() - c->out_off);
    if (n > 0) {
      c->out_off += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && errno == EINTR) continue;
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    mux_conn_reset(m, c);
    return;
  }
  if (c->out_off == c->outbuf.size()) {
    c->outbuf.clear();
    c->out_off = 0;
    if (c->want_out) {
      c->want_out = false;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.ptr = c;
      epoll_ctl(m->epfd, EPOLL_CTL_MOD, c->fd, &ev);
    }
  } else if (!c->want_out) {
    c->want_out = true;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.ptr = c;
    epoll_ctl(m->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

// Cut response frames from [data, data+len); returns consumed bytes or
// SIZE_MAX if the connection was reset (caller must bail immediately).
size_t mux_cut_frames(MuxClient* m, MuxConn* c, const uint8_t* data,
                      size_t len, bool* notified) {
  size_t off = 0;
  while (true) {
    size_t avail = len - off;
    if (avail < kHeader) break;
    const uint8_t* p = data + off;
    if (memcmp(p, kMagic, 4) != 0) {
      mux_conn_reset(m, c);
      return SIZE_MAX;
    }
    uint32_t ms, bs;
    memcpy(&ms, p + 4, 4);
    memcpy(&bs, p + 8, 4);
    ms = ntohl(ms);
    bs = ntohl(bs);
    if (static_cast<uint64_t>(ms) + bs > kMaxBody) {
      mux_conn_reset(m, c);
      return SIZE_MAX;
    }
    size_t total = kHeader + ms + bs;
    if (avail < total) break;
    MetaView mv;
    if (parse_meta(p + kHeader, ms, &mv) && mv.attachment_size <= bs) {
      std::lock_guard<std::mutex> g(m->mu);
      auto it = c->inflight.find(mv.correlation_id);
      if (it != c->inflight.end()) {
        uint8_t* body = static_cast<uint8_t*>(malloc(bs ? bs : 1));
        memcpy(body, p + kHeader + ms, bs);
        mux_complete_locked(m, it->second, 0, &mv, body, bs);
        c->inflight.erase(it);
        c->deadlines.erase(mv.correlation_id);
        *notified = true;
      }
    }
    off += total;
  }
  return off;
}

void mux_read(MuxClient* m, MuxConn* c) {
  // Same direct-cut structure as the server worker: frames are parsed
  // straight out of the read buffer; only a trailing partial frame is
  // staged in c->in, and later reads complete it IN PLACE (ByteBuf
  // tail reads — no stage-then-copy for multi-read frames).
  constexpr size_t kMuxReadChunk = 512 * 1024;
  static thread_local ByteBuf rdbuf;
  rdbuf.reserve(kMuxReadChunk);
  bool notified = false;
  for (;;) {
    bool direct = c->in.empty();
    char* dst = direct
                    ? reinterpret_cast<char*>(rdbuf.data())
                    : reinterpret_cast<char*>(c->in.tail(kMuxReadChunk));
    ssize_t r = ::read(c->fd, dst, kMuxReadChunk);
    if (r > 0) {
      const uint8_t* data;
      size_t dlen;
      if (direct) {
        data = rdbuf.data();
        dlen = static_cast<size_t>(r);
      } else {
        c->in.advance(static_cast<size_t>(r));
        data = c->in.data();
        dlen = c->in.size();
      }
      size_t off = mux_cut_frames(m, c, data, dlen, &notified);
      if (off == SIZE_MAX) {  // reset: c->in already cleared
        if (notified) {
          m->done_cv.notify_all();
          m->ring_cv.notify_all();
        }
        return;
      }
      if (direct) {
        if (off < dlen) {
          stash_direct_remainder(&c->in, &rdbuf, off, dlen);
          rdbuf.reserve(kMuxReadChunk);
        }
      } else if (off) {
        c->in.erase_front(off);
      }
      if (static_cast<size_t>(r) < kMuxReadChunk) break;
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    if (r < 0 && errno == EINTR) continue;
    mux_conn_reset(m, c);
    break;
  }
  if (notified) {
    m->done_cv.notify_all();
    m->ring_cv.notify_all();
  }
}

void mux_sweep_timeouts(MuxClient* m) {
  int64_t now = now_ms();
  bool notified = false;
  std::lock_guard<std::mutex> g(m->mu);
  for (MuxConn* c : m->conns) {
    for (auto it = c->deadlines.begin(); it != c->deadlines.end();) {
      if (it->second >= 0 && now > it->second) {
        auto fit = c->inflight.find(it->first);
        if (fit != c->inflight.end()) {
          mux_complete_locked(m, fit->second, -ETIMEDOUT, nullptr, nullptr, 0);
          c->inflight.erase(fit);
          notified = true;
        }
        it = c->deadlines.erase(it);
      } else {
        ++it;
      }
    }
  }
  if (notified) {
    m->done_cv.notify_all();
    m->ring_cv.notify_all();
  }
}

void mux_reactor(MuxClient* m) {
  epoll_event evs[64];
  int64_t last_sweep = now_ms();
  // wake_pending protocol: submitters skip the eventfd syscall while it
  // is already true.  The reactor leaves it TRUE across busy cycles —
  // flushing staged work every loop anyway — and clears it only right
  // before blocking in epoll (re-checking staged after the clear to
  // close the race).  Under steady pipelined load this reduces wakeup
  // syscalls to ~zero: the exchange() in submit sees true and skips.
  while (!m->stopping.load()) {
    bool busy = m->wake_pending.load(std::memory_order_relaxed);
    int timeout_ms = 50;
    if (busy) {
      timeout_ms = 0;  // work may be staged: poll IO, don't block
    } else {
      // nothing pending when we looked; block until IO or a wake
      timeout_ms = 50;
    }
    int n = epoll_wait(m->epfd, evs, 64, timeout_ms);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      if (evs[i].data.ptr == nullptr) {
        uint64_t junk;
        while (::read(m->wake_fd, &junk, sizeof(junk)) > 0) {
        }
        continue;
      }
      MuxConn* c = static_cast<MuxConn*>(evs[i].data.ptr);
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        mux_conn_reset(m, c);
        continue;
      }
      if (evs[i].events & EPOLLIN) mux_read(m, c);
      if (c->fd >= 0 && (evs[i].events & EPOLLOUT)) mux_flush(m, c);
    }
    if (busy) {
      // consume the pending flag only when about to potentially block
      // next cycle; staged bytes appended after this store trigger a
      // fresh wake (or are caught by the post-clear flush below)
      m->wake_pending.store(false);
    }
    // flush staged submissions every cycle (covers both the woken case
    // and bytes staged after the clear above)
    for (MuxConn* c : m->conns)
      if (c->fd >= 0) mux_flush(m, c);
    int64_t now = now_ms();
    if (now - last_sweep >= 20) {
      mux_sweep_timeouts(m);
      last_sweep = now;
      // revive dead connections (a failed (re)connect leaves fd=-1;
      // staged submissions accumulated meanwhile flush on success)
      for (MuxConn* c : m->conns) {
        if (c->fd < 0 && !m->stopping.load() && mux_connect(m, c))
          mux_flush(m, c);
      }
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// ---- fault injection (chaos/) ----
// Program one site's fault knob (process-wide; see FaultSite /
// FaultAction above).  prob_u32 is the fire threshold out of 2^32
// (0xffffffff ~= always); max_hits < 0 = unlimited.  Counters reset.
void ns_set_fault(int site, int action, uint64_t arg, uint32_t prob_u32,
                  uint64_t seed, long long max_hits) {
  if (site < 0 || site >= FS_COUNT) return;
  FaultState& f = g_faults[site];
  f.arg.store(arg, std::memory_order_relaxed);
  f.prob.store(prob_u32, std::memory_order_relaxed);
  f.seed.store(seed, std::memory_order_relaxed);
  f.max_hits.store(max_hits, std::memory_order_relaxed);
  f.evals.store(0, std::memory_order_relaxed);
  f.hits.store(0, std::memory_order_relaxed);
  f.action.store(static_cast<uint32_t>(action), std::memory_order_release);
  uint32_t any = 0;
  for (int i = 0; i < FS_COUNT; i++)
    if (g_faults[i].action.load(std::memory_order_relaxed)) any = 1;
  g_faults_armed.store(any, std::memory_order_release);
}

void ns_clear_faults() {
  for (int i = 0; i < FS_COUNT; i++) {
    g_faults[i].action.store(0, std::memory_order_relaxed);
    g_faults[i].evals.store(0, std::memory_order_relaxed);
    g_faults[i].hits.store(0, std::memory_order_relaxed);
  }
  g_faults_armed.store(0, std::memory_order_release);
}

unsigned long long ns_fault_hits(int site) {
  if (site < 0 || site >= FS_COUNT) return 0;
  return g_faults[site].hits.load(std::memory_order_relaxed);
}

// ---- server ----
void* ns_create() { return new NativeServer(); }

void ns_set_dispatch(void* h, PyDispatch cb) {
  static_cast<NativeServer*>(h)->dispatch = cb;
}

// Register an arbitrary native method handler (generic dispatch: the
// same hook the built-in echo uses).  Must be called before ns_listen.
void ns_register_native_method(void* h, const char* service,
                               const char* method, NativeMethodFn fn,
                               void* user_data) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  NativeMethod* nm = srv->method_get_or_create(service, method);
  nm->fn = fn;
  nm->user_data = user_data;
}

void ns_register_native_echo(void* h, const char* service, const char* method,
                             int attach_echo) {
  ns_register_native_method(
      h, service, method, builtin_echo_method,
      reinterpret_cast<void*>(static_cast<intptr_t>(attach_echo ? 1 : 0)));
}

// response-builder appends for native handlers (callable from any
// language that can hold a C pointer)
void ns_resp_append_payload(void* resp_ctx, const uint8_t* data,
                            uint64_t len) {
  static_cast<NativeRespCtx*>(resp_ctx)->payload_owned(
      reinterpret_cast<const char*>(data), len);
}

void ns_resp_append_attachment(void* resp_ctx, const uint8_t* data,
                               uint64_t len) {
  static_cast<NativeRespCtx*>(resp_ctx)->attachment.append(
      reinterpret_cast<const char*>(data), len);
}

// enable extra wire protocols on the port (bitmask of ConnProto bits;
// tpu_std is always on).  Call before ns_listen.
void ns_enable_protocols(void* h, uint32_t mask) {
  static_cast<NativeServer*>(h)->proto_mask |= mask;
}

// register a native HTTP handler for `path` (request body → handler →
// response body; 200 on rc 0, 500 on rc>0, rc<0 declines to Python).
// Must be called before ns_listen.
void ns_register_native_http(void* h, const char* path, NativeMethodFn fn,
                             void* user_data) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  std::lock_guard<std::mutex> g(srv->reg_mu);
  auto it = srv->http_methods.find(path);
  NativeMethod* nm;
  if (it != srv->http_methods.end()) {
    nm = it->second;
  } else {
    nm = new NativeMethod();
    srv->http_methods[path] = nm;
    // expose stats under ("http", path) for ns_method_stats
    srv->methods[std::string("http") + '\0' + path] = nm;
  }
  nm->fn = fn;
  nm->user_data = user_data;
}

void ns_register_native_http_echo(void* h, const char* path) {
  ns_register_native_http(h, path, builtin_http_echo, nullptr);
}

// answer GET/SET/DEL/EXISTS/INCR/PING natively from a sharded in-engine
// KV map (the redis_server example's C++ RedisService, natively);
// unrecognized commands still dispatch to the Python RedisService
void ns_redis_enable_native_kv(void* h) {
  static_cast<NativeServer*>(h)->redis_native_kv = true;
}

// 0 = unlimited.  Callable while serving (harvest loops push updated
// auto-limiter values through this) — lookup-only, because inserting
// into the map would race the lock-free worker reads.
void ns_set_method_max_concurrency(void* h, const char* service,
                                   const char* method, int32_t limit) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  std::lock_guard<std::mutex> g(srv->reg_mu);
  auto it = srv->methods.find(std::string(service) + '\0' + method);
  if (it != srv->methods.end())
    it->second->max_concurrency.store(limit, std::memory_order_relaxed);
}

// out[0]=count out[1]=latency_ns_sum out[2]=rejected out[3]=errors
// (cumulative; the Python harvester diffs against its last snapshot)
int ns_method_stats(void* h, const char* service, const char* method,
                    uint64_t* out) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  std::lock_guard<std::mutex> g(srv->reg_mu);
  auto it = srv->methods.find(std::string(service) + '\0' + method);
  if (it == srv->methods.end()) return -1;
  NativeMethod* nm = it->second;
  out[0] = nm->count.load(std::memory_order_relaxed);
  out[1] = nm->latency_ns_sum.load(std::memory_order_relaxed);
  out[2] = nm->rejected.load(std::memory_order_relaxed);
  out[3] = nm->errors.load(std::memory_order_relaxed);
  return 0;
}

// returns bound port (0 for UDS), or -errno. host starting with '/'
// listens on that unix-domain path instead of TCP.
int ns_listen(void* h, const char* host, int port, int nworkers) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  int fd;
  sockaddr_in bound{};
  if (host && host[0] == '/') {
    if (strlen(host) >= sizeof(sockaddr_un{}.sun_path))
      return -ENAMETOOLONG;  // silent truncation would bind elsewhere
    sockaddr_un ua{};
    ua.sun_family = AF_UNIX;
    snprintf(ua.sun_path, sizeof(ua.sun_path), "%s", host);
    // only remove a STALE socket file: hijacking a live server's path
    // must fail with EADDRINUSE like the TCP bind would
    int probe = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (probe >= 0) {
      if (::connect(probe, reinterpret_cast<sockaddr*>(&ua), sizeof(ua)) ==
          0) {
        ::close(probe);
        return -EADDRINUSE;
      }
      ::close(probe);
    }
    ::unlink(host);
    fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    if (::bind(fd, reinterpret_cast<sockaddr*>(&ua), sizeof(ua)) < 0 ||
        ::listen(fd, 1024) < 0) {
      int e = errno;
      ::close(fd);
      return -e;
    }
  } else {
    fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -EINVAL;
    }
    if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        ::listen(fd, 1024) < 0) {
      int e = errno;
      ::close(fd);
      return -e;
    }
    socklen_t blen = sizeof(bound);
    getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
  }
  srv->listen_fd = fd;
  srv->running.store(true);
  if (nworkers < 1) nworkers = 1;
  for (int i = 0; i < nworkers; i++) {
    Worker* w = new Worker();
    w->srv = srv;
    w->epfd = epoll_create1(0);
    w->wake_fd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;
    epoll_ctl(w->epfd, EPOLL_CTL_ADD, w->wake_fd, &ev);
    srv->workers.push_back(w);
    srv->threads.emplace_back(worker_loop, srv, w);
  }
  srv->acceptor = std::thread(acceptor_loop, srv);
  return ntohs(bound.sin_port);
}

// thread-safe response send from Python fallback handlers
int ns_send(void* h, uint64_t conn_id, const uint8_t* data, uint64_t len) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  // conns_mu held for the whole send: close_conn erases under the same
  // lock before deleting, so the Conn cannot be freed under us
  std::lock_guard<std::mutex> g(srv->conns_mu);
  auto it = srv->conns.find(conn_id);
  if (it == srv->conns.end()) return -ENOTCONN;
  Worker* w = it->second.first;
  Conn* c = it->second.second;
  conn_queue_write(w, c, std::string(reinterpret_cast<const char*>(data), len));
  return c->dead.load() ? -EPIPE : 0;
}

// Server response ring: flush one harvested window of completions for a
// connection as ONE scatter-gather burst (the server half of
// nc_mux_submit_many).  Small frames coalesce into a contiguous burst
// range — a window of 4KB replies reaches the kernel through a SINGLE
// iovec — while frames ≥ kViewThreshold ride writev as borrowed views.
// Views are safe: the caller's frame bytes outlive this call, and
// conn_write_parts COPIES any unsent remainder into the outq before
// returning, so nothing borrowed survives the call.
int ns_send_burst(void* h, uint64_t conn_id, const uint8_t* const* frames,
                  const uint64_t* lens, int n) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  // conns_mu held for the whole burst, same lifetime rule as ns_send
  std::lock_guard<std::mutex> g(srv->conns_mu);
  auto it = srv->conns.find(conn_id);
  if (it == srv->conns.end()) return -ENOTCONN;
  Worker* w = it->second.first;
  Conn* c = it->second.second;
  // heap holders with trivially-destructible TLS slots, NOT plain
  // thread_local objects: ns_send_burst runs on Python-created threads
  // (server dispatch), and a C++ TLS destructor registered there races
  // glibc's _dl_deallocate_tls at thread exit (TSan-visible).  The
  // buffers intentionally live for the thread's lifetime to keep
  // capacity warm across windows.
  thread_local std::string* burst_p = new std::string();
  thread_local std::vector<OutPart>* parts_p = new std::vector<OutPart>();
  std::string& burst = *burst_p;
  std::vector<OutPart>& parts = *parts_p;
  burst.clear();
  parts.clear();
  for (int i = 0; i < n; i++) {
    if (lens[i] >= kViewThreshold) {
      parts.push_back(
          {true, reinterpret_cast<size_t>(frames[i]), (size_t)lens[i]});
    } else {
      size_t base = burst.size();
      burst.append(reinterpret_cast<const char*>(frames[i]), lens[i]);
      parts_add_burst_range(&parts, base, (size_t)lens[i]);
    }
  }
  srv->ring_windows.fetch_add(1, std::memory_order_relaxed);
  srv->ring_responses.fetch_add((uint64_t)n, std::memory_order_relaxed);
  conn_write_parts(w, c, burst, parts);
  return c->dead.load() ? -EPIPE : 0;
}

// out[0..2] = ring windows flushed, responses carried, writev bursts
void ns_ring_stats(void* h, uint64_t* out) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  out[0] = srv->ring_windows.load(std::memory_order_relaxed);
  out[1] = srv->ring_responses.load(std::memory_order_relaxed);
  out[2] = srv->flush_bursts.load(std::memory_order_relaxed);
}

// Python finished answering a dispatched http/redis frame: resume
// cutting (and reading) the connection.  Pairs 1:1 with each
// P_HTTP/P_REDIS dispatch callback.
void ns_py_done(void* h, uint64_t conn_id) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  // conns_mu held across the resume push: close_conn purges the
  // worker's resume list under w->mu BEFORE delete, but only for
  // entries already pushed — holding conns_mu here means a concurrent
  // close either runs fully before us (we find nothing) or after our
  // push (purge removes it)
  std::lock_guard<std::mutex> g(srv->conns_mu);
  auto it = srv->conns.find(conn_id);
  if (it == srv->conns.end()) return;
  Worker* w = it->second.first;
  Conn* c = it->second.second;
  if (c->py_pending.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    {
      std::lock_guard<std::mutex> g2(w->mu);
      w->resume.push_back(c);
    }
    w->notify();
  }
}

// Python fallback asks to close (Controller::CloseConnection analog)
void ns_close_conn(void* h, uint64_t conn_id) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  std::lock_guard<std::mutex> g(srv->conns_mu);
  auto it = srv->conns.find(conn_id);
  if (it == srv->conns.end()) return;
  Conn* c = it->second.second;
  c->dead.store(true);
  it->second.first->notify();
  // actual close happens on the worker when the conn next polls
  // readable.  The shutdown rides out_mu like every other fd user:
  // close_conn closes + invalidates the fd under that lock, so we can
  // never shut down a recycled fd number (TSan-lane finding).
  {
    std::lock_guard<std::mutex> g2(c->out_mu);
    if (c->fd >= 0) ::shutdown(c->fd, SHUT_RDWR);
  }
}

void ns_stop(void* h) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  if (!srv->running.exchange(false)) return;
  ::close(srv->listen_fd);
  if (srv->acceptor.joinable()) srv->acceptor.join();
  for (Worker* w : srv->workers) {
    w->stop.store(true);
    w->notify();
  }
  for (auto& t : srv->threads) t.join();
  {
    std::lock_guard<std::mutex> g(srv->conns_mu);
    for (auto& kv : srv->conns) {
      ::close(kv.second.second->fd);
      delete kv.second.second;
    }
    srv->conns.clear();
  }
  for (Worker* w : srv->workers) {
    ::close(w->epfd);
    ::close(w->wake_fd);
    delete w;
  }
  srv->workers.clear();
  srv->threads.clear();
}

void ns_destroy(void* h) {
  ns_stop(h);
  delete static_cast<NativeServer*>(h);
}

// ---- client ----
void* nc_pool_create(const char* host, int port, int connect_timeout_ms) {
  ClientPool* p = new ClientPool();
  p->host = host;
  p->port = port;
  p->connect_timeout_ms = connect_timeout_ms;
  return p;
}

void nc_pool_destroy(void* h) {
  ClientPool* p = static_cast<ClientPool*>(h);
  {
    std::lock_guard<std::mutex> g(p->mu);
    for (PooledFd& pf : p->free_fds) ::close(pf.fd);
  }
  delete p;
}

// Response out-params struct (mirrored by ctypes)
struct NcResponse {
  uint8_t* data;        // malloc'd full body (payload+attachment); nc_free it
  uint64_t body_len;
  uint64_t attachment_size;
  int32_t error_code;
  int32_t compress_type;  // response meta compress_type (Python decompresses)
  char error_text[240];
};

void nc_free(uint8_t* p) { free(p); }

// One pooled-connection RPC round trip.  Packs meta in C, writes
// header+meta+payload(+attachment), reads exactly one response frame
// for our correlation id.  Returns 0 ok; -ETIMEDOUT; -EPIPE on IO fail;
// -EBADMSG on protocol garbage.
int nc_call(void* h, const char* service, const char* method, uint64_t log_id,
            const uint8_t* payload, uint64_t payload_len,
            const uint8_t* attachment, uint64_t attachment_len, int timeout_ms,
            NcResponse* out) {
  ClientPool* p = static_cast<ClientPool*>(h);
  out->data = nullptr;
  out->body_len = 0;
  out->attachment_size = 0;
  out->error_code = 0;
  out->error_text[0] = 0;
  uint64_t cid = p->next_cid.fetch_add(1);
  std::string meta =
      pack_request_meta(service, strlen(service), method, strlen(method), cid,
                        attachment_len, log_id);
  // header+meta in one small buffer; payload/attachment ride writev
  // straight from the caller's memory — zero user-space copies on the
  // large-payload path (small payloads coalesce below so tiny requests
  // still cost ONE syscall)
  std::string hm;
  hm.reserve(kHeader + meta.size() +
             (payload_len + attachment_len < kViewThreshold
                  ? payload_len + attachment_len
                  : 0));
  hm.resize(kHeader);
  put_header(&hm[0], meta.size(), payload_len + attachment_len);
  hm += meta;
  bool coalesce = payload_len + attachment_len < kViewThreshold;
  if (coalesce) {
    if (payload_len)
      hm.append(reinterpret_cast<const char*>(payload), payload_len);
    if (attachment_len)
      hm.append(reinterpret_cast<const char*>(attachment), attachment_len);
  }

  // one reconnect retry on stale pooled fd (server may have closed it)
  for (int attempt = 0; attempt < 2; attempt++) {
    PooledFd pf;
    if (attempt == 0) {
      if (!pool_acquire(p, &pf)) return -ECONNREFUSED;
    } else {
      int fd = pool_connect(p);
      if (fd < 0) return -ECONNREFUSED;
      pf = PooledFd{fd, 0};
    }
    fd_set_timeout(&pf, timeout_ms);
    bool wrote;
    if (coalesce) {
      wrote = write_all(pf.fd, hm.data(), hm.size());
    } else {
      iovec iov[3];
      iov[0] = {const_cast<char*>(hm.data()), hm.size()};
      int cnt = 1;
      if (payload_len)
        iov[cnt++] = {const_cast<uint8_t*>(payload), payload_len};
      if (attachment_len)
        iov[cnt++] = {const_cast<uint8_t*>(attachment), attachment_len};
      wrote = writev_all(pf.fd, iov, cnt);
    }
    if (!wrote) {
      ::close(pf.fd);
      continue;  // stale fd: retry once on a fresh connection
    }
    // single recv loop: header lands with (usually all of) the body in
    // one read; SO_RCVTIMEO supplies the deadline with no poll() calls.
    // The staging buffer is capped at the view threshold: small
    // responses still complete in one recv, while anything larger
    // spills at most 16KB and then reads STRAIGHT into the body malloc
    // (a 64KB staging buffer re-copied most of a 64KB response).
    uint8_t hdr_buf[16 * 1024];
    size_t have = 0;
    uint32_t ms = 0, bs = 0;
    uint8_t* body = nullptr;  // malloc'd once sizes are known
    std::vector<uint8_t> meta_buf;
    bool fail = false, timed_out = false;
    size_t total_rest = 0;  // ms + bs
    while (true) {
      if (have >= kHeader && body == nullptr) {
        if (memcmp(hdr_buf, kMagic, 4) != 0) {
          fail = true;
          break;
        }
        memcpy(&ms, hdr_buf + 4, 4);
        memcpy(&bs, hdr_buf + 8, 4);
        ms = ntohl(ms);
        bs = ntohl(bs);
        if (static_cast<uint64_t>(ms) + bs > kMaxBody) {
          fail = true;
          break;
        }
        total_rest = static_cast<size_t>(ms) + bs;
        meta_buf.resize(ms);
        body = static_cast<uint8_t*>(malloc(bs ? bs : 1));
        // move any bytes already read past the header into place
        size_t extra = have - kHeader;
        if (extra > total_rest) {  // trailing garbage beyond our frame
          fail = true;
          break;
        }
        size_t mcopy = extra < ms ? extra : ms;
        memcpy(meta_buf.data(), hdr_buf + kHeader, mcopy);
        if (extra > mcopy)
          memcpy(body, hdr_buf + kHeader + mcopy, extra - mcopy);
        have = kHeader + extra;
      }
      if (body != nullptr && have == kHeader + total_rest) break;
      // choose destination for the next read
      char* dst;
      size_t want;
      if (body == nullptr) {
        dst = reinterpret_cast<char*>(hdr_buf) + have;
        want = sizeof(hdr_buf) - have;
      } else {
        size_t got_rest = have - kHeader;
        if (got_rest < ms) {
          dst = reinterpret_cast<char*>(meta_buf.data()) + got_rest;
          want = ms - got_rest;
        } else {
          dst = reinterpret_cast<char*>(body) + (got_rest - ms);
          want = total_rest - got_rest;
        }
      }
      ssize_t r = ::recv(pf.fd, dst, want, 0);
      if (r > 0) {
        have += static_cast<size_t>(r);
        continue;
      }
      if (r < 0 && errno == EINTR) continue;
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        timed_out = true;  // SO_RCVTIMEO expired
        break;
      }
      fail = true;  // EOF or hard error
      break;
    }
    if (timed_out) {
      free(body);
      ::close(pf.fd);
      return -ETIMEDOUT;
    }
    if (fail) {
      bool fresh_fd_never_answered = (body == nullptr && have == 0);
      free(body);
      ::close(pf.fd);
      if (attempt == 0 && fresh_fd_never_answered)
        continue;  // reset while idle in pool → retry once
      return body == nullptr && have < kHeader ? -EPIPE : -EBADMSG;
    }
    MetaView m;
    if (!parse_meta(meta_buf.data(), ms, &m) || m.correlation_id != cid) {
      // one-in-flight per fd: a mismatched cid means the fd carried
      // stale state — don't pool it back
      free(body);
      ::close(pf.fd);
      return -EBADMSG;
    }
    if (m.attachment_size > bs) {  // server-controlled size: validate
      free(body);
      ::close(pf.fd);
      return -EBADMSG;
    }
    pool_release(p, pf);
    out->data = body;
    out->body_len = bs;
    out->attachment_size = m.attachment_size;
    out->error_code = m.error_code;
    out->compress_type = static_cast<int32_t>(m.compress_type);
    snprintf(out->error_text, sizeof(out->error_text), "%s",
             m.error_text.c_str());
    return 0;
  }
  return -EPIPE;
}

// ---- multiplexed async client ----
void* nc_mux_create(const char* host, int port, int nconns) {
  MuxClient* m = new MuxClient();
  m->host = host;
  m->port = port;
  m->epfd = epoll_create1(0);
  m->wake_fd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;
  epoll_ctl(m->epfd, EPOLL_CTL_ADD, m->wake_fd, &ev);
  if (nconns < 1) nconns = 1;
  for (int i = 0; i < nconns; i++) {
    MuxConn* c = new MuxConn();
    if (!mux_connect(m, c)) {
      // leave fd=-1; reactor retries via reset on use
    }
    m->conns.push_back(c);
  }
  m->reactor = std::thread(mux_reactor, m);
  return m;
}

// enqueue one RPC; returns the correlation id (>0) or 0 on shutdown
uint64_t nc_mux_submit(void* h, const char* service, const char* method,
                       uint64_t log_id, const uint8_t* payload,
                       uint64_t payload_len, const uint8_t* attachment,
                       uint64_t attachment_len, int timeout_ms,
                       uint64_t tag) {
  MuxClient* m = static_cast<MuxClient*>(h);
  if (m->stopping.load()) return 0;
  uint64_t cid = m->next_cid.fetch_add(1);
  std::string meta =
      pack_request_meta(service, strlen(service), method, strlen(method), cid,
                        attachment_len, log_id);
  MuxConn* c = m->conns[cid % m->conns.size()];
  int64_t deadline = timeout_ms > 0 ? now_ms() + timeout_ms : -1;
  // register the cid BEFORE staging bytes: once staged, the reactor
  // may flush and the response may arrive — an unregistered cid's
  // response would be dropped.  Maps ride m->mu, staging rides the
  // per-conn stage_mu so submitters don't contend with the reactor's
  // completion processing.
  {
    std::lock_guard<std::mutex> g(m->mu);
    c->inflight[cid] = tag;
    c->deadlines[cid] = deadline;
  }
  {
    std::lock_guard<std::mutex> g(c->stage_mu);
    if (c->fd < 0 && c->staged.size() > (16u << 20)) {
      // connection down and backlog already deep: fail fast instead of
      // queueing without bound (deadline-less submits would otherwise
      // grow staged forever against a dead peer)
      std::lock_guard<std::mutex> g2(m->mu);
      c->inflight.erase(cid);
      c->deadlines.erase(cid);
      return 0;
    }
    size_t base = c->staged.size();
    c->staged.resize(base + kHeader);
    put_header(&c->staged[base], meta.size(), payload_len + attachment_len);
    c->staged += meta;
    if (payload_len)
      c->staged.append(reinterpret_cast<const char*>(payload), payload_len);
    if (attachment_len)
      c->staged.append(reinterpret_cast<const char*>(attachment),
                       attachment_len);
  }
  if (!m->wake_pending.exchange(true)) {
    uint64_t one = 1;
    ssize_t r = ::write(m->wake_fd, &one, sizeof(one));
    (void)r;
  }
  return cid;
}

// Stage a WINDOW of n same-method RPCs in one crossing: ONE cid-range
// registration under m->mu, ONE staging append under the conn's
// stage_mu, ONE reactor wake — amortizing nc_mux_submit's three
// lock/syscall touches over the whole window.  The whole window lands
// on one connection so the reactor flushes it as one writev burst and
// the server's cut loop sees it as one read burst (the PR 5 batcher
// then accumulates it as one window).  Tags are tag_base + i; the
// caller sets kRingTagBit in tag_base so completions route to the
// ring lane (nc_mux_harvest), not the shared done queue.  Returns the
// number of calls staged: k < n means calls k..n-1 were NOT staged
// (shutdown or a dead conn with a deep backlog) and the caller must
// fail those slots itself.
int nc_mux_submit_many(void* h, const char* service, const char* method,
                       uint64_t log_id, const uint8_t* const* payloads,
                       const uint64_t* lens, int n, int timeout_ms,
                       uint64_t tag_base) {
  MuxClient* m = static_cast<MuxClient*>(h);
  if (n <= 0 || m->stopping.load()) return 0;
  uint64_t cid0 = m->next_cid.fetch_add(static_cast<uint64_t>(n));
  MuxConn* c = m->conns[cid0 % m->conns.size()];
  int64_t deadline = timeout_ms > 0 ? now_ms() + timeout_ms : -1;
  size_t slen = strlen(service), mlen = strlen(method);
  // register ALL cids before staging ANY bytes (same
  // response-before-registration rule as nc_mux_submit)
  {
    std::lock_guard<std::mutex> g(m->mu);
    if (m->stopping.load()) return 0;
    for (int i = 0; i < n; i++) {
      c->inflight[cid0 + i] = tag_base + static_cast<uint64_t>(i);
      c->deadlines[cid0 + i] = deadline;
    }
  }
  {
    std::lock_guard<std::mutex> g(c->stage_mu);
    if (c->fd < 0 && c->staged.size() > (16u << 20)) {
      std::lock_guard<std::mutex> g2(m->mu);
      for (int i = 0; i < n; i++) {
        c->inflight.erase(cid0 + i);
        c->deadlines.erase(cid0 + i);
      }
      return 0;
    }
    size_t need = 0;
    for (int i = 0; i < n; i++) need += kHeader + lens[i];
    c->staged.reserve(c->staged.size() + need + 64 * n);
    for (int i = 0; i < n; i++) {
      std::string meta = pack_request_meta(service, slen, method, mlen,
                                           cid0 + i, 0, log_id);
      size_t base = c->staged.size();
      c->staged.resize(base + kHeader);
      put_header(&c->staged[base], meta.size(), lens[i]);
      c->staged += meta;
      if (lens[i])
        c->staged.append(reinterpret_cast<const char*>(payloads[i]),
                         lens[i]);
    }
  }
  m->stat_ring_windows.fetch_add(1, std::memory_order_relaxed);
  m->stat_ring_calls.fetch_add(static_cast<uint64_t>(n),
                               std::memory_order_relaxed);
  if (!m->wake_pending.exchange(true)) {
    uint64_t one = 1;
    ssize_t r = ::write(m->wake_fd, &one, sizeof(one));
    (void)r;
  }
  return n;
}

// Harvest up to max_n RING-lane completions (tags carrying
// kRingTagBit), blocking up to timeout_ms for the first.  Mirrors
// nc_mux_poll against the separate ring queue.  out[i].data is
// malloc'd; caller frees.
int nc_mux_harvest(void* h, MuxCompletion* out, int max_n, int timeout_ms) {
  MuxClient* m = static_cast<MuxClient*>(h);
  std::unique_lock<std::mutex> lk(m->mu);
  if (m->ring_done.empty()) {
    ns_cv_wait_for_ms(m->ring_cv, lk, timeout_ms, [m] {
      return !m->ring_done.empty() || m->stopping.load();
    });
  }
  int n = 0;
  while (n < max_n && !m->ring_done.empty()) {
    out[n++] = m->ring_done.front();
    m->ring_done.pop_front();
  }
  if (n > 0) {
    m->stat_ring_harvests.fetch_add(1, std::memory_order_relaxed);
    m->stat_ring_completions.fetch_add(static_cast<uint64_t>(n),
                                       std::memory_order_relaxed);
  }
  return n;
}

// Ring step-log counters: out[0]=windows staged out[1]=calls staged
// out[2]=harvest batches out[3]=completions harvested.
void nc_mux_ring_stats(void* h, uint64_t* out) {
  MuxClient* m = static_cast<MuxClient*>(h);
  out[0] = m->stat_ring_windows.load(std::memory_order_relaxed);
  out[1] = m->stat_ring_calls.load(std::memory_order_relaxed);
  out[2] = m->stat_ring_harvests.load(std::memory_order_relaxed);
  out[3] = m->stat_ring_completions.load(std::memory_order_relaxed);
}

// One SYNC RPC multiplexed over the mux reactor: stage the frame, park
// on a per-call waiter, return the completion.  Many caller threads
// share the reactor's few connections; submissions from concurrent
// callers batch into single writes.  Returns 0 ok, -ETIMEDOUT, -EPIPE,
// -ECANCELED on shutdown.  out->data is malloc'd; caller frees
// (nc_free) — unless the caller copies it out first (the CPython
// extension does) and frees inline.
int nc_mux_call(void* h, const char* service, size_t service_len,
                const char* method, size_t method_len, uint64_t log_id,
                const uint8_t* payload, uint64_t payload_len,
                const uint8_t* attachment, uint64_t attachment_len,
                int timeout_ms, NcResponse* out) {
  MuxClient* m = static_cast<MuxClient*>(h);
  out->data = nullptr;
  out->body_len = 0;
  out->attachment_size = 0;
  out->error_code = 0;
  out->compress_type = 0;
  out->error_text[0] = 0;
  if (m->stopping.load()) return -ECANCELED;
  struct timespec ts0;
  clock_gettime(CLOCK_MONOTONIC, &ts0);
  MuxWaiter waiter;
  uint64_t tag = reinterpret_cast<uint64_t>(&waiter);
  uint64_t cid = m->next_cid.fetch_add(1);
  std::string meta = pack_request_meta(service, service_len, method,
                                       method_len, cid, attachment_len,
                                       log_id);
  MuxConn* c = m->conns[cid % m->conns.size()];
  int64_t deadline = timeout_ms > 0 ? now_ms() + timeout_ms : -1;
  // register cid + waiter BEFORE staging (see nc_mux_submit: a staged
  // frame can be answered before an unregistered cid would be mapped)
  {
    std::lock_guard<std::mutex> g(m->mu);
    if (m->stopping.load()) return -ECANCELED;
    c->inflight[cid] = tag;
    c->deadlines[cid] = deadline;
    m->waiters[tag] = &waiter;
  }
  {
    std::lock_guard<std::mutex> g(c->stage_mu);
    if (c->fd < 0 && c->staged.size() > (16u << 20)) {
      std::lock_guard<std::mutex> g2(m->mu);
      c->inflight.erase(cid);
      c->deadlines.erase(cid);
      m->waiters.erase(tag);
      m->stat_fail.fetch_add(1, std::memory_order_relaxed);
      return -EPIPE;
    }
    size_t base = c->staged.size();
    c->staged.resize(base + kHeader);
    put_header(&c->staged[base], meta.size(), payload_len + attachment_len);
    c->staged += meta;
    if (payload_len)
      c->staged.append(reinterpret_cast<const char*>(payload), payload_len);
    if (attachment_len)
      c->staged.append(reinterpret_cast<const char*>(attachment),
                       attachment_len);
  }
  if (!m->wake_pending.exchange(true)) {
    uint64_t one = 1;
    ssize_t r = ::write(m->wake_fd, &one, sizeof(one));
    (void)r;
  }
  bool got;
  {
    std::unique_lock<std::mutex> lk(waiter.mu);
    // the reactor's timeout sweep delivers -ETIMEDOUT; this wait bound
    // is only a backstop against a wedged reactor
    int64_t backstop_ms = timeout_ms > 0 ? timeout_ms + 2000 : 3600 * 1000;
    got = ns_cv_wait_for_ms(waiter.cv, lk, backstop_ms,
                            [&] { return waiter.ready; });
  }  // drop waiter.mu BEFORE m->mu: routing takes m->mu then waiter.mu
  if (!got) {
    bool deregistered = false;
    {
      std::lock_guard<std::mutex> g(m->mu);
      auto wit = m->waiters.find(tag);
      if (wit != m->waiters.end()) {
        // nobody routed the completion yet and now nobody can: safe to
        // abandon the call (a late response hits an unknown cid)
        m->waiters.erase(wit);
        c->inflight.erase(cid);
        c->deadlines.erase(cid);
        deregistered = true;
      }
    }
    if (deregistered) {
      m->stat_fail.fetch_add(1, std::memory_order_relaxed);
      return -ETIMEDOUT;
    }
    // completion routing is mid-flight (erased from waiters under
    // m->mu, ready about to be set): finish the handoff
    std::unique_lock<std::mutex> lk(waiter.mu);
    waiter.cv.wait(lk, [&] { return waiter.ready; });
  }
  MuxCompletion& comp = waiter.comp;
  if (comp.rc != 0 || comp.error_code != 0) {
    m->stat_fail.fetch_add(1, std::memory_order_relaxed);
  } else {
    struct timespec ts1;
    clock_gettime(CLOCK_MONOTONIC, &ts1);
    uint64_t us = (ts1.tv_sec - ts0.tv_sec) * 1000000ull +
                  (ts1.tv_nsec - ts0.tv_nsec) / 1000;
    m->stat_ok.fetch_add(1, std::memory_order_relaxed);
    m->stat_lat_us_sum.fetch_add(us, std::memory_order_relaxed);
    uint64_t prev = m->stat_lat_us_max.load(std::memory_order_relaxed);
    while (us > prev && !m->stat_lat_us_max.compare_exchange_weak(
                            prev, us, std::memory_order_relaxed)) {
    }
  }
  if (comp.rc != 0) {
    if (comp.data) free(comp.data);
    return comp.rc;
  }
  out->data = comp.data;
  out->body_len = comp.body_len;
  out->attachment_size = comp.attachment_size;
  out->error_code = comp.error_code;
  out->compress_type = comp.compress_type;
  snprintf(out->error_text, sizeof(out->error_text), "%s", comp.error_text);
  return 0;
}

// Cumulative sync-call stats: out[0]=ok_count out[1]=latency_us_sum
// out[2]=latency_us_max (reset to 0 by this read — windowed max)
// out[3]=fail_count.  The Python harvester diffs counts/sums against
// its last snapshot (same protocol as ns_method_stats).
void nc_mux_stats(void* h, uint64_t* out) {
  MuxClient* m = static_cast<MuxClient*>(h);
  out[0] = m->stat_ok.load(std::memory_order_relaxed);
  out[1] = m->stat_lat_us_sum.load(std::memory_order_relaxed);
  out[2] = m->stat_lat_us_max.exchange(0, std::memory_order_relaxed);
  out[3] = m->stat_fail.load(std::memory_order_relaxed);
}

// harvest up to max completions (blocks up to timeout_ms); returns count
int nc_mux_poll(void* h, MuxCompletion* out, int max_n, int timeout_ms) {
  MuxClient* m = static_cast<MuxClient*>(h);
  std::unique_lock<std::mutex> lk(m->mu);
  if (m->done.empty()) {
    ns_cv_wait_for_ms(m->done_cv, lk, timeout_ms, [m] {
      return !m->done.empty() || m->stopping.load();
    });
  }
  int n = 0;
  while (n < max_n && !m->done.empty()) {
    out[n++] = m->done.front();
    m->done.pop_front();
  }
  return n;
}

void nc_mux_destroy(void* h);  // defined below, used by press_worker

// ---- native load generator (the rpc_press engine, reference
// tools/rpc_press is likewise native) ----
struct NcBenchResult {
  uint64_t ok;
  uint64_t failed;
  double qps;
  double p50_us;
  double p99_us;
  double p999_us;
  double avg_us;
};

// One press worker: sync pooled round trips against service/method
// "EchoService"/"Echo" with a `payload_len`-byte message, recording
// microsecond latencies until the deadline.
static void press_worker(const char* host, int port, const char* service,
                         const char* method, int payload_len,
                         int64_t deadline_ms, std::vector<uint32_t>* lats,
                         uint64_t* failed, int depth, int conns) {
  void* pool_h = nc_pool_create(host, port, 3000);
  // request payload: EchoRequest{message: 'x' * payload_len}
  PbWriter req;
  std::string msg(payload_len, 'x');
  req.field_bytes(1, msg.data(), msg.size());
  const uint8_t* payload = reinterpret_cast<const uint8_t*>(req.out.data());
  uint64_t plen = req.out.size();
  NcResponse resp;
  if (depth <= 1) {
    // sync mode: one in-flight, pooled fd
    while (now_ms() < deadline_ms) {
      int64_t t0 = now_ms();
      struct timespec ts0, ts1;
      clock_gettime(CLOCK_MONOTONIC, &ts0);
      int rc = nc_call(pool_h, service, method, 0, payload, plen,
                       nullptr, 0, 3000, &resp);
      clock_gettime(CLOCK_MONOTONIC, &ts1);
      (void)t0;
      if (rc == 0 && resp.error_code == 0) {
        if (resp.data) free(resp.data);
        uint64_t us = (ts1.tv_sec - ts0.tv_sec) * 1000000ull +
                      (ts1.tv_nsec - ts0.tv_nsec) / 1000;
        lats->push_back(static_cast<uint32_t>(us));
      } else {
        if (resp.data) free(resp.data);
        (*failed)++;
      }
    }
  } else {
    // pipelined mode: `depth` in-flight over a mux client with `conns`
    // connections (in-flight RPCs round-robin over them by cid)
    void* mux_h = nc_mux_create(host, port, conns < 1 ? 1 : conns);
    std::unordered_map<uint64_t, struct timespec> t0s;
    std::vector<MuxCompletion> comps(depth);
    int inflight = 0;
    uint64_t tag = 0;
    while (now_ms() < deadline_ms || inflight > 0) {
      bool deadline_past = now_ms() >= deadline_ms;
      while (!deadline_past && inflight < depth) {
        struct timespec ts0;
        clock_gettime(CLOCK_MONOTONIC, &ts0);
        ++tag;
        if (!nc_mux_submit(mux_h, service, method, 0, payload, plen,
                           nullptr, 0, 3000, tag))
          break;
        t0s[tag] = ts0;
        inflight++;
      }
      int n = nc_mux_poll(mux_h, comps.data(), depth, 100);
      struct timespec ts1;
      clock_gettime(CLOCK_MONOTONIC, &ts1);
      for (int i = 0; i < n; i++) {
        inflight--;
        auto it = t0s.find(comps[i].tag);
        if (comps[i].rc == 0 && comps[i].error_code == 0 &&
            it != t0s.end()) {
          uint64_t us = (ts1.tv_sec - it->second.tv_sec) * 1000000ull +
                        (ts1.tv_nsec - it->second.tv_nsec) / 1000;
          lats->push_back(static_cast<uint32_t>(us));
        } else {
          (*failed)++;
        }
        if (it != t0s.end()) t0s.erase(it);
        if (comps[i].data) free(comps[i].data);
      }
      if (n == 0 && now_ms() >= deadline_ms + 3500) break;  // stuck drain
    }
    nc_mux_destroy(mux_h);
  }
  nc_pool_destroy(pool_h);
}

// End-to-end echo load test with zero Python in the loop (both sides of
// the wire are this framework's native engine).  depth<=1 → sync
// threads; depth>1 → each thread pipelines `depth` in-flight RPCs.
int nc_bench_echo(const char* host, int port, const char* service,
                  const char* method, int payload_len, int concurrency,
                  int duration_ms, int depth, int conns,
                  NcBenchResult* out) {
  if (concurrency < 1) concurrency = 1;
  int64_t t_start = now_ms();
  int64_t deadline = t_start + duration_ms;
  std::vector<std::vector<uint32_t>> lats(concurrency);
  std::vector<uint64_t> fails(concurrency, 0);
  std::vector<std::thread> threads;
  for (int i = 0; i < concurrency; i++) {
    lats[i].reserve(1 << 18);
    threads.emplace_back(press_worker, host, port, service, method,
                         payload_len, deadline, &lats[i], &fails[i], depth,
                         conns);
  }
  for (auto& t : threads) t.join();
  int64_t t_end = now_ms();
  std::vector<uint32_t> all;
  uint64_t failed = 0;
  for (int i = 0; i < concurrency; i++) {
    all.insert(all.end(), lats[i].begin(), lats[i].end());
    failed += fails[i];
  }
  out->ok = all.size();
  out->failed = failed;
  double wall_s = (t_end - t_start) / 1000.0;
  out->qps = wall_s > 0 ? all.size() / wall_s : 0;
  if (all.empty()) {
    out->p50_us = out->p99_us = out->p999_us = out->avg_us = -1;
    return 0;
  }
  std::sort(all.begin(), all.end());
  out->p50_us = all[all.size() / 2];
  out->p99_us = all[std::min(all.size() - 1, all.size() * 99 / 100)];
  out->p999_us = all[std::min(all.size() - 1, all.size() * 999 / 1000)];
  double sum = 0;
  for (uint32_t v : all) sum += v;
  out->avg_us = sum / all.size();
  return 0;
}

// ---- native HTTP / redis load generators (tools/rpc_press analogs:
// the reference benchmarks its http/redis servers with native clients;
// a Python client would measure the GIL, not the server) ----

static int bench_connect(const char* host, int port) {
  ClientPool p;
  p.host = host;
  p.port = port;
  p.connect_timeout_ms = 3000;
  int fd = pool_connect(&p);
  if (fd >= 0) {
    struct timeval tv {3, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  return fd;
}

static void http_press_worker(const char* host, int port, const char* path,
                              int payload_len, int64_t deadline_ms,
                              int depth, std::vector<uint32_t>* lats,
                              uint64_t* failed) {
  int fd = bench_connect(host, port);
  if (fd < 0) {
    (*failed)++;
    return;
  }
  std::string req;
  {
    char head[256];
    int n = snprintf(head, sizeof(head),
                     "POST %s HTTP/1.1\r\nHost: bench\r\nContent-Type: "
                     "application/octet-stream\r\nContent-Length: %d\r\n\r\n",
                     path, payload_len);
    req.assign(head, n);
    req.append(static_cast<size_t>(payload_len), 'x');
  }
  std::deque<struct timespec> pend;
  std::vector<char> rbuf(1 << 20);
  size_t rlen = 0;
  bool dead = false;
  while (!dead && (now_ms() < deadline_ms || !pend.empty())) {
    while (static_cast<int>(pend.size()) < depth && now_ms() < deadline_ms) {
      struct timespec t0;
      clock_gettime(CLOCK_MONOTONIC, &t0);
      if (!write_all(fd, req.data(), req.size())) {
        dead = true;
        break;
      }
      pend.push_back(t0);
    }
    if (pend.empty()) break;
    if (rlen == rbuf.size()) rbuf.resize(rbuf.size() * 2);
    ssize_t r = ::read(fd, rbuf.data() + rlen, rbuf.size() - rlen);
    if (r <= 0) {
      dead = true;
      break;
    }
    rlen += static_cast<size_t>(r);
    size_t off = 0;
    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    while (!pend.empty()) {
      // find end of headers
      size_t he = 0;
      const char* p = rbuf.data() + off;
      size_t avail = rlen - off;
      for (size_t i = 3; i < avail; i++) {
        if (p[i] == '\n' && p[i - 1] == '\r' && p[i - 2] == '\n' &&
            p[i - 3] == '\r') {
          he = i + 1;
          break;
        }
      }
      if (!he) break;
      const char* val;
      size_t val_len;
      uint64_t cl = 0;
      if (http_find_header(p, he, "content-length", 14, &val, &val_len)) {
        for (size_t i = 0; i < val_len; i++)
          cl = cl * 10 + (val[i] - '0');
      }
      if (avail < he + cl) break;
      bool ok = avail >= 12 && memcmp(p, "HTTP/1.1 200", 12) == 0;
      struct timespec t0 = pend.front();
      pend.pop_front();
      if (ok) {
        uint64_t us = (t1.tv_sec - t0.tv_sec) * 1000000ull +
                      (t1.tv_nsec - t0.tv_nsec) / 1000;
        lats->push_back(static_cast<uint32_t>(us));
      } else {
        (*failed)++;
      }
      off += he + cl;
    }
    if (off) {
      memmove(rbuf.data(), rbuf.data() + off, rlen - off);
      rlen -= off;
    }
  }
  *failed += pend.size();
  ::close(fd);
}

int nc_bench_http(const char* host, int port, const char* path,
                  int payload_len, int concurrency, int duration_ms,
                  int depth, NcBenchResult* out) {
  if (concurrency < 1) concurrency = 1;
  if (depth < 1) depth = 1;
  int64_t t_start = now_ms();
  int64_t deadline = t_start + duration_ms;
  std::vector<std::vector<uint32_t>> lats(concurrency);
  std::vector<uint64_t> fails(concurrency, 0);
  std::vector<std::thread> threads;
  for (int i = 0; i < concurrency; i++) {
    lats[i].reserve(1 << 16);
    threads.emplace_back(http_press_worker, host, port, path, payload_len,
                         deadline, depth, &lats[i], &fails[i]);
  }
  for (auto& t : threads) t.join();
  int64_t t_end = now_ms();
  std::vector<uint32_t> all;
  uint64_t failed = 0;
  for (int i = 0; i < concurrency; i++) {
    all.insert(all.end(), lats[i].begin(), lats[i].end());
    failed += fails[i];
  }
  out->ok = all.size();
  out->failed = failed;
  double wall_s = (t_end - t_start) / 1000.0;
  out->qps = wall_s > 0 ? all.size() / wall_s : 0;
  if (all.empty()) {
    out->p50_us = out->p99_us = out->p999_us = out->avg_us = -1;
    return 0;
  }
  std::sort(all.begin(), all.end());
  out->p50_us = all[all.size() / 2];
  out->p99_us = all[std::min(all.size() - 1, all.size() * 99 / 100)];
  out->p999_us = all[std::min(all.size() - 1, all.size() * 999 / 1000)];
  double sum = 0;
  for (uint32_t v : all) sum += v;
  out->avg_us = sum / all.size();
  return 0;
}

// one RESP reply's wire length at p (0 = incomplete, SIZE_MAX = bad)
static size_t resp_reply_len(const char* p, size_t len) {
  if (len < 3) return 0;
  char t = p[0];
  const char* nl = static_cast<const char*>(memchr(p, '\n', len));
  if (!nl) return 0;
  size_t line = static_cast<size_t>(nl - p) + 1;
  if (t == '+' || t == '-' || t == ':') return line;
  if (t == '$') {
    long n = strtol(p + 1, nullptr, 10);
    if (n < 0) return line;  // nil bulk
    size_t total = line + static_cast<size_t>(n) + 2;
    return len >= total ? total : 0;
  }
  if (t == '*') {
    long n = strtol(p + 1, nullptr, 10);
    size_t off = line;
    for (long i = 0; i < n; i++) {
      size_t r = resp_reply_len(p + off, len - off);
      if (r == 0 || r == SIZE_MAX) return r;
      off += r;
    }
    return off;
  }
  return SIZE_MAX;
}

static void redis_press_worker(const char* host, int port, int value_len,
                               int64_t deadline_ms, int depth, int wid,
                               std::vector<uint32_t>* lats,
                               uint64_t* failed) {
  int fd = bench_connect(host, port);
  if (fd < 0) {
    (*failed)++;
    return;
  }
  // alternating SET key:<wid> <val> / GET key:<wid> — each command is
  // one op (reference redis benchmarks count commands)
  char key[32];
  int klen = snprintf(key, sizeof(key), "bench:%d", wid);
  std::string val(static_cast<size_t>(value_len), 'v');
  std::string set_cmd, get_cmd;
  {
    char h[64];
    set_cmd.append("*3\r\n$3\r\nSET\r\n");
    set_cmd.append(h, snprintf(h, sizeof(h), "$%d\r\n", klen));
    set_cmd.append(key, klen);
    set_cmd.append("\r\n");
    set_cmd.append(h, snprintf(h, sizeof(h), "$%d\r\n", value_len));
    set_cmd += val;
    set_cmd.append("\r\n");
    get_cmd.append("*2\r\n$3\r\nGET\r\n");
    get_cmd.append(h, snprintf(h, sizeof(h), "$%d\r\n", klen));
    get_cmd.append(key, klen);
    get_cmd.append("\r\n");
  }
  std::deque<struct timespec> pend;
  std::vector<char> rbuf(1 << 20);
  size_t rlen = 0;
  uint64_t seq = 0;
  bool dead = false;
  while (!dead && (now_ms() < deadline_ms || !pend.empty())) {
    while (static_cast<int>(pend.size()) < depth && now_ms() < deadline_ms) {
      const std::string& cmd = (seq++ & 1) ? get_cmd : set_cmd;
      struct timespec t0;
      clock_gettime(CLOCK_MONOTONIC, &t0);
      if (!write_all(fd, cmd.data(), cmd.size())) {
        dead = true;
        break;
      }
      pend.push_back(t0);
    }
    if (pend.empty()) break;
    if (rlen == rbuf.size()) rbuf.resize(rbuf.size() * 2);
    ssize_t r = ::read(fd, rbuf.data() + rlen, rbuf.size() - rlen);
    if (r <= 0) {
      dead = true;
      break;
    }
    rlen += static_cast<size_t>(r);
    size_t off = 0;
    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    while (!pend.empty()) {
      size_t n = resp_reply_len(rbuf.data() + off, rlen - off);
      if (n == 0) break;
      if (n == SIZE_MAX) {
        dead = true;
        break;
      }
      struct timespec t0 = pend.front();
      pend.pop_front();
      if (rbuf[off] == '-') {
        (*failed)++;
      } else {
        uint64_t us = (t1.tv_sec - t0.tv_sec) * 1000000ull +
                      (t1.tv_nsec - t0.tv_nsec) / 1000;
        lats->push_back(static_cast<uint32_t>(us));
      }
      off += n;
    }
    if (off) {
      memmove(rbuf.data(), rbuf.data() + off, rlen - off);
      rlen -= off;
    }
  }
  *failed += pend.size();
  ::close(fd);
}

int nc_bench_redis(const char* host, int port, int value_len,
                   int concurrency, int duration_ms, int depth,
                   NcBenchResult* out) {
  if (concurrency < 1) concurrency = 1;
  if (depth < 1) depth = 1;
  int64_t t_start = now_ms();
  int64_t deadline = t_start + duration_ms;
  std::vector<std::vector<uint32_t>> lats(concurrency);
  std::vector<uint64_t> fails(concurrency, 0);
  std::vector<std::thread> threads;
  for (int i = 0; i < concurrency; i++) {
    lats[i].reserve(1 << 16);
    threads.emplace_back(redis_press_worker, host, port, value_len,
                         deadline, depth, i, &lats[i], &fails[i]);
  }
  for (auto& t : threads) t.join();
  int64_t t_end = now_ms();
  std::vector<uint32_t> all;
  uint64_t failed = 0;
  for (int i = 0; i < concurrency; i++) {
    all.insert(all.end(), lats[i].begin(), lats[i].end());
    failed += fails[i];
  }
  out->ok = all.size();
  out->failed = failed;
  double wall_s = (t_end - t_start) / 1000.0;
  out->qps = wall_s > 0 ? all.size() / wall_s : 0;
  if (all.empty()) {
    out->p50_us = out->p99_us = out->p999_us = out->avg_us = -1;
    return 0;
  }
  std::sort(all.begin(), all.end());
  out->p50_us = all[all.size() / 2];
  out->p99_us = all[std::min(all.size() - 1, all.size() * 99 / 100)];
  out->p999_us = all[std::min(all.size() - 1, all.size() * 999 / 1000)];
  double sum = 0;
  for (uint32_t v : all) sum += v;
  out->avg_us = sum / all.size();
  return 0;
}

void nc_mux_destroy(void* h) {
  MuxClient* m = static_cast<MuxClient*>(h);
  m->stopping.store(true);
  uint64_t one = 1;
  ssize_t r = ::write(m->wake_fd, &one, sizeof(one));
  (void)r;
  m->done_cv.notify_all();
  m->ring_cv.notify_all();
  if (m->reactor.joinable()) m->reactor.join();
  // fail whatever the reactor never answered — this also wakes sync
  // callers parked in nc_mux_call so they can't outlive the client
  {
    std::lock_guard<std::mutex> g(m->mu);
    for (MuxConn* c : m->conns) {
      for (auto& kv : c->inflight)
        mux_complete_locked(m, kv.second, -ECANCELED, nullptr, nullptr, 0);
      c->inflight.clear();
      c->deadlines.clear();
    }
  }
  m->done_cv.notify_all();
  m->ring_cv.notify_all();
  for (MuxConn* c : m->conns) {
    if (c->fd >= 0) ::close(c->fd);
    delete c;
  }
  {
    std::lock_guard<std::mutex> g(m->mu);
    for (auto& d : m->done)
      if (d.data) free(d.data);
    m->done.clear();
    for (auto& d : m->ring_done)
      if (d.data) free(d.data);
    m->ring_done.clear();
  }
  ::close(m->epfd);
  ::close(m->wake_fd);
  delete m;
}

}  // extern "C"
