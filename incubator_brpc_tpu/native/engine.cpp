// Native transport engine — the C++ hot path for the tpu_std wire.
//
// Analog of the reference's C++ core loops: InputMessenger::OnNewMessages
// (input_messenger.cpp:317-382, read+cut+dispatch) and Socket::StartWrite/
// KeepWrite (socket.cpp:1584-1790).  The reference is C++ end to end; this
// engine restores that property for the framing/IO cycle so the Python
// layer above (services, combos, observability) rides a native data path:
//
//   * server: N worker threads, each owning an epoll set; connections are
//     assigned round-robin at accept.  Frames are cut and, for methods
//     registered as native-echo, answered entirely in C++ (no GIL).  All
//     other frames are handed to a Python dispatch callback (the ctypes
//     layer re-acquires the GIL only for those).
//   * client: a connection pool with blocking call/response round trips;
//     the meta protobuf is packed/parsed here so Python touches only the
//     user payload bytes.  One in-flight RPC per pooled fd — the pooled
//     connection type (channel.h:84-89, GetPooledSocket analog).
//
// Wire format (protocols/tpu_std.py): b"TRPC" u32(meta_size) u32(body_size)
// then RpcMeta pb then body (payload + attachment).  The tiny subset of
// protobuf needed for RpcMeta/Echo is hand-encoded below — schema in
// protos/rpc_meta.proto; field numbers are load-bearing.
//
// Build: g++ -O2 -shared -fPIC -pthread engine.cpp -o _engine.so

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t kMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kHeader = 12;
constexpr uint64_t kMaxBody = 2ull << 30;

// ---------------------------------------------------------------------------
// minimal protobuf
// ---------------------------------------------------------------------------

struct PbWriter {
  std::string out;
  void varint(uint64_t v) {
    while (v >= 0x80) {
      out.push_back(static_cast<char>(v | 0x80));
      v >>= 7;
    }
    out.push_back(static_cast<char>(v));
  }
  void tag(uint32_t field, uint32_t wire) { varint((field << 3) | wire); }
  void field_varint(uint32_t f, uint64_t v) {
    if (v) {
      tag(f, 0);
      varint(v);
    }
  }
  void field_bytes(uint32_t f, const char* p, size_t n) {
    tag(f, 2);
    varint(n);
    out.append(p, n);
  }
};

struct PbReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }
  // returns field number, 0 at end/error; wire type in *wire
  uint32_t next(uint32_t* wire) {
    if (p >= end || !ok) return 0;
    uint64_t key = varint();
    if (!ok) return 0;
    *wire = key & 7;
    return static_cast<uint32_t>(key >> 3);
  }
  bool bytes(const uint8_t** out, size_t* n) {
    uint64_t len = varint();
    if (!ok || len > static_cast<uint64_t>(end - p)) {
      ok = false;
      return false;
    }
    *out = p;
    *n = len;
    p += len;
    return true;
  }
  void skip(uint32_t wire) {
    switch (wire) {
      case 0:
        varint();
        break;
      case 1:
        if (end - p >= 8)
          p += 8;
        else
          ok = false;
        break;
      case 2: {
        const uint8_t* d;
        size_t n;
        bytes(&d, &n);
        break;
      }
      case 5:
        if (end - p >= 4)
          p += 4;
        else
          ok = false;
        break;
      default:
        ok = false;
    }
  }
};

// Parsed RpcMeta subset (protos/rpc_meta.proto)
struct MetaView {
  std::string service, method;   // request.service_name/.method_name
  uint64_t correlation_id = 0;   // field 4
  uint64_t attachment_size = 0;  // field 5
  uint64_t compress_type = 0;    // field 3
  int32_t error_code = 0;        // response.error_code
  std::string error_text;        // response.error_text
  bool has_request = false, has_response = false;
  bool has_stream = false, has_auth = false, has_device_segs = false;
};

bool parse_meta(const uint8_t* data, size_t len, MetaView* m) {
  PbReader r{data, data + len};
  uint32_t wire;
  while (uint32_t f = r.next(&wire)) {
    if (f == 1 && wire == 2) {  // RpcRequestMeta
      const uint8_t* d;
      size_t n;
      if (!r.bytes(&d, &n)) return false;
      m->has_request = true;
      PbReader rr{d, d + n};
      uint32_t w2;
      while (uint32_t f2 = rr.next(&w2)) {
        if (f2 == 1 && w2 == 2) {
          const uint8_t* s;
          size_t sn;
          if (!rr.bytes(&s, &sn)) return false;
          m->service.assign(reinterpret_cast<const char*>(s), sn);
        } else if (f2 == 2 && w2 == 2) {
          const uint8_t* s;
          size_t sn;
          if (!rr.bytes(&s, &sn)) return false;
          m->method.assign(reinterpret_cast<const char*>(s), sn);
        } else {
          rr.skip(w2);
        }
      }
      if (!rr.ok) return false;
    } else if (f == 2 && wire == 2) {  // RpcResponseMeta
      const uint8_t* d;
      size_t n;
      if (!r.bytes(&d, &n)) return false;
      m->has_response = true;
      PbReader rr{d, d + n};
      uint32_t w2;
      while (uint32_t f2 = rr.next(&w2)) {
        if (f2 == 1 && w2 == 0) {
          m->error_code = static_cast<int32_t>(rr.varint());
        } else if (f2 == 2 && w2 == 2) {
          const uint8_t* s;
          size_t sn;
          if (!rr.bytes(&s, &sn)) return false;
          m->error_text.assign(reinterpret_cast<const char*>(s), sn);
        } else {
          rr.skip(w2);
        }
      }
      if (!rr.ok) return false;
    } else if (f == 3 && wire == 0) {
      m->compress_type = r.varint();
    } else if (f == 4 && wire == 0) {
      m->correlation_id = r.varint();
    } else if (f == 5 && wire == 0) {
      m->attachment_size = r.varint();
    } else if (f == 6) {
      m->has_stream = true;
      r.skip(wire);
    } else if (f == 7) {
      m->has_device_segs = true;
      r.skip(wire);
    } else if (f == 8) {
      m->has_auth = true;
      r.skip(wire);
    } else {
      r.skip(wire);
    }
  }
  return r.ok;
}

// EchoRequest view (protos/echo.proto): message=1 code=2 server_fail=3
// close_fd=4 sleep_us=5.  Any fault-injection field present → not native.
struct EchoView {
  const uint8_t* msg = nullptr;
  size_t msg_len = 0;
  uint64_t code = 0;
  bool plain = true;  // no fault-injection fields
};

bool parse_echo(const uint8_t* data, size_t len, EchoView* e) {
  PbReader r{data, data + len};
  uint32_t wire;
  while (uint32_t f = r.next(&wire)) {
    if (f == 1 && wire == 2) {
      if (!r.bytes(&e->msg, &e->msg_len)) return false;
    } else if (f == 2 && wire == 0) {
      e->code = r.varint();
    } else if (f == 3 || f == 4 || f == 5) {
      e->plain = false;
      r.skip(wire);
    } else {
      r.skip(wire);
    }
  }
  return r.ok;
}

std::string pack_request_meta(const char* service, size_t service_len,
                              const char* method, size_t method_len,
                              uint64_t cid, uint64_t att_size,
                              uint64_t log_id) {
  PbWriter req;
  req.field_bytes(1, service, service_len);
  req.field_bytes(2, method, method_len);
  req.field_varint(3, log_id);
  PbWriter meta;
  meta.field_bytes(1, req.out.data(), req.out.size());
  meta.field_varint(4, cid);
  meta.field_varint(5, att_size);
  return std::move(meta.out);
}

std::string pack_response_meta(uint64_t cid, uint64_t att_size) {
  PbWriter meta;
  meta.field_varint(4, cid);
  meta.field_varint(5, att_size);
  return std::move(meta.out);
}

void put_header(char* dst, uint32_t meta_size, uint32_t body_size) {
  memcpy(dst, kMagic, 4);
  uint32_t m = htonl(meta_size), b = htonl(body_size);
  memcpy(dst + 4, &m, 4);
  memcpy(dst + 8, &b, 4);
}

// ---------------------------------------------------------------------------
// IO helpers
// ---------------------------------------------------------------------------

int set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// write fully (blocking fd)
bool write_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool read_exact(int fd, char* p, size_t n, int timeout_ms) {
  while (n) {
    if (timeout_ms >= 0) {
      struct pollfd pfd {fd, POLLIN, 0};
      int rc = ::poll(&pfd, 1, timeout_ms);
      if (rc == 0) {
        errno = ETIMEDOUT;
        return false;
      }
      if (rc < 0) {
        if (errno == EINTR) continue;
        return false;
      }
    }
    ssize_t r = ::read(fd, p, n);
    if (r == 0) {
      errno = ECONNRESET;
      return false;
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// ---------------------------------------------------------------------------
// server
// ---------------------------------------------------------------------------

using PyDispatch = void (*)(uint64_t conn_id, const uint8_t* frame,
                            uint64_t len);

struct Conn {
  int fd = -1;
  uint64_t id = 0;
  std::vector<uint8_t> in;   // partial-frame accumulation
  std::deque<std::string> outq;  // pending writes (epoll-out driven)
  size_t out_off = 0;        // offset into outq.front()
  std::mutex out_mu;
  bool want_out = false;     // EPOLLOUT armed
  std::atomic<bool> dead{false};
};

struct Worker;

struct NativeServer {
  std::vector<std::thread> threads;
  std::vector<Worker*> workers;
  int listen_fd = -1;
  std::thread acceptor;
  std::atomic<bool> running{false};
  std::atomic<uint64_t> next_conn_id{1};
  std::atomic<uint32_t> rr{0};
  PyDispatch dispatch = nullptr;
  // native fast-path registry: "service\0method" → attach_echo flag
  std::unordered_map<std::string, bool> native_echo;
  std::mutex reg_mu;
  std::mutex conns_mu;
  std::unordered_map<uint64_t, std::pair<Worker*, Conn*>> conns;

  bool echo_lookup(const std::string& svc, const std::string& m, bool* attach) {
    std::lock_guard<std::mutex> g(reg_mu);
    auto it = native_echo.find(svc + '\0' + m);
    if (it == native_echo.end()) return false;
    *attach = it->second;
    return true;
  }
};

struct Worker {
  NativeServer* srv;
  int epfd = -1;
  int wake_fd = -1;  // eventfd: new conns / pending writes / stop
  std::mutex mu;
  std::vector<Conn*> incoming;
  std::vector<Conn*> writable;  // conns with queued output to arm
  std::atomic<bool> stop{false};

  void notify() {
    uint64_t one = 1;
    ssize_t n = ::write(wake_fd, &one, sizeof(one));
    (void)n;
  }
};

void conn_queue_write(Worker* w, Conn* c, std::string&& data) {
  bool need_arm = false;
  {
    std::lock_guard<std::mutex> g(c->out_mu);
    if (c->dead.load()) return;
    if (c->outq.empty()) {
      // try inline write first (StartWrite analog: first writer writes)
      size_t off = 0;
      while (off < data.size()) {
        ssize_t n = ::write(c->fd, data.data() + off, data.size() - off);
        if (n > 0) {
          off += static_cast<size_t>(n);
          continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
        if (n < 0 && errno == EINTR) continue;
        c->dead.store(true);
        return;
      }
      if (off == data.size()) return;  // fully written inline
      c->outq.emplace_back(data.substr(off));
      need_arm = !c->want_out;
    } else {
      c->outq.emplace_back(std::move(data));
      need_arm = !c->want_out;
    }
  }
  if (need_arm) {
    std::lock_guard<std::mutex> g(w->mu);
    w->writable.push_back(c);
    w->notify();
  }
}

// drain queued output on EPOLLOUT; returns false on fatal error
bool conn_flush(Conn* c) {
  std::lock_guard<std::mutex> g(c->out_mu);
  while (!c->outq.empty()) {
    std::string& front = c->outq.front();
    while (c->out_off < front.size()) {
      ssize_t n =
          ::write(c->fd, front.data() + c->out_off, front.size() - c->out_off);
      if (n > 0) {
        c->out_off += static_cast<size_t>(n);
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    c->out_off = 0;
    c->outq.pop_front();
  }
  return true;
}

void close_conn(NativeServer* srv, Worker* w, Conn* c) {
  c->dead.store(true);
  epoll_ctl(w->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  ::close(c->fd);
  // ns_send holds conns_mu while touching a Conn, so erasing under the
  // same lock before delete makes the free safe against sender threads
  {
    std::lock_guard<std::mutex> g(srv->conns_mu);
    srv->conns.erase(c->id);
  }
  // purge any stale pointers queued for this worker (we ARE the worker
  // thread, the only consumer of these lists)
  {
    std::lock_guard<std::mutex> g(w->mu);
    for (auto it = w->writable.begin(); it != w->writable.end();) {
      it = (*it == c) ? w->writable.erase(it) : it + 1;
    }
    for (auto it = w->incoming.begin(); it != w->incoming.end();) {
      it = (*it == c) ? w->incoming.erase(it) : it + 1;
    }
  }
  delete c;
}

// handle one complete frame; returns false → close connection
bool server_on_frame(NativeServer* srv, Worker* w, Conn* c,
                     const uint8_t* frame, size_t len) {
  uint32_t meta_size, body_size;
  memcpy(&meta_size, frame + 4, 4);
  memcpy(&body_size, frame + 8, 4);
  meta_size = ntohl(meta_size);
  body_size = ntohl(body_size);
  const uint8_t* meta_p = frame + kHeader;
  const uint8_t* body_p = meta_p + meta_size;

  MetaView m;
  if (parse_meta(meta_p, meta_size, &m) && m.has_request && !m.has_response &&
      !m.compress_type && !m.has_stream && !m.has_auth && !m.has_device_segs &&
      m.attachment_size <= body_size) {
    bool attach_echo = false;
    if (srv->echo_lookup(m.service, m.method, &attach_echo)) {
      size_t req_len = body_size - m.attachment_size;
      EchoView e;
      if (parse_echo(body_p, req_len, &e) && e.plain) {
        // ---- the native echo fast path: zero Python, zero GIL ----
        PbWriter resp;
        if (e.msg_len) resp.field_bytes(1, reinterpret_cast<const char*>(e.msg),
                                        e.msg_len);
        resp.field_varint(2, e.code);
        uint64_t att = attach_echo ? m.attachment_size : 0;
        std::string meta_out = pack_response_meta(m.correlation_id, att);
        std::string out;
        out.resize(kHeader);
        put_header(&out[0], meta_out.size(), resp.out.size() + att);
        out += meta_out;
        out += resp.out;
        if (att)
          out.append(reinterpret_cast<const char*>(body_p + req_len), att);
        conn_queue_write(w, c, std::move(out));
        return !c->dead.load();
      }
    }
  }
  // ---- Python fallback: full framework semantics ----
  if (srv->dispatch) {
    srv->dispatch(c->id, frame, len);
    return !c->dead.load();
  }
  return false;
}

void worker_loop(NativeServer* srv, Worker* w) {
  epoll_event evs[128];
  while (!w->stop.load()) {
    int n = epoll_wait(w->epfd, evs, 128, 500);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      if (evs[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t junk;
        while (::read(w->wake_fd, &junk, sizeof(junk)) > 0) {
        }
        std::vector<Conn*> add, arm;
        {
          std::lock_guard<std::mutex> g(w->mu);
          add.swap(w->incoming);
          arm.swap(w->writable);
        }
        for (Conn* c : add) {
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          if (epoll_ctl(w->epfd, EPOLL_CTL_ADD, c->fd, &ev) < 0) {
            close_conn(srv, w, c);
          }
        }
        for (Conn* c : arm) {
          if (c->dead.load()) continue;
          std::lock_guard<std::mutex> g(c->out_mu);
          if (!c->outq.empty() && !c->want_out) {
            c->want_out = true;
            epoll_event ev{};
            ev.events = EPOLLIN | EPOLLOUT;
            ev.data.ptr = c;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          }
        }
        continue;
      }
      Conn* c = static_cast<Conn*>(evs[i].data.ptr);
      bool fatal = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) fatal = true;
      if (!fatal && (evs[i].events & EPOLLOUT)) {
        if (!conn_flush(c)) {
          fatal = true;
        } else {
          std::lock_guard<std::mutex> g(c->out_mu);
          if (c->outq.empty() && c->want_out) {
            c->want_out = false;
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.ptr = c;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          }
        }
      }
      if (!fatal && (evs[i].events & EPOLLIN)) {
        // level-triggered read: pull what's there, cut complete frames
        char buf[64 * 1024];
        for (;;) {
          ssize_t r = ::read(c->fd, buf, sizeof(buf));
          if (r > 0) {
            c->in.insert(c->in.end(), buf, buf + r);
            if (static_cast<size_t>(r) < sizeof(buf)) break;
            continue;
          }
          if (r == 0) {
            fatal = true;
            break;
          }
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          if (errno == EINTR) continue;
          fatal = true;
          break;
        }
        // cut frames
        size_t off = 0;
        while (!fatal) {
          size_t avail = c->in.size() - off;
          if (avail < kHeader) break;
          const uint8_t* p = c->in.data() + off;
          if (memcmp(p, kMagic, 4) != 0) {
            fatal = true;  // non-tpu_std traffic: native port speaks one
            break;
          }
          uint32_t ms, bs;
          memcpy(&ms, p + 4, 4);
          memcpy(&bs, p + 8, 4);
          ms = ntohl(ms);
          bs = ntohl(bs);
          if (static_cast<uint64_t>(ms) + bs > kMaxBody) {
            fatal = true;
            break;
          }
          size_t total = kHeader + ms + bs;
          if (avail < total) break;
          if (!server_on_frame(srv, w, c, p, total)) fatal = true;
          off += total;
        }
        if (off) c->in.erase(c->in.begin(), c->in.begin() + off);
        if (c->dead.load()) fatal = true;
      }
      if (fatal) close_conn(srv, w, c);
    }
  }
}

void acceptor_loop(NativeServer* srv) {
  while (srv->running.load()) {
    struct pollfd pfd {srv->listen_fd, POLLIN, 0};
    int rc = ::poll(&pfd, 1, 300);
    if (rc <= 0) continue;
    int fd = ::accept4(srv->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) continue;
    set_nodelay(fd);
    Conn* c = new Conn();
    c->fd = fd;
    c->id = srv->next_conn_id.fetch_add(1);
    Worker* w =
        srv->workers[srv->rr.fetch_add(1) % srv->workers.size()];
    {
      std::lock_guard<std::mutex> g(srv->conns_mu);
      srv->conns[c->id] = {w, c};
    }
    {
      std::lock_guard<std::mutex> g(w->mu);
      w->incoming.push_back(c);
    }
    w->notify();
  }
}

// ---------------------------------------------------------------------------
// client pool
// ---------------------------------------------------------------------------

struct PooledFd {
  int fd;
  int rcvtimeo_ms;  // currently-set SO_RCVTIMEO (avoid per-call setsockopt)
};

struct ClientPool {
  std::string host;
  int port;
  int connect_timeout_ms;
  std::mutex mu;
  std::vector<PooledFd> free_fds;
  std::atomic<uint64_t> next_cid{1};
};

void fd_set_timeout(PooledFd* pf, int timeout_ms) {
  if (pf->rcvtimeo_ms == timeout_ms) return;
  struct timeval tv;
  if (timeout_ms < 0) {
    tv.tv_sec = 0;
    tv.tv_usec = 0;  // 0 = block forever
  } else {
    tv.tv_sec = timeout_ms / 1000;
    tv.tv_usec = (timeout_ms % 1000) * 1000;
  }
  setsockopt(pf->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  pf->rcvtimeo_ms = timeout_ms;
}

int pool_connect(ClientPool* p) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(p->port));
  if (inet_pton(AF_INET, p->host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  set_nodelay(fd);
  return fd;
}

bool pool_acquire(ClientPool* p, PooledFd* out) {
  {
    std::lock_guard<std::mutex> g(p->mu);
    if (!p->free_fds.empty()) {
      *out = p->free_fds.back();
      p->free_fds.pop_back();
      return true;
    }
  }
  int fd = pool_connect(p);
  if (fd < 0) return false;
  *out = PooledFd{fd, 0};
  return true;
}

void pool_release(ClientPool* p, PooledFd pf) {
  std::lock_guard<std::mutex> g(p->mu);
  p->free_fds.push_back(pf);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// ---- server ----
void* ns_create() { return new NativeServer(); }

void ns_set_dispatch(void* h, PyDispatch cb) {
  static_cast<NativeServer*>(h)->dispatch = cb;
}

void ns_register_native_echo(void* h, const char* service, const char* method,
                             int attach_echo) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  std::lock_guard<std::mutex> g(srv->reg_mu);
  srv->native_echo[std::string(service) + '\0' + method] = attach_echo != 0;
}

// returns bound port, or -errno
int ns_listen(void* h, const char* host, int port, int nworkers) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -EINVAL;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 1024) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
  srv->listen_fd = fd;
  srv->running.store(true);
  if (nworkers < 1) nworkers = 1;
  for (int i = 0; i < nworkers; i++) {
    Worker* w = new Worker();
    w->srv = srv;
    w->epfd = epoll_create1(0);
    w->wake_fd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;
    epoll_ctl(w->epfd, EPOLL_CTL_ADD, w->wake_fd, &ev);
    srv->workers.push_back(w);
    srv->threads.emplace_back(worker_loop, srv, w);
  }
  srv->acceptor = std::thread(acceptor_loop, srv);
  return ntohs(bound.sin_port);
}

// thread-safe response send from Python fallback handlers
int ns_send(void* h, uint64_t conn_id, const uint8_t* data, uint64_t len) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  // conns_mu held for the whole send: close_conn erases under the same
  // lock before deleting, so the Conn cannot be freed under us
  std::lock_guard<std::mutex> g(srv->conns_mu);
  auto it = srv->conns.find(conn_id);
  if (it == srv->conns.end()) return -ENOTCONN;
  Worker* w = it->second.first;
  Conn* c = it->second.second;
  conn_queue_write(w, c, std::string(reinterpret_cast<const char*>(data), len));
  return c->dead.load() ? -EPIPE : 0;
}

// Python fallback asks to close (Controller::CloseConnection analog)
void ns_close_conn(void* h, uint64_t conn_id) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  std::lock_guard<std::mutex> g(srv->conns_mu);
  auto it = srv->conns.find(conn_id);
  if (it == srv->conns.end()) return;
  it->second.second->dead.store(true);
  it->second.first->notify();
  // actual close happens on the worker when the conn next polls readable
  ::shutdown(it->second.second->fd, SHUT_RDWR);
}

void ns_stop(void* h) {
  NativeServer* srv = static_cast<NativeServer*>(h);
  if (!srv->running.exchange(false)) return;
  ::close(srv->listen_fd);
  if (srv->acceptor.joinable()) srv->acceptor.join();
  for (Worker* w : srv->workers) {
    w->stop.store(true);
    w->notify();
  }
  for (auto& t : srv->threads) t.join();
  {
    std::lock_guard<std::mutex> g(srv->conns_mu);
    for (auto& kv : srv->conns) {
      ::close(kv.second.second->fd);
      delete kv.second.second;
    }
    srv->conns.clear();
  }
  for (Worker* w : srv->workers) {
    ::close(w->epfd);
    ::close(w->wake_fd);
    delete w;
  }
  srv->workers.clear();
  srv->threads.clear();
}

void ns_destroy(void* h) {
  ns_stop(h);
  delete static_cast<NativeServer*>(h);
}

// ---- client ----
void* nc_pool_create(const char* host, int port, int connect_timeout_ms) {
  ClientPool* p = new ClientPool();
  p->host = host;
  p->port = port;
  p->connect_timeout_ms = connect_timeout_ms;
  return p;
}

void nc_pool_destroy(void* h) {
  ClientPool* p = static_cast<ClientPool*>(h);
  {
    std::lock_guard<std::mutex> g(p->mu);
    for (PooledFd& pf : p->free_fds) ::close(pf.fd);
  }
  delete p;
}

// Response out-params struct (mirrored by ctypes)
struct NcResponse {
  uint8_t* data;        // malloc'd full body (payload+attachment); nc_free it
  uint64_t body_len;
  uint64_t attachment_size;
  int32_t error_code;
  int32_t compress_type;  // response meta compress_type (Python decompresses)
  char error_text[240];
};

void nc_free(uint8_t* p) { free(p); }

// One pooled-connection RPC round trip.  Packs meta in C, writes
// header+meta+payload(+attachment), reads exactly one response frame
// for our correlation id.  Returns 0 ok; -ETIMEDOUT; -EPIPE on IO fail;
// -EBADMSG on protocol garbage.
int nc_call(void* h, const char* service, const char* method, uint64_t log_id,
            const uint8_t* payload, uint64_t payload_len,
            const uint8_t* attachment, uint64_t attachment_len, int timeout_ms,
            NcResponse* out) {
  ClientPool* p = static_cast<ClientPool*>(h);
  out->data = nullptr;
  out->body_len = 0;
  out->attachment_size = 0;
  out->error_code = 0;
  out->error_text[0] = 0;
  uint64_t cid = p->next_cid.fetch_add(1);
  std::string meta =
      pack_request_meta(service, strlen(service), method, strlen(method), cid,
                        attachment_len, log_id);
  // ONE contiguous request buffer → one write syscall (this box may be
  // a single shared core: per-RPC syscall count IS the qps ceiling)
  std::string wire;
  wire.reserve(kHeader + meta.size() + payload_len + attachment_len);
  wire.resize(kHeader);
  put_header(&wire[0], meta.size(), payload_len + attachment_len);
  wire += meta;
  if (payload_len)
    wire.append(reinterpret_cast<const char*>(payload), payload_len);
  if (attachment_len)
    wire.append(reinterpret_cast<const char*>(attachment), attachment_len);

  // one reconnect retry on stale pooled fd (server may have closed it)
  for (int attempt = 0; attempt < 2; attempt++) {
    PooledFd pf;
    if (attempt == 0) {
      if (!pool_acquire(p, &pf)) return -ECONNREFUSED;
    } else {
      int fd = pool_connect(p);
      if (fd < 0) return -ECONNREFUSED;
      pf = PooledFd{fd, 0};
    }
    fd_set_timeout(&pf, timeout_ms);
    if (!write_all(pf.fd, wire.data(), wire.size())) {
      ::close(pf.fd);
      continue;  // stale fd: retry once on a fresh connection
    }
    // single recv loop: header lands with (usually all of) the body in
    // one read; SO_RCVTIMEO supplies the deadline with no poll() calls
    uint8_t hdr_buf[64 * 1024];
    size_t have = 0;
    uint32_t ms = 0, bs = 0;
    uint8_t* body = nullptr;  // malloc'd once sizes are known
    std::vector<uint8_t> meta_buf;
    bool fail = false, timed_out = false;
    size_t total_rest = 0;  // ms + bs
    while (true) {
      if (have >= kHeader && body == nullptr) {
        if (memcmp(hdr_buf, kMagic, 4) != 0) {
          fail = true;
          break;
        }
        memcpy(&ms, hdr_buf + 4, 4);
        memcpy(&bs, hdr_buf + 8, 4);
        ms = ntohl(ms);
        bs = ntohl(bs);
        if (static_cast<uint64_t>(ms) + bs > kMaxBody) {
          fail = true;
          break;
        }
        total_rest = static_cast<size_t>(ms) + bs;
        meta_buf.resize(ms);
        body = static_cast<uint8_t*>(malloc(bs ? bs : 1));
        // move any bytes already read past the header into place
        size_t extra = have - kHeader;
        if (extra > total_rest) {  // trailing garbage beyond our frame
          fail = true;
          break;
        }
        size_t mcopy = extra < ms ? extra : ms;
        memcpy(meta_buf.data(), hdr_buf + kHeader, mcopy);
        if (extra > mcopy)
          memcpy(body, hdr_buf + kHeader + mcopy, extra - mcopy);
        have = kHeader + extra;
      }
      if (body != nullptr && have == kHeader + total_rest) break;
      // choose destination for the next read
      char* dst;
      size_t want;
      if (body == nullptr) {
        dst = reinterpret_cast<char*>(hdr_buf) + have;
        want = sizeof(hdr_buf) - have;
      } else {
        size_t got_rest = have - kHeader;
        if (got_rest < ms) {
          dst = reinterpret_cast<char*>(meta_buf.data()) + got_rest;
          want = ms - got_rest;
        } else {
          dst = reinterpret_cast<char*>(body) + (got_rest - ms);
          want = total_rest - got_rest;
        }
      }
      ssize_t r = ::recv(pf.fd, dst, want, 0);
      if (r > 0) {
        have += static_cast<size_t>(r);
        continue;
      }
      if (r < 0 && errno == EINTR) continue;
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        timed_out = true;  // SO_RCVTIMEO expired
        break;
      }
      fail = true;  // EOF or hard error
      break;
    }
    if (timed_out) {
      free(body);
      ::close(pf.fd);
      return -ETIMEDOUT;
    }
    if (fail) {
      bool fresh_fd_never_answered = (body == nullptr && have == 0);
      free(body);
      ::close(pf.fd);
      if (attempt == 0 && fresh_fd_never_answered)
        continue;  // reset while idle in pool → retry once
      return body == nullptr && have < kHeader ? -EPIPE : -EBADMSG;
    }
    MetaView m;
    if (!parse_meta(meta_buf.data(), ms, &m) || m.correlation_id != cid) {
      // one-in-flight per fd: a mismatched cid means the fd carried
      // stale state — don't pool it back
      free(body);
      ::close(pf.fd);
      return -EBADMSG;
    }
    if (m.attachment_size > bs) {  // server-controlled size: validate
      free(body);
      ::close(pf.fd);
      return -EBADMSG;
    }
    pool_release(p, pf);
    out->data = body;
    out->body_len = bs;
    out->attachment_size = m.attachment_size;
    out->error_code = m.error_code;
    out->compress_type = static_cast<int32_t>(m.compress_type);
    snprintf(out->error_text, sizeof(out->error_text), "%s",
             m.error_text.c_str());
    return 0;
  }
  return -EPIPE;
}

}  // extern "C"
