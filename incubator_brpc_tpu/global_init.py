"""Process-global one-time initialisation.

Analog of reference GlobalInitializeOrDie (global.cpp:379-580): runs
once, registers every built-in protocol, naming service, load balancer
and compress handler, and exposes default process variables. Called by
Server.start and Channel.init (the reference calls it from both too).
"""

from __future__ import annotations

import threading

_once = threading.Lock()
_done = False


def global_init():
    global _done
    if _done:
        return
    with _once:
        if _done:
            return
        from incubator_brpc_tpu.protocols import tpu_std

        tpu_std.register()
        try:
            from incubator_brpc_tpu.protocols import streaming

            streaming.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import http as http_proto

            http_proto.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import h2 as h2_proto

            h2_proto.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import redis as redis_proto

            redis_proto.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import memcache as memcache_proto

            memcache_proto.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import thrift as thrift_proto

            thrift_proto.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import mongo as mongo_proto

            mongo_proto.register()
        except ImportError:
            pass
        try:
            from incubator_brpc_tpu.protocols import rtmp as rtmp_proto

            rtmp_proto.register()
        except ImportError:
            pass
        try:
            # LAST: esp is headerless and must sit at the chain's end
            from incubator_brpc_tpu.protocols import legacy as legacy_protos

            legacy_protos.register()
        except ImportError:
            pass
        # naming services + load balancers self-register on import
        try:
            from incubator_brpc_tpu.client import naming_service  # noqa: F401
            from incubator_brpc_tpu.client import naming_remote  # noqa: F401
            from incubator_brpc_tpu.client import load_balancer  # noqa: F401
        except ImportError:
            pass
        from incubator_brpc_tpu.metrics.default_variables import (
            expose_default_variables,
        )

        expose_default_variables()
        _done = True
