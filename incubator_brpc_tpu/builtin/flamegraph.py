"""Stack sampling + self-contained SVG flamegraph rendering.

Analog of the reference's /hotspots visualization (hotspots_service.cpp
:733-796 bundles pprof + flot JS to draw profiles in the browser).  The
tpu-native equivalent needs no bundled JS: a wall-clock sampler over
``sys._current_frames()`` (the managed-runtime stand-in for gperftools'
SIGPROF sampling) aggregates stacks, and the renderer emits a single
static SVG — rect layout identical to Brendan Gregg's flamegraph.pl,
hover detail via native ``<title>`` tooltips.
"""

from __future__ import annotations

import hashlib
import sys
import threading
import time
from html import escape
from typing import Dict, List, Tuple

Stack = Tuple[str, ...]  # root-first frame labels


def sample_stacks(
    seconds: float, hz: int = 100, skip_current: bool = True
) -> Dict[Stack, int]:
    """Sample every thread's Python stack for `seconds` at `hz`.
    Returns {root-first stack: sample count}.  The sampling thread
    itself (and, optionally, the calling handler's thread) is excluded
    so the profile shows the server's work, not the profiler's."""
    agg: Dict[Stack, int] = {}
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    period = 1.0 / hz
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if skip_current and tid == me:
                continue
            stack: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            key = tuple(reversed(stack))
            agg[key] = agg.get(key, 0) + 1
        time.sleep(period)
    return agg


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self.children: Dict[str, _Node] = {}


def _build_trie(stacks: Dict[Stack, float]) -> _Node:
    root = _Node("all")
    for stack, weight in stacks.items():
        root.value += weight
        node = root
        for frame in stack:
            child = node.children.get(frame)
            if child is None:
                child = node.children[frame] = _Node(frame)
            child.value += weight
            node = child
    return root


def _color(name: str) -> str:
    # stable warm palette per frame name (flamegraph.pl hash colors)
    h = hashlib.md5(name.encode()).digest()
    r = 205 + h[0] % 50
    g = 60 + h[1] % 130
    b = h[2] % 60
    return f"rgb({r},{g},{b})"


def render_flamegraph(
    stacks: Dict[Stack, float],
    title: str = "flame graph",
    unit: str = "samples",
    width: int = 1200,
) -> str:
    """Aggregated stacks → standalone SVG string."""
    root = _build_trie(stacks)
    if root.value <= 0:
        return (
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
            f'height="40"><text x="8" y="24">no samples</text></svg>'
        )
    row_h = 17
    # depth of the trie bounds the image height
    def depth(n: _Node) -> int:
        return 1 + max((depth(c) for c in n.children.values()), default=0)

    levels = depth(root)
    height = (levels + 2) * row_h + 28
    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" font-family="monospace" font-size="11">',
        '<style>rect:hover{stroke:#000;stroke-width:1}</style>',
        f'<text x="8" y="18" font-size="14">{escape(title)} '
        f'— {root.value:.0f} {escape(unit)}</text>',
    ]
    min_w = 0.5  # px: below this a frame (and its children) is elided

    def emit(node: _Node, x: float, y: int, w: float):
        if w < min_w:
            return
        pct = 100.0 * node.value / root.value
        label = node.name if w > 60 else ""
        out.append(
            f'<g><title>{escape(node.name)} — {node.value:.0f} '
            f"{escape(unit)} ({pct:.2f}%)</title>"
            f'<rect x="{x:.2f}" y="{y}" width="{max(w - 0.3, 0.3):.2f}" '
            f'height="{row_h - 1}" fill="{_color(node.name)}" rx="1"/>'
            + (
                f'<text x="{x + 3:.2f}" y="{y + 12}" '
                f'clip-path="inset(0)">{escape(label[: int(w // 7)])}</text>'
                if label
                else ""
            )
            + "</g>"
        )
        cx = x
        for child in sorted(
            node.children.values(), key=lambda c: -c.value
        ):
            cw = w * child.value / node.value
            emit(child, cx, y - row_h, cw)
            cx += cw

    base_y = height - row_h - 4
    emit(root, 0.0, base_y, float(width))
    out.append("</svg>")
    return "".join(out)
