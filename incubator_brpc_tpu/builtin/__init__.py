"""Builtin HTTP services — the observability surface.

Analog of reference src/brpc/builtin/ (13.2k LoC): served on the same
port as RPC traffic (the InputMessenger inversion lets HTTP coexist
with tpu_std), or restricted via internal_port. Implemented pages:

  /            index: links to everything (index_service)
  /status      server overview: methods, qps, latency pXX, concurrency
  /vars[?f]    metrics dump with wildcard filter; ?console=1 (or a
               browser Accept header) renders the HTML dashboard with
               SVG sparklines from the 1 Hz sampler rings
  /metrics     Prometheus text exposition (prometheus_metrics_service)
  /flags       runtime flag listing + ?setvalue editing (flags_service)
  /connections live socket table (connections_service)
  /rpcz        tracing spans; ?trace= merges the sqlite backend
  /health      liveness probe (health_service)
  /version     framework version
  /list        registered services/methods (list_service)
  /threads     runtime worker/blocked counts
  /bthreads    full stack dump of every thread/task (gdb-plugin analog)
  /ids         CallId pool stats (ids_service analog)
  /sockets     Socket pool stats
  /pprof/profile, /hotspots/cpu   cProfile capture (?seconds=N)
  /hotspots/contention            lock-wait profile (Collector-sampled)
  /hotspots/heap, /hotspots/growth  tracemalloc profiles
  /vlog        toggle verbose logging

Handlers are plain callables (server, http_msg) -> (status, body,
content_type), registered per path at server start.
"""

from __future__ import annotations

import io
import json
import threading
import time

from incubator_brpc_tpu import __version__ as _version
from incubator_brpc_tpu.metrics.variable import dump_exposed, list_exposed, _registry
from incubator_brpc_tpu.utils.flags import list_flags, set_flag

_START_TIME = time.time()


def register_builtin_services(server):
    for path, fn in {
        "/": index_page,
        "/index": index_page,
        "/status": status_page,
        "/vars": vars_page,
        "/metrics": metrics_page,
        "/flags": flags_page,
        "/connections": connections_page,
        "/rpcz": rpcz_page,
        "/rpcz/export": rpcz_export_page,
        "/cluster/export": cluster_export_page,
        "/cluster/metrics": cluster_metrics_page,
        "/cluster/latency_breakdown": cluster_latency_breakdown_page,
        "/cluster/stragglers": cluster_stragglers_page,
        "/rpc_dump": rpc_dump_page,
        "/latency_breakdown": latency_breakdown_page,
        "/health": health_page,
        "/version": version_page,
        "/list": list_page,
        "/threads": threads_page,
        "/bthreads": bthreads_page,
        "/ids": ids_page,
        "/sockets": sockets_page,
        "/pprof/profile": pprof_profile,
        "/pprof/heap": pprof_heap,
        "/pprof/growth": pprof_growth,
        "/pprof/symbol": pprof_symbol,
        "/pprof/cmdline": pprof_cmdline,
        "/hotspots/cpu": pprof_profile,
        "/hotspots/contention": contention_page,
        "/hotspots/heap": heap_page,
        "/hotspots/growth": growth_page,
        "/hotspots/hbm": hbm_page,
        "/hotspots/device": device_page,
        "/hotspots/runtime": runtime_page,
        "/protobufs": protobufs_page,
        "/dir": dir_page,
        "/vlog": vlog_page,
        "/chaos": chaos_page,
        "/batching": batching_page,
        "/admission": admission_page,
        "/cache": cache_page,
        "/resharding": resharding_page,
        "/replication": replication_page,
        "/serving": serving_page,
    }.items():
        server.add_builtin_handler(path, fn)


def index_page(server, msg):
    pages = [
        "status", "vars", "vars?console=1", "metrics", "flags",
        "connections", "rpcz", "rpcz/export?trace=", "latency_breakdown",
        "cluster/export", "cluster/metrics", "cluster/latency_breakdown",
        "cluster/stragglers", "rpc_dump", "health",
        "version", "list", "threads",
        "bthreads", "ids", "sockets", "hotspots/cpu",
        "hotspots/contention", "hotspots/heap", "hotspots/growth",
        "hotspots/hbm", "hotspots/device", "hotspots/runtime",
        "pprof/heap", "pprof/growth", "pprof/symbol", "pprof/cmdline",
        "protobufs", "dir", "vlog", "chaos", "batching", "admission",
        "cache", "resharding", "replication", "serving",
    ]
    links = "\n".join(f'<a href="/{p}">/{p}</a><br>' for p in pages)
    return 200, f"<html><body><h1>{server.options.server_info_name}</h1>{links}</body></html>", "text/html"


def status_page(server, msg):
    # pull native fast-path completions into MethodStatus first, so the
    # page reflects traffic the C++ engine answered off-GIL
    server.harvest_native_stats()
    out = [f"server: {server.options.server_info_name}"]
    out.append(f"version: {_version}")
    out.append(f"uptime_s: {time.time() - _START_TIME:.0f}")
    out.append(f"listen: {server.listen_endpoint}")
    out.append(f"connections: {server.connection_count()}")
    out.append("")
    for full_name, status in sorted(server._method_status.items()):
        rec = status.latency_rec
        out.append(
            f"{full_name}:\n"
            f"  count={rec.count()} qps={rec.qps():.1f} concurrency={status.concurrency}\n"
            f"  latency_us avg={rec.latency():.0f} p50={rec.latency_percentile(0.5):.0f} "
            f"p90={rec.latency_percentile(0.9):.0f} p99={rec.latency_percentile(0.99):.0f} "
            f"p999={rec.latency_percentile(0.999):.0f} max={rec.max_latency():.0f}"
            + (
                " (percentiles approximate: native fast-path folds at mean)"
                if rec.bulk_folded
                else ""
            )
            + "\n"
            f"  errors={status.errors.get_value()}"
            + (
                # the (possibly moving) limiter state: current
                # max_concurrency for the auto limiter was computed but
                # never surfaced per-render before the /batching round
                f" limiter={type(status.limiter).__name__}"
                f" max_concurrency={status.limiter.max_concurrency()}"
                if status.limiter
                else ""
            )
            + _admission_status_line(server, full_name)
            + _batch_status_line(server, full_name)
        )
    out.extend(_streams_section())
    out.extend(_replication_section())
    out.extend(_serving_section())
    out.extend(_ring_section(server))
    return 200, "\n".join(out), "text/plain"


def _admission_status_line(server, full_name: str) -> str:
    """One /status line per method when a tiered admission policy is
    active: the tier tenant-less traffic resolves to, its capacity
    share and quota (server/admission.py, docs/overload.md)."""
    adm = getattr(server, "admission", None)
    if adm is None or not adm.policy.active:
        return ""
    policy = adm.policy
    tier = policy.tier_of("", full_name)
    spec = policy.tiers.get(tier)
    return (
        f"\n  admission: tier={tier} share={policy.share(tier):.2f} "
        f"quota={spec.quota if spec else 0} "
        f"inflight={adm.tier_inflight(tier)}"
    )


def _streams_section():
    """Live streaming-RPC streams grouped per negotiating method
    (streaming/observe.py registry) — empty when the process never
    established a stream, so /status costs nothing extra then."""
    import sys

    observe = sys.modules.get("incubator_brpc_tpu.streaming.observe")
    if observe is None:
        return []
    by_method = observe.streams_by_method()
    if not by_method:
        return []
    lines = ["", "streams:"]
    for method, rows in sorted(by_method.items()):
        lines.append(f"  {method}: {len(rows)} live")
        for r in rows[:16]:  # bound the page, not the registry
            lines.append(
                f"    id={r['id']} peer={r['peer']} "
                f"frames_out={r['frames_sent']} frames_in={r['frames_received']} "
                f"unconsumed={r['unconsumed']} consumed={r['consumed_bytes']} "
                f"writer_blocked={r['writer_blocked_us']}us"
            )
        if len(rows) > 16:
            lines.append(f"    ... {len(rows) - 16} more")
    return lines


def _replication_section():
    """Per-replica-group /status lines (replication/group.py registry)
    — empty when the process registered no groups, so /status costs
    nothing extra then (same discipline as _streams_section)."""
    import sys

    grp = sys.modules.get("incubator_brpc_tpu.replication.group")
    if grp is None:
        return []
    groups = grp.groups_snapshot()
    if not groups:
        return []
    lines = ["", "replication:"]
    for name, d in sorted(groups.items()):
        healthy = sum(
            1 for r in d["replicas"] if r["alive"] and not r["repairing"]
        )
        c = d["counters"]
        lines.append(
            f"  {name}: leader={d['leader']} epoch={d['epoch']} "
            f"lease_remaining={d['lease_remaining_s']:.3f}s "
            f"quorum={d['quorum']} serving={healthy}/{len(d['replicas'])} "
            f"writes={c['quorum_writes']} fenced={c['fenced_writes']} "
            f"quorum_failures={c['quorum_failures']} "
            f"leader_changes={c['leader_changes']} "
            f"repair_keys={c['repair_keys']} hedged={c['hedged_reads']}"
        )
    return lines


def _serving_section():
    """Per-session /status lines (serving/session.py registry) —
    empty when the process served no disaggregated sessions, so
    /status costs nothing extra then (same discipline as
    _streams_section)."""
    import sys

    sess = sys.modules.get("incubator_brpc_tpu.serving.session")
    if sess is None:
        return []
    sessions = sess.sessions_snapshot()
    if not sessions:
        return []
    lines = ["", "serving:"]
    for sid, d in sorted(sessions.items())[:32]:  # bound the page
        lines.append(
            f"  {sid}: state={d['state']} replica={d['replica']} "
            f"epoch={d['epoch']} kv_epoch={d['kv_epoch']} "
            f"kv_bytes={d['kv_bytes']} "
            f"tokens={d['tokens']}/{d['max_tokens']} "
            f"prefills={d['prefill_executions']} "
            f"migrations={d['migrations']}"
        )
    if len(sessions) > 32:
        lines.append(f"  ... {len(sessions) - 32} more")
    return lines


def _ring_section(server):
    """One ``ring:`` /status line when ring traffic exists: the server
    engine's response-ring step log (ns_ring_stats) plus the process's
    client-side ring counters (metrics/ring_metrics.py) — empty when
    neither lane ever fired, so /status costs nothing extra then (same
    discipline as _streams_section)."""
    import sys

    srv = {"windows": 0, "responses": 0, "flush_bursts": 0}
    eng_stats = server._engine_op(
        lambda eng: eng.ring_stats() if hasattr(eng, "ring_stats") else None
    ) if hasattr(server, "_engine_op") else None
    if eng_stats:
        srv = eng_stats
    rm = sys.modules.get("incubator_brpc_tpu.metrics.ring_metrics")
    cli = rm.snapshot() if rm is not None else {
        "crossings": 0, "windows": 0, "flush_bursts": 0,
    }
    if not any(srv.values()) and not any(cli.values()):
        return []
    return [
        "",
        "ring:",
        (
            f"  server windows={srv['windows']} "
            f"responses={srv['responses']} "
            f"flush_bursts={srv['flush_bursts']}"
        ),
        (
            f"  client crossings={cli['crossings']} "
            f"windows={cli['windows']}"
        ),
    ]


def _batch_status_line(server, full_name: str) -> str:
    """One /status line for a batched method: live queue depth + the
    coalescing shape (batching/batcher.py counters)."""
    batcher = server._batchers.get(full_name)
    if batcher is None:
        return ""
    return (
        f"\n  batching: queue_depth={batcher.pending()} "
        f"batches={batcher.batches} rows={batcher.rows} "
        f"shed={batcher.shed.get_value()} "
        f"occupancy={batcher.occupancy():.2f} "
        f"max_wait_us={batcher.policy.max_wait_us}"
    )


def vars_page(server, msg):
    wildcard = msg.query.get("filter", msg.query.get("f", "*"))
    # tri-state: console=1 forces HTML, console=0 forces plain text,
    # absent sniffs the Accept header (browsers get the dashboard)
    console = msg.query.get("console")
    want_html = (
        console not in ("0", "false")
        if console is not None
        else "text/html" in (msg.header("accept", "") or "")
    )
    if want_html:
        return vars_html(wildcard)
    pairs = dump_exposed(wildcard)
    return 200, "\n".join(f"{k} : {v}" for k, v in pairs), "text/plain"


def _sparkline_svg(values, w=120, h=22) -> str:
    """Inline SVG sparkline (the reference embeds flot JS for its
    dashboard plots; an SVG needs no scripts)."""
    if len(values) < 2:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    step = w / (len(values) - 1)
    pts = " ".join(
        f"{i * step:.1f},{h - 2 - (v - lo) / span * (h - 4):.1f}"
        for i, v in enumerate(values)
    )
    return (
        f'<svg width="{w}" height="{h}"><polyline points="{pts}" '
        'fill="none" stroke="#4a90d9" stroke-width="1.5"/></svg>'
    )


def vars_html(wildcard: str):
    """HTML dashboard: value table with 1 Hz-series sparklines for
    windowed variables (Window/PerSecond sampler rings)."""
    import html as _html

    rows = []
    for name, desc in dump_exposed(wildcard):
        var = _registry.get(name)
        spark = ""
        sampler = getattr(var, "_sampler", None)
        if sampler is not None:
            from incubator_brpc_tpu.metrics.window import PerSecond

            with sampler.lock:
                series = [v for _, v in sampler.samples]
            if series and all(isinstance(v, (int, float)) for v in series):
                if isinstance(var, PerSecond) and len(series) > 1:
                    # show the per-second rate series, not cumulative
                    series = [
                        b - a for a, b in zip(series, series[1:])
                    ]
                spark = _sparkline_svg(series)
        rows.append(
            f"<tr><td><code>{_html.escape(name)}</code></td>"
            f"<td>{_html.escape(str(desc))}</td><td>{spark}</td></tr>"
        )
    body = (
        "<html><head><style>"
        "body{font-family:monospace;margin:16px}"
        "table{border-collapse:collapse}"
        "td{border-bottom:1px solid #ddd;padding:3px 12px 3px 0;"
        "vertical-align:middle}"
        "</style></head><body>"
        f"<h2>/vars ({_html.escape(wildcard)})</h2>"
        '<p><a href="/">index</a> · plain text: <a href="/vars?console=0">/vars?console=0</a></p>'
        "<table><tr><th>variable</th><th>value</th><th>last&nbsp;~10s</th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )
    return 200, body, "text/html"


def metrics_page(server, msg):
    """Prometheus text exposition (prometheus_metrics_service.h:26)."""
    from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension

    lines = []
    for name in list_exposed():
        var = _registry.get(name)
        if var is None:
            continue
        if isinstance(var, MultiDimension):
            for key, sub in var.items():
                labels = ",".join(
                    f'{k}="{v}"' for k, v in zip(var.labels, key)
                )
                val = _num(sub.get_value())
                if val is not None:
                    lines.append(f"{name}{{{labels}}} {val}")
            continue
        val = _num(var.get_value())
        if val is not None:
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {val}")
    return 200, "\n".join(lines) + "\n", "text/plain; version=0.0.4"


def _num(v):
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return v
    return None


def flags_page(server, msg):
    setv = msg.query.get("setvalue")
    name = msg.query.get("flag")
    if setv is not None and name:
        ok = set_flag(name, setv)
        if not ok:
            return 403, f"flag {name} is not reloadable or value invalid", "text/plain"
        return 200, f"{name} set to {setv}", "text/plain"
    out = []
    for fname, f in sorted(list_flags().items()):
        mark = " (R)" if f.reloadable else ""
        out.append(f"{fname}={f.value}{mark}  default={f.default}  {f.help}")
    out.append("")
    out.append("set with /flags?flag=NAME&setvalue=VALUE (reloadable flags only)")
    return 200, "\n".join(out), "text/plain"


def connections_page(server, msg):
    from incubator_brpc_tpu.transport import socket as sm

    out = [
        f"total_connections: {sm.g_connections.get_value()}",
        f"in_bytes: {sm.g_in_bytes.get_value()}  out_bytes: {sm.g_out_bytes.get_value()}",
        f"in_messages: {sm.g_in_messages.get_value()}  out_messages: {sm.g_out_messages.get_value()}",
        "",
    ]
    if server._acceptor is not None:
        for sock in server._acceptor.connections():
            if sock is None:
                continue
            out.append(
                f"sid={sock.sid:x} remote={sock.remote} failed={sock.failed} "
                f"unwritten={sock._unwritten}"
            )
    return 200, "\n".join(out), "text/plain"


def rpcz_page(server, msg):
    from incubator_brpc_tpu.observability import trace as trace_mod
    from incubator_brpc_tpu.observability.span import parse_trace_id, span_db

    trace = msg.query.get("trace")
    if trace:
        try:
            tid = parse_trace_id(trace)
        except ValueError:
            return 400, f"bad trace id {trace!r} (hex expected)", "text/plain"
        if msg.query.get("stitch") not in (None, "", "0", "false"):
            # cluster view: follow the peer endpoints on this trace's
            # client sub-spans, pull their spans over /rpcz/export, and
            # render one tree with per-leg wire+queue residuals
            from incubator_brpc_tpu.observability import cluster

            stitched = cluster.render_stitched(tid)
            if stitched is None:
                return 200, f"no spans for trace {trace}", "text/plain"
            return 200, stitched, "text/plain"
        lines = []
        # hierarchical timeline: client span → collective legs → server
        # span, indented, each line carrying its phase deltas
        tree = trace_mod.render(tid)
        if tree:
            lines.append(tree)
        # sqlite backend covers ring-evicted spans and prior runs
        persisted = span_db().persisted_by_trace(tid)
        in_ring = {s.describe() for s in span_db().by_trace(tid)}
        lines += [
            f"[persisted] {d}" for d in persisted if d not in in_ring
        ]
        if not lines:
            return 200, f"no spans for trace {trace}", "text/plain"
        return 200, "\n".join(lines), "text/plain"
    spans = span_db().recent(int(msg.query.get("n", "50")))
    if not spans:
        return 200, "no spans collected (set rpcz_enabled=true and make calls)", "text/plain"
    return 200, "\n".join(s.describe() for s in reversed(spans)), "text/plain"


def latency_breakdown_page(server, msg):
    """Per-method per-phase latency percentiles (parse/queue/callback/
    write/send, from rpcz span stamps) + the _runtime queue-wait rows.
    The same numbers export to Prometheus as rpc_phase_latency_us."""
    from incubator_brpc_tpu.observability import latency_breakdown

    return 200, latency_breakdown.render(), "text/plain"


def rpcz_export_page(server, msg):
    """This process's SpanDB spans for one trace, as JSON — the wire
    format the cluster stitcher consumes (observability/cluster.py).
    Ids travel in the canonical hex form so they copy-paste between
    /rpcz pages, x-trace-id headers and this endpoint."""
    from incubator_brpc_tpu.observability import cluster
    from incubator_brpc_tpu.observability.span import parse_trace_id

    trace = msg.query.get("trace")
    if not trace:
        return 400, "missing trace=<hex id>", "text/plain"
    try:
        tid = parse_trace_id(trace)
    except ValueError:
        return 400, f"bad trace id {trace!r} (hex expected)", "text/plain"
    payload = cluster.export_trace(
        tid, endpoint=str(server.listen_endpoint or "")
    )
    return 200, json.dumps(payload), "application/json"


def _cluster_export_payload(server) -> dict:
    """This replica's mergeable aggregation STATE (counts + histogram
    buckets, never computed percentiles): per-method server latency and
    every exposed MultiDimension family."""
    from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension
    from incubator_brpc_tpu.observability import cluster  # noqa: F401 — registers fan-out metrics

    server.harvest_native_stats()
    methods = {}
    for full_name, status in server._method_status.items():
        snap = status.latency_rec.mergeable_snapshot()
        errors = int(status.errors.get_value())
        if not snap["count"] and not snap["latency_num"] and not errors:
            continue
        methods[full_name] = {"latency": snap, "errors": errors}
    dims = {}
    for name in list_exposed():
        var = _registry.get(name)
        if isinstance(var, MultiDimension):
            snap = var.mergeable_snapshot()
            if snap["stats"]:
                dims[name] = snap
    return {
        "endpoint": str(server.listen_endpoint or ""),
        "methods": methods,
        "dims": dims,
    }


def cluster_export_page(server, msg):
    """The scrape surface /cluster/metrics on any replica pulls from
    the whole pod and merges exactly (_cluster_export_payload)."""
    return 200, json.dumps(_cluster_export_payload(server)), "application/json"


def _is_self_endpoint(server, ep: str) -> bool:
    """Does `ep` name THIS server?  The scrape must answer itself
    in-process: a synchronous HTTP fetch back to our own port from
    inside a builtin handler would hold the runtime worker the inner
    request needs — a self-deadlock on single-worker runtimes."""
    host, sep, port = ep.rpartition(":")
    if not sep or not port.isdigit() or int(port) != server.port:
        return False
    lep = server.listen_endpoint
    lhost = str(getattr(lep, "host", "") or "")
    return host in ("127.0.0.1", "localhost", "0.0.0.0", lhost)


def _cluster_scrape(server, msg):
    """Shared replica-resolution + scrape for the /cluster pages.
    Returns ((payloads, errors), None) or (None, error_response)."""
    from incubator_brpc_tpu.observability import cluster

    spec = msg.query.get("replicas", "")
    if not spec:
        return None, (
            400,
            "missing replicas=host:port,... or replicas=<naming url>",
            "text/plain",
        )
    try:
        replicas = cluster.resolve_replicas(spec)
    except Exception as e:  # noqa: BLE001
        return None, (400, f"bad replicas spec: {e}", "text/plain")
    if not replicas:
        return None, (400, f"no replicas resolved from {spec!r}", "text/plain")
    try:
        timeout = float(msg.query.get("timeout_s", "3"))
    except ValueError:
        return None, (400, "bad timeout_s", "text/plain")
    payloads, errors = [], []
    for ep in replicas:
        if _is_self_endpoint(server, ep):
            payloads.append(_cluster_export_payload(server))
            cluster.cluster_scrapes_total << 1
        else:
            p, e = cluster.scrape_exports([ep], timeout=timeout)
            payloads.extend(p)
            errors.extend(e)
    return (payloads, errors), None


def cluster_metrics_page(server, msg):
    """Pod-merged Prometheus-style exposition.  ?replicas= names the
    pod (explicit endpoints or a naming url); each replica's
    /cluster/export state merges elementwise, so latency percentiles
    here are exactly those of the pooled samples — not an average of
    per-replica percentiles."""
    from incubator_brpc_tpu.observability import cluster

    scraped, err = _cluster_scrape(server, msg)
    if err is not None:
        return err
    payloads, errors = scraped
    merged = cluster.merge_exports(payloads)
    return 200, cluster.render_merged_metrics(merged, errors), "text/plain"


def cluster_latency_breakdown_page(server, msg):
    """/latency_breakdown over the whole pod: per-replica recorder
    state merged exactly, rendered with the same table the local page
    uses."""
    from incubator_brpc_tpu.observability import cluster, latency_breakdown

    scraped, err = _cluster_scrape(server, msg)
    if err is not None:
        return err
    payloads, errors = scraped
    merged = cluster.merge_exports(payloads)
    table = cluster.merged_breakdown(merged)
    head = [
        f"merged over {len(merged['replicas'])} replicas: "
        + ",".join(merged["replicas"])
    ]
    head += [f"[unreachable] {e}" for e in errors]
    body = (
        latency_breakdown.render_table(table)
        if table
        else "no phase data on any replica (rpcz_enabled must be true)"
    )
    return 200, "\n".join(head) + "\n\n" + body, "text/plain"


def cluster_stragglers_page(server, msg):
    """Shard/replica straggler attribution over the sliding fan-out
    window: peers ranked by drag on fan-out tail latency, split into
    server time vs wire+queue residual (?window_s= overrides)."""
    from incubator_brpc_tpu.observability import cluster

    window = msg.query.get("window_s")
    try:
        window_f = float(window) if window else None
    except ValueError:
        return 400, f"bad window_s {window!r}", "text/plain"
    report = cluster.fanout_tracker().report(window_f)
    return 200, json.dumps(report, indent=1), "application/json"


def rpc_dump_page(server, msg):
    """Request-capture control + visibility (observability/rpc_dump.py).

    GET  → JSON: enabled flag, dir, ratio, sampled count, dump files.
    POST → enable capture at runtime: /rpc_dump?dir=PATH&ratio=0.01
           (or the same keys as a JSON body); dir="" / disable=1 turns
           it off.  Same gate ServerOptions.rpc_dump_dir arms at start.
    """
    from incubator_brpc_tpu.observability.rpc_dump import (
        RpcDumpContext,
        list_dump_files,
    )

    if msg.method == "POST":
        params = {k: v for k, v in msg.query.items()}
        body = msg.body.to_bytes() if len(msg.body) else b""
        if body:
            try:
                parsed = json.loads(body.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                parsed = None
            if not isinstance(parsed, dict):
                return 400, "POST body must be a JSON object", "text/plain"
            params.update(parsed)
        if params.get("disable") not in (None, "", "0", "false", False):
            server._rpc_dump_ctx = None
            return 200, json.dumps({"enabled": False}), "application/json"
        dump_dir = params.get("dir")
        if not dump_dir:
            return 400, "missing dir=PATH (or disable=1)", "text/plain"
        try:
            ratio = float(params.get("ratio", 0.01))
            if not (0 < ratio <= 1):
                raise ValueError
        except (TypeError, ValueError):
            return 400, f"bad ratio {params.get('ratio')!r} (0<ratio<=1)", "text/plain"
        try:
            server._rpc_dump_ctx = RpcDumpContext(
                str(dump_dir), sample_ratio=ratio
            )
        except OSError as e:
            return 400, f"cannot open dump dir: {e}", "text/plain"
        return (
            200,
            json.dumps({"enabled": True, "dir": str(dump_dir), "ratio": ratio}),
            "application/json",
        )
    ctx = getattr(server, "_rpc_dump_ctx", None)
    if ctx is None:
        return 200, json.dumps({"enabled": False}), "application/json"
    return (
        200,
        json.dumps(
            {
                "enabled": True,
                "dir": ctx.dump_dir,
                "ratio": ctx.sample_ratio,
                "sampled": ctx.sampled,
                "files": list_dump_files(ctx.dump_dir),
            }
        ),
        "application/json",
    )


def health_page(server, msg):
    return (200, "OK", "text/plain") if server.is_running() else (503, "stopping", "text/plain")


def version_page(server, msg):
    return 200, f"incubator-brpc_tpu/{_version}", "text/plain"


def list_page(server, msg):
    out = []
    for name, svc in sorted(server.services().items()):
        out.append(name)
        for mname, spec in sorted(svc.method_specs().items()):
            out.append(
                f"  {mname}({spec.request_class.__name__}) -> {spec.response_class.__name__}"
            )
    return 200, "\n".join(out), "text/plain"


def threads_page(server, msg):
    import threading

    from incubator_brpc_tpu.runtime.scheduler import _default_control

    out = [f"python_threads: {threading.active_count()}"]
    if _default_control is not None:
        out.append(f"runtime_workers: {_default_control.worker_count()}")
        out.append(f"runtime_blocked: {_default_control.blocked_count()}")
    for t in threading.enumerate():
        out.append(f"  {t.name} daemon={t.daemon}")
    return 200, "\n".join(out), "text/plain"


def bthreads_page(server, msg):
    """Full stack dump of every runtime thread/task (the reference's
    /bthreads debug page + gdb_bthread_stack plugin, without gdb)."""
    from incubator_brpc_tpu.tools.task_stacks import dump_stacks

    return 200, dump_stacks(), "text/plain"


def ids_page(server, msg):
    from incubator_brpc_tpu.runtime.call_id import default_pool

    pool = default_pool()
    return (
        200,
        f"call_id_slots: {len(pool._slots)}\nfree: {len(pool._free)}\n"
        f"live: {len(pool._slots) - len(pool._free)}",
        "text/plain",
    )


def sockets_page(server, msg):
    from incubator_brpc_tpu.transport.socket import Socket

    pool = Socket._pool
    return (
        200,
        f"socket_slots: {pool.size()}\nfree: {pool.free_count()}\n"
        f"live: {pool.size() - pool.free_count()}",
        "text/plain",
    )


def pprof_profile(server, msg):
    """CPU profile capture — the /hotspots/cpu analog (gperftools in the
    reference, builtin/hotspots_service.cpp; cProfile+pstats here).
    ?view=flame samples sys._current_frames() instead and renders an
    SVG flamegraph (the reference bundles pprof+flot JS for the same
    visualization, hotspots_service.cpp:733-796)."""
    seconds = min(float(msg.query.get("seconds", "1")), 10.0)
    if msg.query.get("view") == "flame":
        from incubator_brpc_tpu.builtin.flamegraph import (
            render_flamegraph,
            sample_stacks,
        )

        stacks = sample_stacks(seconds)
        svg = render_flamegraph(
            {k: float(v) for k, v in stacks.items()},
            title=f"cpu wall-clock samples over {seconds:g}s",
        )
        return 200, svg, "image/svg+xml"
    import cProfile
    import pstats

    prof = cProfile.Profile()
    prof.enable()
    time.sleep(seconds)
    prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(40)
    return 200, buf.getvalue(), "text/plain"


def contention_page(server, msg):
    """Contention profile (reference /hotspots/contention: bthread
    mutex wait samples through the bvar Collector, mutex.cpp:106-180).
    ?reset=1 clears the aggregate."""
    from incubator_brpc_tpu.observability.contention import profiler

    if msg.query.get("reset"):
        profiler().reset()
        return 200, "contention profile reset", "text/plain"
    if msg.query.get("view") == "flame":
        from incubator_brpc_tpu.builtin.flamegraph import render_flamegraph

        stacks = {
            stack: ns / 1000.0
            for stack, (count, ns) in profiler().snapshot().items()
        }
        return (
            200,
            render_flamegraph(stacks, title="lock contention", unit="us"),
            "image/svg+xml",
        )
    return 200, profiler().render(int(msg.query.get("top", "40"))), "text/plain"


_tracemalloc_baseline = [None]


def heap_page(server, msg):
    """Heap profile via tracemalloc (reference /hotspots/heap uses
    tcmalloc MallocExtension; tracemalloc is the managed-runtime
    equivalent). First call starts tracing; later calls report the
    top allocation sites."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(12)
        _tracemalloc_baseline[0] = None
        return 200, "tracemalloc started; re-fetch for the profile", "text/plain"
    snap = tracemalloc.take_snapshot()
    top = snap.statistics("lineno")[: int(msg.query.get("top", "40"))]
    cur, peak = tracemalloc.get_traced_memory()
    out = [f"--- heap  current={cur} peak={peak}", ""]
    out += [str(s) for s in top]
    return 200, "\n".join(out), "text/plain"


def growth_page(server, msg):
    """Heap growth since the previous /hotspots/growth call (reference
    /hotspots/growth: tcmalloc growth stacks)."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(12)
        _tracemalloc_baseline[0] = tracemalloc.take_snapshot()
        return 200, "tracemalloc started; re-fetch for growth", "text/plain"
    snap = tracemalloc.take_snapshot()
    base = _tracemalloc_baseline[0]
    _tracemalloc_baseline[0] = snap
    if base is None:
        return 200, "baseline captured; re-fetch for growth", "text/plain"
    diff = snap.compare_to(base, "lineno")[: int(msg.query.get("top", "40"))]
    out = ["--- growth since last fetch", ""]
    out += [str(s) for s in diff]
    return 200, "\n".join(out), "text/plain"


def hbm_page(server, msg):
    """HBM heap profile (observability/profiling.py): per-tag adopted
    device bytes, cross-checked against the device's own census with
    an explicit ``<dark>`` bucket.  ``?growth=1`` diffs against the
    previous growth fetch; ``?rebase=1`` snaps the census baseline so
    everything currently resident counts as explained."""
    from incubator_brpc_tpu.observability import profiling

    if msg.query.get("rebase") not in (None, "", "0", "false"):
        cen = profiling.rebase_census()
        return (
            200,
            f"census baseline rebased to {cen['bytes']} bytes "
            f"(source={cen['source']})",
            "text/plain",
        )
    top = int(msg.query.get("top", "40"))
    if msg.query.get("growth") not in (None, "", "0", "false"):
        return 200, profiling.render_hbm_growth(top), "text/plain"
    return 200, profiling.render_hbm(top=top), "text/plain"


def device_page(server, msg):
    """Device-time attribution (observability/profiling.py).  Without
    arguments: the always-on per-kernel-family counter table.
    ``?seconds=N`` arms an on-demand ``jax.profiler.trace`` window (the
    deep capture; chaos site ``profile.capture``) and summarizes the
    families that executed inside it."""
    from incubator_brpc_tpu.observability import profiling

    seconds = msg.query.get("seconds")
    if seconds is None:
        return 200, profiling.render_device(), "text/plain"
    try:
        seconds_f = float(seconds)
    except ValueError:
        return 400, f"bad seconds {seconds!r}", "text/plain"
    try:
        result = profiling.device_capture(seconds_f)
    except profiling.CaptureError as e:
        # failed capture → error page; serving continues and the
        # finally-disarmed trace session never leaks (regression-tested)
        return 500, f"device capture failed: {e}", "text/plain"
    return 200, profiling.render_capture(result), "text/plain"


def runtime_page(server, msg):
    """Runtime occupancy (observability/profiling.py): worker/blocked/
    parked counts, steal and park totals, per-worker run-queue depth
    and the task queue-wait aggregate — the M:N scheduler's utilization
    evidence."""
    from incubator_brpc_tpu.observability import profiling

    return 200, profiling.render_runtime(), "text/plain"


# ---------------------------------------------------------------------------
# pprof protocol endpoints (reference builtin/pprof_service.h:38-58):
# machine-readable profiles an external `pprof` / `go tool pprof` can
# fetch.  Python allocation sites have no machine addresses, so each
# distinct file:line:function gets a stable SYNTHETIC address which
# /pprof/symbol resolves back — the exact contract pprof's two-step
# fetch+symbolize protocol defines.
# ---------------------------------------------------------------------------

_pprof_sym_lock = threading.Lock()
_pprof_sym_by_name: dict = {}
_pprof_name_by_addr: dict = {}
_PPROF_ADDR_BASE = 0x10000000000  # clear of real mappings


def _pprof_addr_of(name: str) -> int:
    with _pprof_sym_lock:
        addr = _pprof_sym_by_name.get(name)
        if addr is None:
            addr = _PPROF_ADDR_BASE + 16 * (len(_pprof_sym_by_name) + 1)
            _pprof_sym_by_name[name] = addr
            _pprof_name_by_addr[addr] = name
        return addr


def _pprof_heap_text(stats) -> str:
    """Legacy gperftools heap-profile text format over tracemalloc
    traceback statistics (what `pprof http://host/pprof/heap` parses)."""
    total_objs = sum(s.count for s in stats)
    total_bytes = sum(s.size for s in stats)
    lines = [
        f"heap profile: {total_objs}: {total_bytes} "
        f"[{total_objs}: {total_bytes}] @ heap_v2/1"
    ]
    for s in stats:
        addrs = []
        for frame in s.traceback:
            sym = f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
            addrs.append(f"{_pprof_addr_of(sym):#x}")
        if not addrs:
            addrs.append(f"{_pprof_addr_of('unknown'):#x}")
        lines.append(
            f"{s.count}: {s.size} [{s.count}: {s.size}] @ "
            + " ".join(addrs)
        )
    lines.append("")
    lines.append("MAPPED_LIBRARIES:")
    return "\n".join(lines)


def pprof_heap(server, msg):
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(12)
        return (
            200,
            "tracemalloc started; re-fetch for the profile",
            "text/plain",
        )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("traceback")[: int(msg.query.get("top", "200"))]
    return 200, _pprof_heap_text(stats), "text/plain"


_pprof_growth_baseline = [None]  # separate from /hotspots/growth's slot:
# each endpoint diffs against ITS OWN previous fetch


def pprof_growth(server, msg):
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(12)
        _pprof_growth_baseline[0] = tracemalloc.take_snapshot()
        return 200, "tracemalloc started; re-fetch for growth", "text/plain"
    snap = tracemalloc.take_snapshot()
    base = _pprof_growth_baseline[0]
    _pprof_growth_baseline[0] = snap
    if base is None:
        return 200, "baseline captured; re-fetch for growth", "text/plain"
    diff = snap.compare_to(base, "traceback")
    grown = [d for d in diff if d.size_diff > 0][
        : int(msg.query.get("top", "200"))
    ]

    class _Stat:  # adapt StatisticDiff to the heap-text shape
        __slots__ = ("count", "size", "traceback")

        def __init__(self, d):
            self.count = max(1, d.count_diff)
            self.size = d.size_diff
            self.traceback = d.traceback

    return 200, _pprof_heap_text([_Stat(d) for d in grown]), "text/plain"


def pprof_symbol(server, msg):
    """GET → whether symbolization is available; POST with a +-joined
    hex address list → one "0xaddr\\tname" line per address (the pprof
    symbolization handshake, pprof_service.h GetSymbol)."""
    if msg.method != "POST" or not len(msg.body):
        with _pprof_sym_lock:
            n = max(1, len(_pprof_sym_by_name))
        return 200, f"num_symbols: {n}\n", "text/plain"
    out = []
    body = msg.body.to_bytes().decode("latin1")
    for tok in body.replace("\n", "+").split("+"):
        tok = tok.strip()
        if not tok:
            continue
        try:
            addr = int(tok, 16)
        except ValueError:
            continue
        with _pprof_sym_lock:
            name = _pprof_name_by_addr.get(addr, "unknown")
        out.append(f"{tok}\t{name}")
    return 200, "\n".join(out) + "\n", "text/plain"


def pprof_cmdline(server, msg):
    """Process command line (pprof uses it to label the binary)."""
    try:
        with open("/proc/self/cmdline", "rb") as f:
            raw = f.read()
        return 200, raw.replace(b"\0", b"\n").decode(
            "utf-8", "replace"
        ), "text/plain"
    except OSError:
        import sys as _sys

        return 200, "\n".join(_sys.argv), "text/plain"


def _proto_label(f):
    from google.protobuf.descriptor import FieldDescriptor as FD

    if f.is_repeated:
        return "map" if (
            f.type == FD.TYPE_MESSAGE and f.message_type.GetOptions().map_entry
        ) else "repeated"
    return "optional" if f.has_presence else ""


def _proto_type_name(f):
    from google.protobuf.descriptor import FieldDescriptor as FD

    names = {
        FD.TYPE_DOUBLE: "double", FD.TYPE_FLOAT: "float",
        FD.TYPE_INT64: "int64", FD.TYPE_UINT64: "uint64",
        FD.TYPE_INT32: "int32", FD.TYPE_FIXED64: "fixed64",
        FD.TYPE_FIXED32: "fixed32", FD.TYPE_BOOL: "bool",
        FD.TYPE_STRING: "string", FD.TYPE_BYTES: "bytes",
        FD.TYPE_UINT32: "uint32", FD.TYPE_SFIXED32: "sfixed32",
        FD.TYPE_SFIXED64: "sfixed64", FD.TYPE_SINT32: "sint32",
        FD.TYPE_SINT64: "sint64",
    }
    if f.type == FD.TYPE_MESSAGE:
        if f.message_type.GetOptions().map_entry:
            kf = f.message_type.fields_by_name["key"]
            vf = f.message_type.fields_by_name["value"]
            return f"<{_proto_type_name(kf)}, {_proto_type_name(vf)}>"
        return f.message_type.full_name
    if f.type == FD.TYPE_ENUM:
        return f.enum_type.full_name
    return names.get(f.type, f"type{f.type}")


def _describe_descriptor(d) -> str:
    """Render one message descriptor as proto-style text (the reference
    /protobufs shows DebugString of the descriptor,
    builtin/protobufs_service.cpp)."""
    lines = [f"message {d.full_name} {{"]
    for f in d.fields:
        label = _proto_label(f)
        ty = _proto_type_name(f)
        decl = (
            f"  map{ty} {f.name} = {f.number};"
            if label == "map"
            else f"  {label + ' ' if label else ''}{ty} {f.name} = {f.number};"
        )
        lines.append(decl)
    for e in d.enum_types:
        lines.append(f"  enum {e.name} {{")
        for v in e.values:
            lines.append(f"    {v.name} = {v.number};")
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines)


def protobufs_page(server, msg):
    """Message schemas of every registered method (reference
    /protobufs, builtin/protobufs_service.cpp: lists message types,
    ?name shows one DebugString).  Nested field message/enum types are
    indexed transitively, so every full name the schema output mentions
    resolves."""
    from google.protobuf.descriptor import FieldDescriptor as FD

    descriptors = {}
    enums = {}

    def visit(d):
        if d.full_name in descriptors:
            return
        descriptors[d.full_name] = d
        for f in d.fields:
            if f.type == FD.TYPE_MESSAGE:
                if f.message_type.GetOptions().map_entry:
                    # the synthetic entry type stays hidden, but its
                    # VALUE type is printed in schemas — index it
                    vf = f.message_type.fields_by_name["value"]
                    if vf.type == FD.TYPE_MESSAGE:
                        visit(vf.message_type)
                    elif vf.type == FD.TYPE_ENUM:
                        enums[vf.enum_type.full_name] = vf.enum_type
                else:
                    visit(f.message_type)
            elif f.type == FD.TYPE_ENUM:
                enums[f.enum_type.full_name] = f.enum_type

    for full, spec in sorted(server.methods().items()):
        for cls in (spec.request_class, spec.response_class):
            if cls is not None and hasattr(cls, "DESCRIPTOR"):
                visit(cls.DESCRIPTOR)
    want = msg.query.get("name", msg.query.get("msg"))
    if want:
        d = descriptors.get(want)
        if d is not None:
            return 200, _describe_descriptor(d), "text/plain"
        e = enums.get(want)
        if e is not None:
            lines = [f"enum {e.full_name} {{"]
            lines += [f"  {v.name} = {v.number};" for v in e.values]
            lines.append("}")
            return 200, "\n".join(lines), "text/plain"
        return 404, f"unknown message {want!r}", "text/plain"
    out = ["registered protobuf messages (?name=Full.Name for schema):", ""]
    out += list(descriptors)
    out += list(enums)
    return 200, "\n".join(out), "text/plain"


def dir_page(server, msg):
    """Filesystem browser (reference /dir, builtin/dir_service.cpp).
    Gated behind the ``enable_dir_service`` flag exactly like the
    reference's -enable_dir_service (default OFF): arbitrary
    filesystem reads must be an explicit operator decision, toggleable
    at runtime via /flags?setvalue."""
    import os
    import stat as _stat

    from incubator_brpc_tpu.utils.flags import get_flag

    if not get_flag("enable_dir_service", False):
        return (
            403,
            "/dir is disabled; enable with the enable_dir_service flag "
            "(reference -enable_dir_service, likewise default off)",
            "text/plain",
        )
    path = msg.query.get("path", ".") or "/"
    try:
        st = os.stat(path)
        if _stat.S_ISDIR(st.st_mode):
            rows = []
            for name in sorted(os.listdir(path)):
                full = os.path.join(path, name)
                try:
                    s = os.stat(full)
                    kind = "d" if _stat.S_ISDIR(s.st_mode) else "-"
                    rows.append(f"{kind} {s.st_size:>12} {name}")
                except OSError:
                    rows.append(f"? {'?':>12} {name}")
            return (
                200,
                f"--- {os.path.abspath(path)} ---\n" + "\n".join(rows),
                "text/plain",
            )
        size = st.st_size
        if size > (8 << 20):
            return 403, f"{path}: {size} bytes (over the 8MB cap)", "text/plain"
        with open(path, "rb") as f:
            body = f.read()
        return 200, body, "application/octet-stream"
    except OSError as e:
        return 404, f"{path}: {e}", "text/plain"


def chaos_page(server, msg):
    """Fault-injection control + visibility (chaos/injector.py).

    GET             → JSON: armed flag, active plan, per-site hit
                      counts (native engine sites harvested into
                      chaos_injected_total as a side effect — the
                      /metrics family and this page agree)
    GET ?disarm=1   → disarm the active plan
    POST <plan json>→ arm the posted FaultPlan (replaces any armed one)
    """
    from incubator_brpc_tpu.chaos import injector
    from incubator_brpc_tpu.chaos.plan import FaultPlan

    if msg.method == "POST":
        # POST wins over a stray ?disarm= in the URL: silently
        # discarding a posted plan would leave the caller believing
        # chaos is armed while nothing injects
        body = msg.body.to_bytes() if len(msg.body) else b""
        if not body:
            return 400, "POST expects a FaultPlan JSON body", "text/plain"
        try:
            plan = FaultPlan.from_json(body.decode("utf-8"))
            injector.arm(plan)
        except Exception as e:  # noqa: BLE001
            return 400, f"bad fault plan: {e}", "text/plain"
        return (
            200,
            json.dumps({"armed": True, "plan": plan.to_dict()}),
            "application/json",
        )
    if msg.query.get("disarm") not in (None, "", "0", "false"):
        injector.disarm()
        return 200, json.dumps({"armed": False}), "application/json"
    return 200, json.dumps(injector.describe(), indent=1), "application/json"


def batching_page(server, msg):
    """Micro-batching control + visibility (batching/, docs/batching.md).

    GET  → JSON per batched method: policy, live occupancy / queue
           depth, batches/rows/shed counters, service-time EMA.
    POST → tune one method's max_wait_us at runtime:
           /batching?method=Svc.Method&max_wait_us=N (or the same keys
           as a JSON body).  The latency/throughput dial, reloadable
           like /flags.
    """
    batchers = server._batchers
    if msg.method == "POST":
        params = {k: v for k, v in msg.query.items()}
        body = msg.body.to_bytes() if len(msg.body) else b""
        if body:
            try:
                parsed = json.loads(body.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                parsed = None
            if not isinstance(parsed, dict):
                return 400, "POST body must be a JSON object", "text/plain"
            params.update(parsed)
        name = params.get("method")
        if not name:
            return 400, "missing method=Svc.Method", "text/plain"
        batcher = batchers.get(name)
        if batcher is None:
            return (
                404,
                f"no live batcher for {name!r} (batched methods: "
                f"{sorted(batchers)})",
                "text/plain",
            )
        wait = params.get("max_wait_us")
        if wait is None:
            return 400, "missing max_wait_us=N", "text/plain"
        try:
            wait = int(wait)
            if wait < 0:
                raise ValueError
        except (TypeError, ValueError):
            return 400, f"bad max_wait_us {wait!r}", "text/plain"
        batcher.set_max_wait_us(wait)
        return (
            200,
            json.dumps({"method": name, "max_wait_us": wait}),
            "application/json",
        )
    out = {
        "enabled": bool(batchers),
        "methods": {
            name: batcher.describe()
            for name, batcher in sorted(batchers.items())
        },
    }
    return 200, json.dumps(out, indent=1), "application/json"


def admission_page(server, msg):
    """Multi-tenant admission control + visibility (server/admission.py,
    docs/overload.md).

    GET  → JSON: tiers (priority/weight/share/quota/inflight/queue
           depth), tenant mappings + quotas + inflight, per-method
           tier overrides, cumulative shed counts, the code mapping.
    POST → live-tune, JSON body (or query params):
             {"tier": "bulk", "weight": 4, "quota": 0}
             {"tenant": "batch-ingest", "set_tier": "bulk", "quota": 8}
             {"method": "PsService.Put", "set_tier": "bulk"}
           Weights re-derive every tier's capacity share immediately —
           the shed dial, reloadable like /flags and /batching.
    """
    adm = server.admission
    if msg.method == "POST":
        params = {k: v for k, v in msg.query.items()}
        body = msg.body.to_bytes() if len(msg.body) else b""
        if body:
            try:
                parsed = json.loads(body.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                parsed = None
            if not isinstance(parsed, dict):
                return 400, "POST body must be a JSON object", "text/plain"
            params.update(parsed)
        try:
            if "tier" in params:
                adm.policy.set_tier(
                    str(params["tier"]),
                    weight=(
                        float(params["weight"])
                        if "weight" in params else None
                    ),
                    quota=(
                        int(params["quota"]) if "quota" in params else None
                    ),
                    priority=(
                        int(params["priority"])
                        if "priority" in params else None
                    ),
                )
            elif "tenant" in params:
                adm.policy.set_tenant(
                    str(params["tenant"]),
                    tier=params.get("set_tier"),
                    quota=(
                        int(params["quota"]) if "quota" in params else None
                    ),
                )
            elif "method" in params:
                if "set_tier" not in params:
                    return 400, "method tuning needs set_tier=", "text/plain"
                adm.policy.set_method_tier(
                    str(params["method"]), str(params["set_tier"])
                )
            else:
                return (
                    400,
                    "POST tunes one of tier= / tenant= / method= "
                    "(see docs/overload.md)",
                    "text/plain",
                )
        except (TypeError, ValueError) as e:
            return 400, f"bad admission tuning: {e}", "text/plain"
        return 200, json.dumps(adm.describe(), indent=1), "application/json"
    return 200, json.dumps(adm.describe(), indent=1), "application/json"


def cache_page(server, msg):
    """HBM cache tier visibility (cache/store.py, docs/cache.md):
    store occupancy vs budget, hit/miss/eviction counters, and which
    protocol fronts (redis/memcache) share it.  Finds the store behind
    whichever service option carries one."""
    stores = {}
    opts = server.options
    for front in ("redis_service", "memcache_service"):
        svc = getattr(opts, front, None)
        store = getattr(svc, "store", None)
        if store is not None and hasattr(store, "stats"):
            stores.setdefault(id(store), {"store": store, "fronts": []})[
                "fronts"
            ].append(front.replace("_service", ""))
    if not stores:
        return (
            200,
            json.dumps({"enabled": False, "reason": "no cache-tier service"}),
            "application/json",
        )
    out = []
    for ent in stores.values():
        d = ent["store"].stats()
        d["fronts"] = ent["fronts"]
        out.append(d)
    return 200, json.dumps({"enabled": True, "stores": out}, indent=1), "application/json"


def resharding_page(server, msg):
    """Live scheme-migration visibility (resharding/migration.py,
    docs/resharding.md): every registered migration's per-replica
    state — phase, routing epoch, scheme pair, and the step-log
    counters (keys moved/copied/drained, checksum failures, survivor
    completions, rollbacks) the zero-downtime proof reads.
    ``?name=<migration>`` filters to one migration."""
    from incubator_brpc_tpu.resharding.migration import states_snapshot

    states = states_snapshot()
    name = msg.query.get("name")
    if name is not None:
        st = states.get(name)
        if st is None:
            return (
                404,
                json.dumps({"error": f"no migration named {name!r}"}),
                "application/json",
            )
        return 200, json.dumps(st, indent=1), "application/json"
    return (
        200,
        json.dumps({"migrations": states}, indent=1),
        "application/json",
    )


def serving_page(server, msg):
    """Disaggregated-serving visibility (serving/, docs/serving.md):
    every registered session's state machine position, ownership
    epoch, KV residency (kv_epoch/n_layers/kv_bytes), token progress,
    the per-session migration log (the exactly-once audit trail) and
    the ``rpc_serving_*`` counters.  ``?session=<id>`` filters to one
    session."""
    import sys

    sess_mod = sys.modules.get("incubator_brpc_tpu.serving.session")
    sessions = sess_mod.sessions_snapshot() if sess_mod is not None else {}
    sid = msg.query.get("session")
    if sid is not None:
        d = sessions.get(sid)
        if d is None:
            return (
                404,
                json.dumps({"error": f"no session named {sid!r}"}),
                "application/json",
            )
        return 200, json.dumps(d, indent=1), "application/json"
    metrics_mod = sys.modules.get("incubator_brpc_tpu.serving.metrics")
    return (
        200,
        json.dumps(
            {
                "enabled": bool(sessions),
                "sessions": sessions,
                "counters": (
                    metrics_mod.snapshot() if metrics_mod is not None else {}
                ),
            },
            indent=1,
        ),
        "application/json",
    )


def replication_page(server, msg):
    """Replicated HA tier visibility (replication/, docs/replication.md):
    every registered replica group's leader, lease epoch, remaining
    lease time, per-replica health (alive/repairing/applied_seq/
    epoch_floor) and the step-log counters (quorum writes/failures,
    fenced writes, leader changes, repair keys, hedged reads) the
    zero-acked-write-loss proof reads.  ``?name=<group>`` filters to
    one group."""
    from incubator_brpc_tpu.replication.group import groups_snapshot

    groups = groups_snapshot()
    name = msg.query.get("name")
    if name is not None:
        g = groups.get(name)
        if g is None:
            return (
                404,
                json.dumps({"error": f"no replica group named {name!r}"}),
                "application/json",
            )
        return 200, json.dumps(g, indent=1), "application/json"
    return (
        200,
        json.dumps({"groups": groups}, indent=1),
        "application/json",
    )


def vlog_page(server, msg):
    import logging as _pylog

    from incubator_brpc_tpu.utils.logging import set_min_log_level

    level = msg.query.get("v")
    if level is not None:
        set_min_log_level(_pylog.DEBUG if level not in ("0", "off") else _pylog.WARNING)
        return 200, f"verbose={level}", "text/plain"
    return 200, "toggle with /vlog?v=1 or /vlog?v=0", "text/plain"
