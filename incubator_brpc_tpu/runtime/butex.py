"""Butex — the single blocking primitive (reference bthread/butex.cpp).

A butex is a 32-bit-word futex for tasks: ``wait(expected)`` blocks the
caller only if the word still equals ``expected`` (the reference's
butex_wait contract, butex.h:36-60); wake/wake_all release waiters. All
higher-level sync (mutex, condition, CallId join, RPC join, stream flow
control) is built on it, exactly as in the reference.

Blocking here parks the OS thread; the scheduler is notified so it can
grow the worker pool (see scheduler.py docstring).
"""

from __future__ import annotations

import threading
from typing import Optional

from incubator_brpc_tpu.runtime import scheduler


class Butex:
    __slots__ = ("_value", "_cond")

    def __init__(self, value: int = 0):
        self._value = value
        self._cond = threading.Condition()

    @property
    def value(self) -> int:
        return self._value

    def set_value(self, v: int):
        with self._cond:
            self._value = v

    def fetch_add(self, delta: int) -> int:
        with self._cond:
            old = self._value
            self._value = (self._value + delta) & 0xFFFFFFFF
            return old

    def wait(self, expected: int, timeout: Optional[float] = None) -> bool:
        """Block while value == expected. Returns False on timeout or if
        the value already differed (EWOULDBLOCK in the reference)."""
        ctrl = scheduler.get_task_control() if scheduler.in_worker() else None
        with self._cond:
            if self._value != expected:
                return False
            if ctrl:
                ctrl.on_task_block()
            try:
                ok = self._cond.wait_for(lambda: self._value != expected, timeout)
            finally:
                if ctrl:
                    ctrl.on_task_unblock()
            return ok

    def wake(self, n: int = 1) -> None:
        with self._cond:
            if n == 1:
                self._cond.notify()
            else:
                self._cond.notify(n)

    def wake_all(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def set_and_wake(self, v: int, all: bool = True) -> None:
        with self._cond:
            self._value = v
            if all:
                self._cond.notify_all()
            else:
                self._cond.notify()
