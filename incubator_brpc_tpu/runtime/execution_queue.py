"""ExecutionQueue — MPSC queue with auto-started consumer task.

Analog of bthread::ExecutionQueue (execution_queue.h:30-35,159,183):
producers from any thread call ``execute``; a single consumer task is
started on demand on the runtime, drains items in batches through the
user callback, and quits when empty (auto-start/auto-quit). Ordered
processing without a dedicated thread. High-priority items jump the
queue (reference execute with TASK_OPTIONS_URGENT).
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Callable, Iterable, List, Optional

from incubator_brpc_tpu.runtime import scheduler

# consumer callback: fn(iterator_of_items) -> None; a stopped queue passes
# is_stopped=True via the `stopped` attr on the batch.


class TaskIterator:
    def __init__(self, items: List, stopped: bool):
        self._items = items
        self.stopped = stopped

    def __iter__(self):
        return iter(self._items)

    def __len__(self):
        return len(self._items)


class ExecutionQueue:
    def __init__(
        self,
        consumer: Callable[[TaskIterator], None],
        batch_max: int = 64,
        wait_recorder: Optional[Callable[[int], None]] = None,
    ):
        """``wait_recorder(wait_us)`` — optional queue-in/queue-out
        latency observer: each item's time between enqueue and the
        consumer batch picking it up is reported (feeds the _runtime
        rows of /latency_breakdown). A ``gate`` attribute on the
        recorder (a Flag-like object) suppresses even the enqueue-side
        clock read while ``gate.value`` is false."""
        self._consumer = consumer
        self._batch_max = batch_max
        self._wait_recorder = wait_recorder
        self._wait_gate = getattr(wait_recorder, "gate", None)
        self._q: deque = deque()  # entries: (item, enqueue_ns | 0)
        self._lock = threading.Lock()
        self._running = False
        self._stopped = False
        self._drained = threading.Condition(self._lock)

    def _entry(self, item):
        if self._wait_recorder is not None and (
            self._wait_gate is None or self._wait_gate.value
        ):
            return (item, _time.monotonic_ns())
        return (item, 0)

    def execute(self, item, urgent: bool = False) -> bool:
        """Enqueue; starts the consumer task if idle. Wait-free for
        producers in the reference; O(1) under a short lock here."""
        with self._lock:
            if self._stopped:
                return False
            if urgent:
                self._q.appendleft(self._entry(item))
            else:
                self._q.append(self._entry(item))
            if self._running:
                return True
            self._running = True
        scheduler.spawn(self._consume_loop)
        return True

    def execute_batch(self, items) -> bool:
        """Enqueue several items with ONE lock acquisition and at most
        ONE consumer wake — the batch-wake API the ICI fabric's
        delivery bursts use (a fan-out that delivers N frames pays one
        task spawn instead of N lock/wake rounds).  All-or-nothing: a
        stopped queue refuses the whole batch (False) so the caller can
        release per-item resources (window credits) in one place."""
        items = list(items)
        if not items:
            return True
        with self._lock:
            if self._stopped:
                return False
            self._q.extend(self._entry(i) for i in items)
            if self._running:
                return True
            self._running = True
        scheduler.spawn(self._consume_loop)
        return True

    def execute_or_inline(self, item) -> bool:
        """Run ``item`` inline in the calling task when the queue is
        idle and empty (ordering is trivially preserved — nothing is
        pending or mid-flight); otherwise enqueue as ``execute`` does.
        Saves the consumer-task handoff in the common one-outstanding-
        item case."""
        with self._lock:
            if self._stopped:
                return False
            if self._running or self._q:
                self._q.append(self._entry(item))
                return True
            self._running = True
        try:
            self._consumer(TaskIterator([item], stopped=False))
        except Exception as e:  # noqa: BLE001
            from incubator_brpc_tpu.utils.logging import log_error

            log_error("ExecutionQueue consumer raised: %r", e)
        # drain anything enqueued meanwhile; resets _running when empty
        self._consume_loop()
        return True

    def _consume_loop(self):
        while True:
            entries = None
            with self._lock:
                if not self._q:
                    self._running = False
                    self._drained.notify_all()
                    if self._stopped:
                        batch = TaskIterator([], stopped=True)
                    else:
                        return
                else:
                    entries = []
                    while self._q and len(entries) < self._batch_max:
                        entries.append(self._q.popleft())
                    items = [e[0] for e in entries]
                    batch = TaskIterator(items, stopped=False)
            if entries and self._wait_recorder is not None:
                # queue-out stamp: report each item's wait.  Outside the
                # queue lock — the recorder is a foreign observer with
                # its own locks (latency_breakdown); producers must not
                # contend with recorder work (callback-under-lock rule)
                now = _time.monotonic_ns()
                for _, t in entries:
                    if t:
                        try:
                            self._wait_recorder((now - t) // 1000)
                        except Exception:  # noqa: BLE001
                            pass
            try:
                self._consumer(batch)
            except Exception as e:  # noqa: BLE001
                from incubator_brpc_tpu.utils.logging import log_error

                log_error("ExecutionQueue consumer raised: %r", e)
            if batch.stopped:
                return

    def stop(self):
        """Analog of execution_queue_stop: flush then signal stopped."""
        with self._lock:
            self._stopped = True
            if not self._running:
                self._running = True
                start = True
            else:
                start = False
        if start:
            scheduler.spawn(self._consume_loop)

    def join(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            return self._drained.wait_for(
                lambda: not self._q and not self._running, timeout
            )

    def __len__(self):
        return len(self._q)
