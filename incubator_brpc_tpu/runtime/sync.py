"""Task-aware sync primitives built on Butex (reference bthread/mutex.cpp,
condition_variable.cpp, countdown_event.cpp).

The reference's bthread_mutex has contention-profiler hooks
(mutex.cpp:106-180) feeding the bvar Collector; TaskMutex mirrors that
by recording wait time into a metrics Adder when contended.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from incubator_brpc_tpu.runtime.butex import Butex


class TaskMutex:
    """Mutex with contention sampling (analog bthread_mutex_t)."""

    _contention_ns_total = 0  # exposed via metrics default_variables

    def __init__(self):
        self._butex = Butex(0)  # 0=unlocked, 1=locked, 2=locked+contended

    def acquire(self, timeout: Optional[float] = None) -> bool:
        with self._butex._cond:
            if self._butex._value == 0:
                self._butex._value = 1
                return True
        from incubator_brpc_tpu.runtime import scheduler

        ctrl = scheduler.get_task_control() if scheduler.in_worker() else None
        if ctrl:
            ctrl.on_task_block()
        start = time.monotonic_ns()
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while True:
                with self._butex._cond:
                    if self._butex._value == 0:
                        self._butex._value = 2
                        waited = time.monotonic_ns() - start
                        TaskMutex._contention_ns_total += waited
                    else:
                        waited = -1
                        remain = (
                            None if deadline is None else deadline - time.monotonic()
                        )
                        if remain is not None and remain <= 0:
                            return False
                        self._butex._cond.wait(remain if remain is not None else 0.1)
                if waited >= 0:
                    # contention profiler (reference mutex.cpp:106-180)
                    # — sampled OUTSIDE the cond lock: stack capture in
                    # the critical section would inflate the very
                    # contention being measured
                    from incubator_brpc_tpu.observability.contention import (
                        record_contention,
                    )

                    record_contention(waited)
                    return True
        finally:
            if ctrl:
                ctrl.on_task_unblock()

    def release(self):
        self._butex.set_and_wake(0, all=False)

    __enter__ = lambda self: self.acquire() and self or self
    def __exit__(self, *exc):
        self.release()


class CountdownEvent:
    """Analog of bthread::CountdownEvent."""

    def __init__(self, initial: int = 1):
        self._butex = Butex(initial)

    def signal(self, n: int = 1):
        with self._butex._cond:
            self._butex._value -= n
            if self._butex._value <= 0:
                self._butex._cond.notify_all()

    def add_count(self, n: int = 1):
        with self._butex._cond:
            self._butex._value += n

    def wait(self, timeout: Optional[float] = None) -> bool:
        from incubator_brpc_tpu.runtime import scheduler

        ctrl = scheduler.get_task_control() if scheduler.in_worker() else None
        with self._butex._cond:
            if self._butex._value <= 0:
                return True
            if ctrl:
                ctrl.on_task_block()
            try:
                return self._butex._cond.wait_for(
                    lambda: self._butex._value <= 0, timeout
                )
            finally:
                if ctrl:
                    ctrl.on_task_unblock()
