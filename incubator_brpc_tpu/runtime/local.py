"""Per-task local storage (analog of bthread keys/TLS, bthread/key.cpp).

Each spawned Task carries its own key→value dict (keytable in the
reference); code running outside the runtime falls back to thread-local
storage. Used by rpcz to carry the parent span (reference span.h:75-78
bthread::tls_bls) and by servers for thread-local user data.
"""

from __future__ import annotations

import threading

from incubator_brpc_tpu.runtime import scheduler

_thread_fallback = threading.local()


def _storage() -> dict:
    task = getattr(scheduler._tls, "current_task", None)
    if task is not None:
        if not hasattr(task, "locals"):
            task.locals = {}
        return task.locals
    d = getattr(_thread_fallback, "d", None)
    if d is None:
        d = _thread_fallback.d = {}
    return d


def get_local(key, default=None):
    return _storage().get(key, default)


def set_local(key, value):
    _storage()[key] = value


def del_local(key):
    _storage().pop(key, None)
