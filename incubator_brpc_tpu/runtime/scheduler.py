"""TaskControl / TaskGroup — work-stealing task scheduler.

Analog of bthread's TaskControl (task_control.h:41-116) and TaskGroup
(task_group.h:60-166): N workers, each with a private run deque; empty
workers steal from random victims (WorkStealingQueue, Chase–Lev in the
reference, work_stealing_queue.h:32-117) then park in a ParkingLot
(parking_lot.h:31).

Deviation from the reference, by design: bthreads context-switch in
user space so a blocked bthread costs nothing; Python tasks occupy
their worker thread while blocked. To preserve the invariant that a
blocked task never starves runnable tasks (the property the M:N design
exists for), workers notify the control on block/unblock and the
control spawns replacement workers up to a cap — an adaptive pool
instead of stack-switching.
"""

from __future__ import annotations

import os
import random
import threading
import time as _time
from collections import deque
from typing import Callable, Optional

from incubator_brpc_tpu.utils.logging import log_error

# queue-out observer: callable(wait_us) fed each task's spawn→run delay
# (observability/latency_breakdown registers itself here; kept as a
# hook so this low-level module never imports the metrics stack). The
# optional gate is a Flag-like object — observation (including the
# per-task clock reads) only happens while gate.value is truthy, so a
# server with rpcz disabled pays nothing per spawn.
_task_queue_observer: Optional[Callable[[int], None]] = None
_task_queue_gate = None

# chaos hook slot (same pattern as the queue observer): chaos.injector
# fills it while an armed plan targets "scheduler.callback"; disarmed
# cost is one `is None` check per task run.
_chaos_hook: Optional[Callable[[], None]] = None

# occupancy observer: second queue-out slot with its own gate, filled by
# observability/profiling (the runtime occupancy sampler) — separate
# from the rpcz-gated latency_breakdown observer so either can be on
# while the other is off.  Same contract: callable(wait_us).
_occupancy_observer: Optional[Callable[[int], None]] = None
_occupancy_gate = None


def set_chaos_hook(cb: Optional[Callable[[], None]]) -> None:
    global _chaos_hook
    _chaos_hook = cb


def set_task_queue_observer(
    cb: Optional[Callable[[int], None]], gate=None
) -> None:
    global _task_queue_observer, _task_queue_gate
    _task_queue_observer = cb
    _task_queue_gate = gate


def set_occupancy_observer(
    cb: Optional[Callable[[int], None]], gate=None
) -> None:
    global _occupancy_observer, _occupancy_gate
    _occupancy_observer = cb
    _occupancy_gate = gate


def _gate_open(gate) -> bool:
    return gate is None or bool(gate.value)


def _observing() -> bool:
    if _task_queue_observer is not None and _gate_open(_task_queue_gate):
        return True
    return _occupancy_observer is not None and _gate_open(_occupancy_gate)


class Task:
    """Handle for a spawned task (stands in for a bthread tid)."""

    __slots__ = ("fn", "args", "_done", "result", "exc", "locals", "queued_ns")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self._done = threading.Event()
        self.result = None
        self.exc = None
        # queue-in stamp, read back at run() for the queue-out delta;
        # clock read only while observation is on (observer + gate)
        self.queued_ns = _time.monotonic_ns() if _observing() else 0

    def run(self):
        if _chaos_hook is not None:
            try:
                _chaos_hook()  # injected callback delay
            except Exception:  # noqa: BLE001 — chaos must not kill workers
                pass
        if self.queued_ns:
            wait_us = (_time.monotonic_ns() - self.queued_ns) // 1000
            obs = _task_queue_observer
            if obs is not None and _gate_open(_task_queue_gate):
                try:
                    obs(wait_us)
                except Exception:  # noqa: BLE001
                    pass
            occ = _occupancy_observer
            if occ is not None and _gate_open(_occupancy_gate):
                try:
                    occ(wait_us)
                except Exception:  # noqa: BLE001
                    pass
        prev = getattr(_tls, "current_task", None)
        _tls.current_task = self
        try:
            self.result = self.fn(*self.args)
        except BaseException as e:  # noqa: BLE001 — task crash must not kill worker
            self.exc = e
            log_error("task %r raised: %r", self.fn, e)
        finally:
            _tls.current_task = prev
            self._done.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Analog of bthread_join."""
        return self._done.wait(timeout)

    def done(self) -> bool:
        return self._done.is_set()


class ParkingLot:
    """Futex-based sleep/wakeup for idle workers (parking_lot.h:31)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._signal = 0

    def signal(self, n: int = 1):
        with self._cond:
            self._signal += n
            if n == 1:
                self._cond.notify()
            else:
                self._cond.notify_all()

    def wait(self, timeout: float = 1.0) -> bool:
        with self._cond:
            if self._signal > 0:
                self._signal -= 1
                return True
            if self._cond.wait(timeout):
                if self._signal > 0:
                    self._signal -= 1
                return True
            return False


class TaskGroup:
    """Per-worker scheduler state (task_group.h): private deque + steal."""

    __slots__ = ("control", "rq", "lock", "worker_id", "steals", "runs")

    def __init__(self, control: "TaskControl", worker_id: int):
        self.control = control
        self.worker_id = worker_id
        self.rq: deque = deque()
        self.lock = threading.Lock()
        # plain ints, bumped GIL-atomically by this group's own worker —
        # the occupancy sampler (observability/profiling) reads them;
        # this module stays metrics-free
        self.steals = 0  # tasks this worker stole from a victim
        self.runs = 0  # tasks this worker executed

    def push(self, task: Task, urgent: bool = False):
        with self.lock:
            if urgent:
                self.rq.appendleft(task)  # bthread_start_urgent: run next
            else:
                self.rq.append(task)

    def pop(self) -> Optional[Task]:
        with self.lock:
            return self.rq.popleft() if self.rq else None

    def steal(self) -> Optional[Task]:
        with self.lock:
            return self.rq.pop() if self.rq else None  # steal from the tail


_tls = threading.local()


class TaskControl:
    """Owns worker threads and global scheduling (task_control.h:41)."""

    def __init__(self, concurrency: Optional[int] = None, max_workers: int = 256):
        self.concurrency = concurrency or max(4, (os.cpu_count() or 4))
        self.max_workers = max_workers
        self._groups: list[TaskGroup] = []
        self._remote_q: deque = deque()  # spawns from non-worker threads
        self._remote_lock = threading.Lock()
        self._lot = ParkingLot()
        self._lock = threading.Lock()
        self._stopped = False
        self._nworkers = 0
        self._nblocked = 0
        self._nparked = 0
        self._parks_total = 0  # cumulative park events (occupancy sampler)
        for _ in range(self.concurrency):
            self._add_worker()

    # ---- spawning ----------------------------------------------------------
    def spawn(self, fn: Callable, *args, urgent: bool = False) -> Task:
        """Analog of bthread_start_background/urgent."""
        task = Task(fn, args)
        group = getattr(_tls, "group", None)
        if group is not None and group.control is self:
            group.push(task, urgent)
        else:
            with self._remote_lock:
                self._remote_q.append(task)
        self._lot.signal(1)
        self._maybe_grow()
        return task

    def _maybe_grow(self):
        # If every worker is occupied by a *blocked* task, runnable work
        # would starve — grow the pool (replacement for bthread context
        # switch). Parked workers are idle capacity, not a reason to grow.
        if self._nblocked >= self._nworkers and self._nworkers < self.max_workers:
            with self._lock:
                if self._nworkers < self.max_workers and not self._stopped:
                    self._add_worker_locked()

    def _add_worker(self):
        with self._lock:
            self._add_worker_locked()

    def _add_worker_locked(self):
        wid = self._nworkers
        self._nworkers += 1
        group = TaskGroup(self, wid)
        self._groups.append(group)
        t = threading.Thread(
            target=self._worker_main, args=(group,), daemon=True, name=f"tpubrpc-w{wid}"
        )
        t.start()

    # ---- worker loop (run_main_task, task_group.cpp:145) -------------------
    def _worker_main(self, group: TaskGroup):
        _tls.group = group
        while not self._stopped:
            task = self._wait_task(group)
            if task is not None:
                group.runs += 1
                task.run()

    def _wait_task(self, group: TaskGroup) -> Optional[Task]:
        """Analog of TaskGroup::wait_task (task_group.cpp:118)."""
        task = group.pop()
        if task is not None:
            return task
        with self._remote_lock:
            if self._remote_q:
                return self._remote_q.popleft()
        task = self._steal_task(group)
        if task is not None:
            group.steals += 1
            return task
        self._nparked += 1
        self._parks_total += 1
        try:
            self._lot.wait(timeout=0.1)
        finally:
            self._nparked -= 1
        return None

    def _steal_task(self, group: TaskGroup) -> Optional[Task]:
        groups = self._groups
        n = len(groups)
        if n <= 1:
            return None
        start = random.randrange(n)
        for i in range(n):
            victim = groups[(start + i) % n]
            if victim is group:
                continue
            task = victim.steal()
            if task is not None:
                return task
        return None

    # ---- blocking integration (butex calls these) --------------------------
    def on_task_block(self):
        self._nblocked += 1
        self._maybe_grow()

    def on_task_unblock(self):
        self._nblocked -= 1

    def stop(self):
        self._stopped = True
        self._lot.signal(self.max_workers)

    # ---- introspection ------------------------------------------------------
    def worker_count(self) -> int:
        return self._nworkers

    def blocked_count(self) -> int:
        return self._nblocked

    def parked_count(self) -> int:
        return self._nparked

    def parks_total(self) -> int:
        return self._parks_total

    def steals_total(self) -> int:
        return sum(g.steals for g in self._groups)

    def runqueue_depth(self) -> int:
        return sum(len(g.rq) for g in self._groups) + len(self._remote_q)

    def occupancy_snapshot(self) -> dict:
        """Point-in-time occupancy state for /hotspots/runtime: totals
        plus one row per worker (run-queue depth, steals, runs).  len()
        on a deque is GIL-atomic, so no victim locks are taken."""
        workers = [
            {
                "worker_id": g.worker_id,
                "rq_depth": len(g.rq),
                "steals": g.steals,
                "runs": g.runs,
            }
            for g in list(self._groups)
        ]
        return {
            "workers": self._nworkers,
            "blocked": self._nblocked,
            "parked": self._nparked,
            "parks_total": self._parks_total,
            "steals_total": sum(w["steals"] for w in workers),
            "remote_q": len(self._remote_q),
            "per_worker": workers,
        }


_default_control: Optional[TaskControl] = None
_default_lock = threading.Lock()


def get_task_control() -> TaskControl:
    global _default_control
    if _default_control is None:
        with _default_lock:
            if _default_control is None:
                _default_control = TaskControl()
    return _default_control


def spawn(fn: Callable, *args) -> Task:
    return get_task_control().spawn(fn, *args)


def spawn_urgent(fn: Callable, *args) -> Task:
    return get_task_control().spawn(fn, *args, urgent=True)


def in_worker() -> bool:
    return getattr(_tls, "group", None) is not None
