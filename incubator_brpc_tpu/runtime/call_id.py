"""CallId — versioned correlation ids with lock/error/destroy semantics.

Analog of bthread_id (reference bthread/id.{h,cpp}, id.h:31-53; doc
docs/cn/bthread_id.md). This is the RPC correlation-id + cancellation +
retry-versioning mechanism: nearly every client-side correctness
property (stale responses of dead retries being dropped, cancellation,
sync Join) rests on it (SURVEY.md §7 "hard parts").

Id layout (fits the wire's int64, like the reference's 64-bit id):
    cid = (generation << 32) | (version << 20) | slot
- ``slot`` (20 bits): index into the slab pool.
- ``generation`` (31 bits): bumped on destroy; a recycled slot's old
  ids never resolve (ABA safety, reference version ranges).
- ``version`` (12 bits): the retry version within one RPC. Each retry
  mints a new version via ``bump_version``; a response carrying a
  superseded version fails ``lock`` and is dropped (reference: "drops
  stale versions = dead retries", baidu_rpc_protocol.cpp:571).
  version 0 is the *wildcard*: it locks/errors whatever version is
  current — used by the overall-deadline timer and join, which apply to
  the RPC as a whole, not to one attempt (reference arms its timer with
  the base id for the same reason).

Semantics (mirroring id.cpp):
- ``lock`` is a mutex: contenders block until unlocked.
- ``error`` runs the id's on_error handler *under the id lock*; if the
  id is currently locked, the error is queued and the handler runs at
  unlock time (reference PendingError list).
- ``unlock_and_destroy`` invalidates all versions and wakes joiners.
- ``join`` blocks until the id is destroyed, across retries
  (sync RPC waits here, channel.cpp:581).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

INVALID_CALL_ID = 0

_SLOT_BITS = 20
_VER_BITS = 12
_SLOT_MASK = (1 << _SLOT_BITS) - 1
_VER_MASK = (1 << _VER_BITS) - 1
_GEN_MASK = (1 << 31) - 1

# on_error(data, cid, error_code, error_text) — must unlock or destroy cid.
OnError = Callable[[object, int, int, str], None]


def _pack(slot_idx: int, gen: int, ver: int) -> int:
    return ((gen & _GEN_MASK) << 32) | ((ver & _VER_MASK) << _SLOT_BITS) | slot_idx


def _unpack(cid: int) -> Tuple[int, int, int]:
    return (
        cid & _SLOT_MASK,
        (cid >> 32) & _GEN_MASK,
        (cid >> _SLOT_BITS) & _VER_MASK,
    )


def wildcard(cid: int) -> int:
    """Version-agnostic form of cid (matches whatever version is current)."""
    return cid & ~(_VER_MASK << _SLOT_BITS)


def wire_cid32(cid: int) -> int:
    """32-bit wire form for protocols whose correlation field is only
    32 bits (thrift seqid, nshead log_id). The low 32 bits of a cid are
    (version, slot) — REUSED verbatim when a slot is recycled, so a
    late response could match a newer RPC on the same slot. The slot
    generation is folded in through a multiplicative hash: a plain XOR
    collides easily for small gen/slot values (genA^genB == slotA^slotB
    happens constantly with concurrent in-flight RPCs), while the
    golden-ratio spread makes any gen difference look random across
    all 32 bits."""
    return (cid ^ ((cid >> 32) * 0x9E3779B1)) & 0xFFFFFFFF


class _IdSlot:
    __slots__ = ("gen", "cur_ver", "alive", "data", "on_error", "locked", "pending", "cond")

    def __init__(self):
        self.gen = 1
        self.cur_ver = 1
        self.alive = False
        self.data = None
        self.on_error: Optional[OnError] = None
        self.locked = False
        self.pending: List[Tuple[int, str]] = []
        self.cond = threading.Condition()


class CallIdPool:
    def __init__(self):
        self._slots: List[_IdSlot] = []
        self._free: List[int] = []
        self._lock = threading.Lock()

    # ---- lifecycle ---------------------------------------------------------
    def create(self, data=None, on_error: Optional[OnError] = None) -> int:
        with self._lock:
            if self._free:
                idx = self._free.pop()
                slot = self._slots[idx]
            else:
                idx = len(self._slots)
                if idx > _SLOT_MASK:
                    raise RuntimeError("CallId slot space exhausted")
                slot = _IdSlot()
                self._slots.append(slot)
        with slot.cond:
            slot.alive = True
            slot.cur_ver = 1
            slot.data = data
            slot.on_error = on_error
            slot.locked = False
            slot.pending.clear()
            return _pack(idx, slot.gen, 1)

    def _slot_of(self, cid: int) -> Optional[_IdSlot]:
        idx = cid & _SLOT_MASK
        if idx >= len(self._slots):
            return None
        return self._slots[idx]

    @staticmethod
    def _valid(slot: _IdSlot, cid: int) -> bool:
        """Valid for lock/error: alive, same generation, current (or
        wildcard) version."""
        _, gen, ver = _unpack(cid)
        return (
            slot.alive
            and slot.gen == gen
            and (ver == 0 or ver == slot.cur_ver)
        )

    @staticmethod
    def _same_rpc(slot: _IdSlot, cid: int) -> bool:
        """Valid for join: alive and same generation (any version)."""
        _, gen, _ = _unpack(cid)
        return slot.alive and slot.gen == gen

    #: sentinel returned by try_lock when the id exists but is locked
    BUSY = object()

    def try_lock(self, cid: int):
        """Non-blocking lock. Returns the data on success, None if this
        version is gone (stale-response drop), or CallIdPool.BUSY if the
        id is currently locked by someone else — callers that must not
        block (the event-dispatcher thread) re-dispatch on BUSY."""
        slot = self._slot_of(cid)
        if slot is None:
            return None
        with slot.cond:
            if not self._valid(slot, cid):
                return None
            if slot.locked:
                return CallIdPool.BUSY
            slot.locked = True
            return slot.data

    # ---- lock / unlock -----------------------------------------------------
    def lock(self, cid: int, timeout: Optional[float] = None):
        """Lock the id. Returns the data on success, None if this version
        of the id no longer exists — the stale-response drop."""
        slot = self._slot_of(cid)
        if slot is None:
            return None
        with slot.cond:
            while self._valid(slot, cid) and slot.locked:
                if not slot.cond.wait(timeout):
                    return None
            if not self._valid(slot, cid):
                return None
            slot.locked = True
            return slot.data

    def unlock(self, cid: int) -> bool:
        slot = self._slot_of(cid)
        if slot is None:
            return False
        run_error = None
        with slot.cond:
            if not slot.locked or not self._valid(slot, cid):
                return False
            if slot.pending:
                run_error = slot.pending.pop(0)  # stay locked; handler owns it
            else:
                slot.locked = False
                slot.cond.notify_all()
        if run_error is not None:
            code, text = run_error
            self._run_on_error(slot, _pack(cid & _SLOT_MASK, slot.gen, slot.cur_ver), code, text)
        return True

    def unlock_and_destroy(self, cid: int) -> bool:
        slot = self._slot_of(cid)
        if slot is None:
            return False
        idx = cid & _SLOT_MASK
        with slot.cond:
            if not self._same_rpc(slot, cid):
                return False
            slot.alive = False
            slot.gen = (slot.gen + 1) & _GEN_MASK or 1
            slot.locked = False
            slot.data = None
            slot.on_error = None
            slot.pending.clear()
            slot.cond.notify_all()
        with self._lock:
            self._free.append(idx)
        return True

    def bump_version(self, cid: int) -> int:
        """Mint the next retry version, invalidating previously-sent wire
        ids. Caller must hold the lock; returns the new current cid."""
        slot = self._slot_of(cid)
        assert slot is not None and slot.locked, "bump_version requires the lock"
        with slot.cond:
            slot.cur_ver += 1
            if slot.cur_ver > _VER_MASK:
                raise RuntimeError("too many retries for one CallId")
            return _pack(cid & _SLOT_MASK, slot.gen, slot.cur_ver)

    # ---- error & join ------------------------------------------------------
    def error(self, cid: int, error_code: int, error_text: str = "") -> bool:
        """Deliver an error to the id (reference bthread_id_error)."""
        slot = self._slot_of(cid)
        if slot is None:
            return False
        with slot.cond:
            if not self._valid(slot, cid):
                return False
            if slot.locked:
                slot.pending.append((error_code, error_text))
                return True
            slot.locked = True
            current = _pack(cid & _SLOT_MASK, slot.gen, slot.cur_ver)
        self._run_on_error(slot, current, error_code, error_text)
        return True

    def _run_on_error(self, slot: _IdSlot, cid: int, code: int, text: str):
        handler = slot.on_error
        data = slot.data
        if handler is None:
            # default: destroy so joiners wake (reference default handler)
            self.unlock_and_destroy(cid)
            return
        handler(data, cid, code, text)  # handler must unlock/destroy

    def join(self, cid: int, timeout: Optional[float] = None) -> bool:
        """Block until the id is destroyed (bthread_id_join), surviving
        retry version bumps."""
        slot = self._slot_of(cid)
        if slot is None:
            return True
        from incubator_brpc_tpu.runtime import scheduler

        ctrl = scheduler.get_task_control() if scheduler.in_worker() else None
        with slot.cond:
            if not self._same_rpc(slot, cid):
                return True
            if ctrl:
                ctrl.on_task_block()
            try:
                return slot.cond.wait_for(lambda: not self._same_rpc(slot, cid), timeout)
            finally:
                if ctrl:
                    ctrl.on_task_unblock()


_default_pool = CallIdPool()


def default_pool() -> CallIdPool:
    return _default_pool
