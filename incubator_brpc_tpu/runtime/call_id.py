"""CallId — versioned correlation ids with lock/error/destroy semantics.

Analog of bthread_id (reference bthread/id.{h,cpp}, id.h:31-53; doc
docs/cn/bthread_id.md). This is the RPC correlation-id + cancellation +
retry-versioning mechanism: nearly every client-side correctness
property (stale responses of dead retries being dropped, cancellation,
sync Join) rests on it (SURVEY.md §7 "hard parts").

Semantics implemented (mirroring id.cpp):
- An id names a slot + exact version. ``lock`` succeeds only for the
  slot's *current* version — a response carrying the id of a superseded
  retry fails to lock and is dropped (reference: "drops stale versions
  = dead retries", baidu_rpc_protocol.cpp:571).
- ``lock`` is a mutex: contenders block until unlocked (the reference
  queues them on the id's butex).
- ``error`` delivers an error to the id's on_error handler *under the
  id lock*; if the id is currently locked, the error is queued and the
  handler runs at unlock time (reference PendingError list).
- ``unlock_and_destroy`` invalidates all versions and wakes joiners.
- ``join`` blocks until the id is destroyed (sync RPC waits here,
  channel.cpp:581).
- ``bump_version`` (reference bthread_id_lock_and_reset_range flavor)
  invalidates wire ids minted for previous attempts; caller must hold
  the lock.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

INVALID_CALL_ID = 0

# on_error(data, cid, error_code, error_text) — must unlock or destroy cid.
OnError = Callable[[object, int, int, str], None]


class _IdSlot:
    __slots__ = (
        "version",
        "alive",
        "data",
        "on_error",
        "locked",
        "pending",
        "cond",
    )

    def __init__(self):
        self.version = 1
        self.alive = False
        self.data = None
        self.on_error: Optional[OnError] = None
        self.locked = False
        self.pending: List[Tuple[int, str]] = []
        self.cond = threading.Condition()


def _pack(slot_idx: int, version: int) -> int:
    return (version << 24) | (slot_idx & 0xFFFFFF)


def _unpack(cid: int) -> Tuple[int, int]:
    return cid & 0xFFFFFF, cid >> 24


class CallIdPool:
    def __init__(self):
        self._slots: List[_IdSlot] = []
        self._free: List[int] = []
        self._lock = threading.Lock()

    # ---- lifecycle ---------------------------------------------------------
    def create(self, data=None, on_error: Optional[OnError] = None) -> int:
        with self._lock:
            if self._free:
                idx = self._free.pop()
                slot = self._slots[idx]
            else:
                idx = len(self._slots)
                slot = _IdSlot()
                self._slots.append(slot)
        with slot.cond:
            slot.alive = True
            slot.data = data
            slot.on_error = on_error
            slot.locked = False
            slot.pending.clear()
        return _pack(idx, slot.version)

    def _slot_of(self, cid: int) -> Optional[_IdSlot]:
        idx, _ = _unpack(cid)
        if idx >= len(self._slots):
            return None
        return self._slots[idx]

    def _valid(self, slot: _IdSlot, cid: int) -> bool:
        _, ver = _unpack(cid)
        return slot.alive and slot.version == ver

    # ---- lock / unlock -----------------------------------------------------
    def lock(self, cid: int, timeout: Optional[float] = None):
        """Lock the id. Returns the data on success, None if the id (or
        this version of it) no longer exists — the stale-response drop."""
        slot = self._slot_of(cid)
        if slot is None:
            return None
        with slot.cond:
            while self._valid(slot, cid) and slot.locked:
                if not slot.cond.wait(timeout):
                    return None
            if not self._valid(slot, cid):
                return None
            slot.locked = True
            return slot.data

    def unlock(self, cid: int) -> bool:
        slot = self._slot_of(cid)
        if slot is None:
            return False
        run_error = None
        with slot.cond:
            if not slot.locked or not self._valid(slot, cid):
                return False
            if slot.pending and self._valid(slot, cid):
                run_error = slot.pending.pop(0)  # stay locked; handler owns it
            else:
                slot.locked = False
                slot.cond.notify_all()
        if run_error is not None:
            code, text = run_error
            self._run_on_error(slot, cid, code, text)
        return True

    def unlock_and_destroy(self, cid: int) -> bool:
        slot = self._slot_of(cid)
        if slot is None:
            return False
        idx, _ = _unpack(cid)
        with slot.cond:
            if not slot.alive:
                return False
            slot.alive = False
            slot.version += 1
            slot.locked = False
            slot.data = None
            slot.on_error = None
            slot.pending.clear()
            slot.cond.notify_all()
        with self._lock:
            self._free.append(idx)
        return True

    def bump_version(self, cid: int) -> int:
        """Invalidate previously-minted wire ids (retry versioning).
        Caller must hold the lock; returns the new current cid."""
        slot = self._slot_of(cid)
        assert slot is not None and slot.locked, "bump_version requires the lock"
        with slot.cond:
            slot.version += 1
            idx, _ = _unpack(cid)
            return _pack(idx, slot.version)

    # ---- error & join ------------------------------------------------------
    def error(self, cid: int, error_code: int, error_text: str = "") -> bool:
        """Deliver an error to the id (reference bthread_id_error)."""
        slot = self._slot_of(cid)
        if slot is None:
            return False
        with slot.cond:
            if not self._valid(slot, cid):
                return False
            if slot.locked:
                slot.pending.append((error_code, error_text))
                return True
            slot.locked = True
        self._run_on_error(slot, cid, error_code, error_text)
        return True

    def _run_on_error(self, slot: _IdSlot, cid: int, code: int, text: str):
        handler = slot.on_error
        data = slot.data
        if handler is None:
            # default: destroy so joiners wake (reference default handler)
            self.unlock_and_destroy(cid)
            return
        handler(data, cid, code, text)  # handler must unlock/destroy

    def join(self, cid: int, timeout: Optional[float] = None) -> bool:
        """Block until the id is destroyed (bthread_id_join)."""
        slot = self._slot_of(cid)
        if slot is None:
            return True
        from incubator_brpc_tpu.runtime import scheduler

        ctrl = scheduler.get_task_control() if scheduler.in_worker() else None
        with slot.cond:
            if not self._valid(slot, cid):
                return True
            if ctrl:
                ctrl.on_task_block()
            try:
                return slot.cond.wait_for(lambda: not self._valid(slot, cid), timeout)
            finally:
                if ctrl:
                    ctrl.on_task_unblock()


_default_pool = CallIdPool()


def default_pool() -> CallIdPool:
    return _default_pool
