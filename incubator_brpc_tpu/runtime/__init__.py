"""Task runtime — the bthread analog (reference src/bthread/).

The reference implements M:N user-space threads with hand-written
context-switch assembly (bthread/context.cpp), per-worker work-stealing
run queues (task_group.cpp), futex-based parking (parking_lot.h), and a
butex primitive unifying all blocking (butex.cpp).

The TPU rebuild keeps the *architecture* — TaskControl owning worker
groups with work-stealing deques and a parking lot, butex as the single
blocking primitive, versioned correlation ids, execution queues, one
timer thread — on top of OS threads (CPython can't swap user-space
stacks; the GIL already serializes compute, and the RPC hot path is IO
where threads release the GIL). TaskControl grows workers adaptively
when tasks block, preserving bthread's "blocking a task never stalls
the event loop" property that the M:N design exists for.
"""

from incubator_brpc_tpu.runtime.scheduler import (  # noqa: F401
    TaskControl,
    get_task_control,
    spawn,
    spawn_urgent,
)
from incubator_brpc_tpu.runtime.butex import Butex  # noqa: F401
from incubator_brpc_tpu.runtime.call_id import CallIdPool, INVALID_CALL_ID  # noqa: F401
from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue  # noqa: F401
from incubator_brpc_tpu.runtime.timer_thread import TimerThread, get_timer_thread  # noqa: F401
from incubator_brpc_tpu.runtime.sync import CountdownEvent  # noqa: F401
