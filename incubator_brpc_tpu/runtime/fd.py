"""fd_wait / task-aware connect — wait on raw fds without blocking
runtime workers.

Analog of reference bthread_fd_wait / bthread_connect (bthread/fd.cpp
EpollThread, :111-408): user code inside a task can park on a file
descriptor's readiness; the wait rides the shared EventDispatcher's
epoll loop (the reference runs a small dedicated epoll thread pool —
same shape, one loop here) and the task blocks on a Butex, so the
worker thread stays available to other tasks via the scheduler's
block/unblock accounting.
"""

from __future__ import annotations

import socket as _pysocket
from typing import Optional

from incubator_brpc_tpu.runtime.butex import Butex
from incubator_brpc_tpu.transport.event_dispatcher import get_dispatcher

EVENT_IN = "in"
EVENT_OUT = "out"


class _FdWaiter:
    """One-shot consumer: wakes the butex on the REQUESTED readiness
    (a writability waiter must not fire on incoming bytes), then
    detaches."""

    __slots__ = ("_butex", "result", "_want")

    def __init__(self, want: str):
        self._butex = Butex(0)
        self.result = 0  # 1 = ready, -1 = error/hup
        self._want = want

    def _fire(self, value: int):
        self.result = value
        self._butex.set_and_wake(1, all=True)

    def _on_epoll_in(self):
        if self._want == EVENT_IN:
            self._fire(1)

    def _on_epoll_out(self):
        if self._want == EVENT_OUT:
            self._fire(1)

    def _on_epoll_err(self):
        self._fire(-1)

    def wait(self, timeout: Optional[float]) -> int:
        # Butex.wait blocks while value == 0 and itself handles the
        # scheduler's block/unblock accounting
        if not self._butex.wait(0, timeout) and self._butex.value != 1:
            return 0
        return self.result


def fd_wait(fd: int, event: str = EVENT_IN, timeout: Optional[float] = None) -> int:
    """Park the calling task until `fd` is readable (EVENT_IN) or
    writable (EVENT_OUT). → 1 ready, 0 timeout, -1 error/hup.
    (bthread_fd_wait analog; the fd must not already be registered
    with the transport — this is for USER fds, not framework sockets.)
    """
    disp = get_dispatcher(fd)
    waiter = _FdWaiter(event)
    if not disp.add_consumer(fd, waiter):
        return -1
    if event == EVENT_OUT and not disp.enable_epollout(fd):
        disp.remove_consumer(fd)
        return -1  # fd not epollable for OUT: fail fast, not timeout
    try:
        return waiter.wait(timeout)
    finally:
        disp.remove_consumer(fd)


def task_connect(
    addr, timeout: Optional[float] = 3.0
) -> Optional[_pysocket.socket]:
    """Non-blocking connect that parks the task instead of the worker
    thread (bthread_connect analog). → connected socket or None."""
    host, port = addr[0], addr[1]
    try:
        family = _pysocket.getaddrinfo(
            host, port, _pysocket.AF_UNSPEC, _pysocket.SOCK_STREAM
        )[0][0]
    except OSError:
        return None
    s = _pysocket.socket(family, _pysocket.SOCK_STREAM)
    s.setblocking(False)
    try:
        rc = s.connect_ex(addr)
        if rc == 0:
            return s
        import errno as _errno

        if rc not in (_errno.EINPROGRESS, _errno.EWOULDBLOCK):
            s.close()
            return None
        if fd_wait(s.fileno(), EVENT_OUT, timeout) != 1:
            s.close()
            return None
        err = s.getsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_ERROR)
        if err != 0:
            s.close()
            return None
        return s
    except OSError:
        s.close()
        return None
