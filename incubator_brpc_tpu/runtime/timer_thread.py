"""TimerThread — dedicated timer scheduling thread.

Analog of bthread::TimerThread (timer_thread.h:50-90): one thread runs
all timers (RPC timeouts, backup-request triggers, health-check
probes). The reference hashes timers into 13 buckets to cut lock
contention; here a single heapq under one lock is enough for CPython.
Unschedule is best-effort exactly like the reference: a timer that
already started running cannot be stopped.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional

from incubator_brpc_tpu.utils.logging import log_error

_counter = itertools.count(1)


class TimerThread:
    def __init__(self, name: str = "tpubrpc-timer"):
        self._heap: list = []  # (deadline, seq, fn, args)
        self._live: set = set()  # seqs still in the heap
        self._cancelled: set = set()
        self._cond = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True, name=name)
        self._thread.start()

    def schedule(self, fn: Callable, delay_s: float, *args) -> int:
        """Run fn(*args) after delay_s seconds. Returns a timer id."""
        deadline = time.monotonic() + max(0.0, delay_s)
        seq = next(_counter)
        with self._cond:
            heapq.heappush(self._heap, (deadline, seq, fn, args))
            self._live.add(seq)
            self._cond.notify()
        return seq

    def schedule_abs(self, fn: Callable, abstime_monotonic: float, *args) -> int:
        seq = next(_counter)
        with self._cond:
            heapq.heappush(self._heap, (abstime_monotonic, seq, fn, args))
            self._live.add(seq)
            self._cond.notify()
        return seq

    def unschedule(self, timer_id: int) -> None:
        """Best-effort cancel (TimerThread::unschedule). A timer that
        already fired is ignored (no leak: only live ids are tracked)."""
        with self._cond:
            if timer_id in self._live:
                self._cancelled.add(timer_id)

    def _run(self):
        while True:
            with self._cond:
                if self._stopped:
                    return
                now = time.monotonic()
                while self._heap and (
                    self._heap[0][1] in self._cancelled or self._heap[0][0] <= now
                ):
                    deadline, seq, fn, args = heapq.heappop(self._heap)
                    self._live.discard(seq)
                    if seq in self._cancelled:
                        self._cancelled.discard(seq)
                        continue
                    break
                else:
                    timeout = self._heap[0][0] - now if self._heap else None
                    self._cond.wait(timeout)
                    continue
            # run expired timer outside the lock
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001
                log_error("timer %r raised: %r", fn, e)

    def stop_and_join(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=1.0)


_default: Optional[TimerThread] = None
_default_lock = threading.Lock()


def get_timer_thread() -> TimerThread:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = TimerThread()
    return _default
