"""Authenticator — connection-level authentication.

Analog of reference brpc::Authenticator (authenticator.h): the client
packs ``generate_credential()`` into the first message it sends on a
connection (we attach it to every tpu_std request meta / http request —
a few bytes — which keeps concurrent-first-write races and pooled/short
reconnects trivially correct); the server verifies the FIRST message on
each connection through the protocol ``verify`` hook
(input_messenger.cpp:282-300) and drops the connection on mismatch.

Usage:
    class MyAuth(Authenticator):
        def generate_credential(self) -> str: ...
        def verify_credential(self, auth_str, peer) -> int: ...  # 0 = ok

    ChannelOptions(auth=MyAuth())   # client side
    ServerOptions(auth=MyAuth())    # server side
"""

from __future__ import annotations

from typing import Optional

from incubator_brpc_tpu.utils.endpoint import EndPoint


class AuthContext:
    """What a verified credential resolved to (reference AuthContext):
    attached to the server connection for handlers to inspect."""

    __slots__ = ("user", "group", "roles", "starter", "is_service")

    def __init__(self, user="", group="", roles="", starter="", is_service=False):
        self.user = user
        self.group = group
        self.roles = roles
        self.starter = starter
        self.is_service = is_service


class Authenticator:
    def generate_credential(self) -> str:
        """Client side: the credential string packed into request meta.
        Raise or return "" to send nothing."""
        raise NotImplementedError

    def verify_credential(
        self, auth_str: str, peer: Optional[EndPoint], context: "AuthContext" = None
    ) -> int:
        """Server side: 0 accepts; nonzero rejects (connection closes /
        gRPC UNAUTHENTICATED). Implementations taking the third
        parameter may fill ``context`` with the resolved identity; on
        success it is attached to the connection and handlers read it
        via ``Controller.auth_context()``. Two-parameter overrides
        (without ``context``) are also accepted."""
        raise NotImplementedError
