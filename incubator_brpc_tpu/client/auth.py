"""Authenticator — connection-level authentication.

Analog of reference brpc::Authenticator (authenticator.h): the client
packs ``generate_credential()`` into the first message it sends on a
connection (we attach it to every tpu_std request meta / http request —
a few bytes — which keeps concurrent-first-write races and pooled/short
reconnects trivially correct); the server verifies the FIRST message on
each connection through the protocol ``verify`` hook
(input_messenger.cpp:282-300) and drops the connection on mismatch.

Usage:
    class MyAuth(Authenticator):
        def generate_credential(self) -> str: ...
        def verify_credential(self, auth_str, peer) -> int: ...  # 0 = ok

    ChannelOptions(auth=MyAuth())   # client side
    ServerOptions(auth=MyAuth())    # server side
"""

from __future__ import annotations

from typing import Optional

from incubator_brpc_tpu.utils.endpoint import EndPoint


class AuthContext:
    """What a verified credential resolved to (reference AuthContext):
    attached to the server connection for handlers to inspect."""

    __slots__ = ("user", "group", "roles", "starter", "is_service")

    def __init__(self, user="", group="", roles="", starter="", is_service=False):
        self.user = user
        self.group = group
        self.roles = roles
        self.starter = starter
        self.is_service = is_service


class Authenticator:
    def generate_credential(self) -> str:
        """Client side: the credential string packed into request meta.
        Raise or return "" to send nothing."""
        raise NotImplementedError

    def verify_credential(
        self, auth_str: str, peer: Optional[EndPoint], context: "AuthContext" = None
    ) -> int:
        """Server side: 0 accepts; nonzero rejects (connection closes /
        gRPC UNAUTHENTICATED). Implementations taking the third
        parameter may fill ``context`` with the resolved identity; on
        success it is attached to the connection and handlers read it
        via ``Controller.auth_context()``. Two-parameter overrides
        (without ``context``) are also accepted."""
        raise NotImplementedError


class CouchbaseAuthenticator(Authenticator):
    """SASL PLAIN credential for couchbase buckets (reference
    policy/couchbase_authenticator.cpp:38-55): the credential is a
    complete memcache-binary SASL_AUTH request — magic 0x80, opcode
    0x21, key "PLAIN", value "<bucket>\\0<bucket>\\0<password>" — sent
    as the first bytes of the connection so the couchbase server
    authenticates the bucket before any command runs."""

    MC_MAGIC_REQUEST = 0x80
    MC_BINARY_SASL_AUTH = 0x21

    def __init__(self, bucket_name: str, bucket_password: str):
        self.bucket_name = bucket_name
        self.bucket_password = bucket_password

    def generate_credential(self) -> str:
        import struct

        key = b"PLAIN"
        value = (
            self.bucket_name.encode() + b"\0"
            + self.bucket_name.encode() + b"\0"
            + self.bucket_password.encode()
        )
        header = struct.pack(
            ">BBHBBHIIQ",
            self.MC_MAGIC_REQUEST, self.MC_BINARY_SASL_AUTH,
            len(key),  # key length
            0, 0, 0,  # extras len, data type, vbucket
            len(key) + len(value),  # total body
            0, 0,  # opaque, cas
        )
        return (header + key + value).decode("latin1")

    def verify_credential(self, auth_str, peer, context=None) -> int:
        # client-only authenticator: the couchbase SERVER verifies
        return 0


class EspAuthenticator(Authenticator):
    """esp service credential (reference policy/esp_authenticator.cpp):
    a fixed magic preamble plus the 2-byte local port.  Verify accepts
    everything — parity with the reference, whose VerifyCredential is
    an explicit no-op."""

    MAGICNUM = b"\0ESP\x01\x02"

    def __init__(self, local_port: int = 0):
        self.local_port = local_port

    def generate_credential(self) -> str:
        import struct

        return (
            self.MAGICNUM + struct.pack("<H", self.local_port)
        ).decode("latin1")

    def verify_credential(self, auth_str, peer, context=None) -> int:
        return 0  # reference EspAuthenticator::VerifyCredential: no-op
