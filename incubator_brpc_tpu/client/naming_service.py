"""Naming services — cluster membership discovery.

Analog of reference NamingService (naming_service.h:30-70): an NS
watches a source and *pushes* server-list updates to its watcher
(NamingServiceActions::ResetServers); polling impls subclass
PeriodicNamingService; NamingServiceThread dedups watchers per URL
(details/naming_service_thread.{h,cpp}).

Built-ins (reference set minus Baidu-internal ones, global.cpp:128-139):
  list://host:port[ w],host:port   static list with optional weights
  file://path                      file with one "host:port [w]" per
                                   line, watched for changes
  tpu://                           the TPU topology: every ici://
                                   port registered on the fabric, plus
                                   mesh devices — the "naming-service
                                   layer resolves TPU slice
                                   coordinates" north-star piece
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from incubator_brpc_tpu.utils.endpoint import EndPoint, str2endpoint
from incubator_brpc_tpu.utils.logging import log_error


@dataclass(frozen=True)
class ServerNode:
    """Analog of brpc::ServerNode (naming_service.h)."""

    endpoint: EndPoint
    weight: int = 1
    tag: str = ""  # PartitionChannel reads "N/M" partition tags from here


class NamingServiceWatcher:
    """Actions interface (NamingServiceActions): receives full resets."""

    def on_servers_changed(self, nodes: List[ServerNode]) -> None:
        raise NotImplementedError


class NamingService:
    name = ""

    def run(self, url: str, watcher: NamingServiceWatcher, stop_event) -> None:
        raise NotImplementedError


def _parse_node_line(line: str) -> Optional[ServerNode]:
    line = line.split("#", 1)[0].strip()
    if not line:
        return None
    parts = line.split()
    ep = str2endpoint(parts[0])
    weight = int(parts[1]) if len(parts) > 1 else 1
    tag = parts[2] if len(parts) > 2 else ""
    return ServerNode(ep, weight, tag)


class PeriodicNamingService(NamingService):
    """Base for polling services (reference PeriodicNamingService)."""

    interval_s = 1.0

    def get_servers(self, path: str) -> List[ServerNode]:
        raise NotImplementedError

    def run(self, url: str, watcher: NamingServiceWatcher, stop_event) -> None:
        path = url.split("://", 1)[1] if "://" in url else url
        last: Optional[List[ServerNode]] = None
        while not stop_event.is_set():
            try:
                nodes = self.get_servers(path)
                if nodes != last:
                    last = nodes
                    watcher.on_servers_changed(nodes)
            except Exception as e:  # noqa: BLE001
                log_error("naming service %s failed: %r", url, e)
            stop_event.wait(self.interval_s)


class ListNamingService(NamingService):
    """list://addr[ w][;tag],addr — static, resolved once."""

    name = "list"

    def run(self, url, watcher, stop_event):
        body = url.split("://", 1)[1]
        nodes = []
        for item in body.split(","):
            node = _parse_node_line(item.replace(";", " "))
            if node:
                nodes.append(node)
        watcher.on_servers_changed(nodes)
        stop_event.wait()  # static: nothing more to do


class FileNamingService(PeriodicNamingService):
    """file://path — one node per line, re-read when it changes
    (the reference test suite's cluster simulator, SURVEY.md §4)."""

    name = "file"

    def get_servers(self, path: str) -> List[ServerNode]:
        nodes = []
        with open(path) as f:
            for line in f:
                node = _parse_node_line(line)
                if node:
                    nodes.append(node)
        return nodes


class TpuTopologyNamingService(PeriodicNamingService):
    """tpu:// — resolve TPU slice coordinates: every server port
    registered on the ICI fabric (tpu://fabric, the default), or the
    mesh devices (tpu://mesh)."""

    name = "tpu"
    interval_s = 0.5

    def get_servers(self, path: str) -> List[ServerNode]:
        if path in ("", "fabric"):
            from incubator_brpc_tpu.parallel.ici import get_fabric

            return [
                ServerNode(EndPoint.ici(*coords))
                for coords in get_fabric().server_coords()
            ]
        if path == "mesh":
            from incubator_brpc_tpu.parallel.mesh import default_mesh, ici_endpoints

            return [ServerNode(ep) for ep in ici_endpoints(default_mesh())]
        raise ValueError(f"unknown tpu:// path {path!r}")


_registry: Dict[str, NamingService] = {}


def register_naming_service(ns: NamingService):
    _registry[ns.name] = ns


def find_naming_service(url: str) -> Optional[NamingService]:
    scheme = url.split("://", 1)[0] if "://" in url else ""
    return _registry.get(scheme)


register_naming_service(ListNamingService())
register_naming_service(FileNamingService())
register_naming_service(TpuTopologyNamingService())


class NamingServiceThread:
    """One background thread per (url); multiplexes watchers
    (reference details/naming_service_thread.{h,cpp})."""

    _threads: Dict[str, "NamingServiceThread"] = {}
    _threads_lock = threading.Lock()

    def __init__(self, url: str, ns: NamingService):
        self.url = url
        self._ns = ns
        self._watchers: List[NamingServiceWatcher] = []
        self._last_nodes: Optional[List[ServerNode]] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"tpubrpc-ns-{ns.name}"
        )
        self._thread.start()

    class _Fan(NamingServiceWatcher):
        def __init__(self, owner):
            self.owner = owner

        def on_servers_changed(self, nodes):
            with self.owner._lock:
                self.owner._last_nodes = list(nodes)
                watchers = list(self.owner._watchers)
            for w in watchers:
                try:
                    w.on_servers_changed(nodes)
                except Exception as e:  # noqa: BLE001
                    log_error("ns watcher raised: %r", e)

    def _run(self):
        try:
            self._ns.run(self.url, NamingServiceThread._Fan(self), self._stop)
        except Exception as e:  # noqa: BLE001 — a bad URL must not kill the
            # cached thread silently; deliver an empty list so watchers see
            # ENOSERVICE rather than hanging on stale state
            log_error("naming service %s died: %r", self.url, e)
            NamingServiceThread._Fan(self).on_servers_changed([])

    def add_watcher(self, watcher: NamingServiceWatcher):
        with self._lock:
            self._watchers.append(watcher)
            nodes = self._last_nodes
        if nodes is not None:
            watcher.on_servers_changed(nodes)

    def remove_watcher(self, watcher: NamingServiceWatcher):
        with self._lock:
            try:
                self._watchers.remove(watcher)
            except ValueError:
                pass

    @classmethod
    def get(cls, url: str) -> Optional["NamingServiceThread"]:
        ns = find_naming_service(url)
        if ns is None:
            return None
        with cls._threads_lock:
            t = cls._threads.get(url)
            if t is None:
                t = cls(url, ns)
                cls._threads[url] = t
            return t
