"""Remote naming services — DNS, remotefile, consul, discovery, nacos.

Analogs of the reference's network-backed naming services
(global.cpp:128-139): domain_naming_service.cpp (http://host DNS
round-robin), remote_file_naming_service.cpp (server list fetched over
HTTP), consul_naming_service.cpp (/v1/health/service),
discovery_naming_service.cpp (Bilibili discovery /discovery/fetch), and
nacos_naming_service.cpp (/nacos/v1/ns/instance/list). All are
PeriodicNamingService subclasses: poll, diff, push.

Everything uses stdlib urllib against the address embedded in the
naming URL, so tests can point them at an in-process HTTP server.
"""

from __future__ import annotations

import json
import socket as _pysocket
import urllib.request
from typing import List
from urllib.parse import parse_qs, urlsplit

from incubator_brpc_tpu.client.naming_service import (
    PeriodicNamingService,
    ServerNode,
    register_naming_service,
)
from incubator_brpc_tpu.utils.endpoint import EndPoint

_HTTP_TIMEOUT_S = 3.0


def _http_get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=_HTTP_TIMEOUT_S) as resp:
        return resp.read()


class DomainNamingService(PeriodicNamingService):
    """http://host[:port] — DNS A/AAAA records, one node per address
    (reference domain_naming_service.cpp + http default port)."""

    name = "http"
    default_port = 80
    interval_s = 5.0

    def get_servers(self, path: str) -> List[ServerNode]:
        hostport = path.split("/", 1)[0]
        host, _, port_s = hostport.partition(":")
        port = int(port_s) if port_s else self.default_port
        infos = _pysocket.getaddrinfo(
            host, port, _pysocket.AF_UNSPEC, _pysocket.SOCK_STREAM
        )
        seen = set()
        nodes = []
        for _f, _t, _p, _cn, sockaddr in infos:
            addr = sockaddr[0]
            if addr in seen:
                continue
            seen.add(addr)
            nodes.append(ServerNode(EndPoint.tcp(addr, port)))
        return sorted(nodes, key=lambda n: str(n.endpoint))


class HttpsDomainNamingService(DomainNamingService):
    name = "https"
    default_port = 443


class RemoteFileNamingService(PeriodicNamingService):
    """remotefile://host:port/path — the server list itself is fetched
    over HTTP; body format matches file:// (one 'host:port [w] [tag]'
    per line). Reference remote_file_naming_service.cpp."""

    name = "remotefile"
    interval_s = 5.0

    def get_servers(self, path: str) -> List[ServerNode]:
        from incubator_brpc_tpu.client.naming_service import _parse_node_line

        body = _http_get(f"http://{path}").decode()
        nodes = []
        for line in body.splitlines():
            node = _parse_node_line(line)
            if node:
                nodes.append(node)
        return nodes


class ConsulNamingService(PeriodicNamingService):
    """consul://host:port/service-name — healthy instances from the
    consul HTTP API (reference consul_naming_service.cpp long-polls
    /v1/health/service; this polls the same endpoint periodically)."""

    name = "consul"
    interval_s = 2.0

    def get_servers(self, path: str) -> List[ServerNode]:
        hostport, _, service = path.partition("/")
        data = json.loads(
            _http_get(
                f"http://{hostport}/v1/health/service/{service}?passing=true"
            )
        )
        nodes = []
        for entry in data:
            svc = entry.get("Service", {})
            addr = svc.get("Address") or entry.get("Node", {}).get("Address")
            port = svc.get("Port")
            if not addr or not port:
                continue
            weight = (svc.get("Weights") or {}).get("Passing", 1)
            tags = svc.get("Tags") or []
            nodes.append(
                ServerNode(
                    EndPoint.tcp(addr, int(port)),
                    int(weight) or 1,
                    tags[0] if tags else "",
                )
            )
        return nodes


class DiscoveryNamingService(PeriodicNamingService):
    """discovery://host:port/appid — Bilibili discovery
    (reference discovery_naming_service.cpp /discovery/fetch):
    data.<appid>.instances[].addrs like 'grpc://1.2.3.4:9000'."""

    name = "discovery"
    interval_s = 2.0

    def get_servers(self, path: str) -> List[ServerNode]:
        hostport, _, appid = path.partition("/")
        raw = json.loads(
            _http_get(
                f"http://{hostport}/discovery/fetch?appid={appid}"
                "&env=prod&status=1"
            )
        )
        data = raw.get("data", {})
        # data may be keyed by appid or be the instance obj directly
        inst_holder = data.get(appid, data) if isinstance(data, dict) else {}
        nodes = []
        for inst in inst_holder.get("instances", []):
            for addr in inst.get("addrs", []):
                _, _, hp = addr.partition("://")
                host, _, port_s = hp.partition(":")
                if host and port_s:
                    nodes.append(ServerNode(EndPoint.tcp(host, int(port_s))))
        return nodes


class NacosNamingService(PeriodicNamingService):
    """nacos://host:port/serviceName[?namespaceId=..&groupName=..] —
    healthy instances from /nacos/v1/ns/instance/list (reference
    nacos_naming_service.cpp)."""

    name = "nacos"
    interval_s = 2.0

    def get_servers(self, path: str) -> List[ServerNode]:
        hostport, _, rest = path.partition("/")
        service, _, query = rest.partition("?")
        params = {k: v[0] for k, v in parse_qs(query).items()}
        url = (
            f"http://{hostport}/nacos/v1/ns/instance/list"
            f"?serviceName={service}&healthyOnly=true"
        )
        for k in ("namespaceId", "groupName"):
            if k in params:
                url += f"&{k}={params[k]}"
        data = json.loads(_http_get(url))
        nodes = []
        for host in data.get("hosts", []):
            if not host.get("enabled", True) or not host.get("healthy", True):
                continue
            nodes.append(
                ServerNode(
                    EndPoint.tcp(host["ip"], int(host["port"])),
                    max(1, int(float(host.get("weight", 1)))),
                )
            )
        return nodes


def register_remote_naming_services():
    register_naming_service(DomainNamingService())
    register_naming_service(HttpsDomainNamingService())
    register_naming_service(RemoteFileNamingService())
    register_naming_service(ConsulNamingService())
    register_naming_service(DiscoveryNamingService())
    register_naming_service(NacosNamingService())


register_remote_naming_services()
