"""Submission/completion ring — vectorized client calls.

io_uring's cure for syscall-bound IO, applied to the Python↔C boundary:
the sync fast path (docs/fastpath.md) costs one boundary crossing per
RPC, which caps the Python API near ~100k qps while the native engine
does ~430k.  A :class:`SubmissionRing` amortizes that crossing over a
WINDOW: Python stages N same-method calls and crosses ONCE
(``mux_submit_many`` — one C lock pass, one staging append, one reactor
wake), the C mux pipelines the frames onto the socket in one writev
burst, and completions come back in bursts through ``mux_harvest`` into
a PREALLOCATED completion ring (zero per-call Python allocation; the
7-slot lists are reused across harvests).

Correlation-slot lifecycle (exactly-once by construction):

1. ``submit()`` assigns a slot id and stages the call.
2. ``flush()`` reserves a contiguous ring-tag block (bit 63 set — the
   engine routes these completions to a ring-only queue the channel's
   background harvester can never steal from) and maps tag → slot.
3. The engine completes every registered cid exactly once: response,
   timeout sweep (-110), connection reset (-EPIPE), or client destroy
   (-ECANCELED).
4. ``harvest()`` pops the tag mapping and resolves the slot exactly
   once; transport errors may first resubmit under the remaining global
   deadline (a fresh single-call window, same slot).  A slot failed by
   the backstop drops its tag into a zombie set so a late completion is
   discarded instead of double-resolving.

Fallback matrix (degradation is byte-for-byte the existing per-call
path — literally ``channel.call_method``):

=====================================  =================================
call shape                             path taken
=====================================  =================================
plain call, native channel             ring (vectorized)
caller-provided Controller             per-call ``call_method`` (which
(tenant-tagged, attachment, stream,    itself picks the fused native
compression, per-call overrides)       path or the Python path per its
                                       own gate — the PR 8 tenant
                                       quota rule rides along for free)
non-native channel (incl. fan-out/     per-call ``call_method`` with
combo subclasses)                      pooled controllers
=====================================  =================================

Error semantics are ERPC-only in every lane: a failed slot yields a
:class:`RingFailure` carrying the same (error_code, error_text) the
equivalent ``call_method`` would have put on the controller, and pooled
controllers are wiped on recycle exactly as on the fast path.
"""

from __future__ import annotations

import itertools
import threading
from time import monotonic_ns as _monotonic_ns
from typing import List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.client.controller import (
    acquire_controller,
    release_controller,
)

# default completion-ring depth == the C harvest batch cap
RING_DEPTH = 128
# hard per-window cap enforced by the extension; flush() chunks to it
WINDOW_MAX = 1024

# process-wide /metrics counters (lazy: the first flush binds them so a
# bare `import client.ring` stays metrics-free)
_metrics = None


def _ring_metrics():
    global _metrics
    if _metrics is None:
        from incubator_brpc_tpu.metrics import ring_metrics

        _metrics = ring_metrics
    return _metrics


class _FanoutLog:
    """Process-wide step log for windowed shard fan-out (docs/fastpath.md
    "server ring" → shard windows).  Counts only — the proof that a
    64-key get_many or a PS fan-out crossed the C boundary once per
    SHARD (not once per key) is ``keys_per_crossing`` ≫ 1 with
    ``crossings == shards`` per window."""

    def __init__(self):
        self._lock = threading.Lock()
        self.windows = 0         # fan-out windows issued
        self.crossings = 0       # per-shard sub-window submissions
        self.keys = 0            # keys/requests carried by those windows
        self.fallback_calls = 0  # per-call degradations inside fan-outs

    def record(self, crossings: int, keys: int,
               fallback_calls: int = 0) -> None:
        with self._lock:
            self.windows += 1
            self.crossings += crossings
            self.keys += keys
            self.fallback_calls += fallback_calls

    def counters(self) -> dict:
        with self._lock:
            crossings = self.crossings
            return {
                "windows": self.windows,
                "crossings": crossings,
                "keys": self.keys,
                "fallback_calls": self.fallback_calls,
                "keys_per_crossing": (
                    self.keys / crossings if crossings else 0.0
                ),
            }


fanout_log = _FanoutLog()


class RingFailure:
    """A failed ring slot: the (error_code, error_text) pair the
    equivalent per-call path would have set on its Controller."""

    __slots__ = ("error_code", "error_text")

    def __init__(self, error_code: int, error_text: str):
        self.error_code = error_code
        self.error_text = error_text

    def failed(self) -> bool:
        return True

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"RingFailure({self.error_code}, {self.error_text!r})"


class SubmissionRing:
    """One caller's submission window + completion ring over a native
    channel's mux client.  NOT thread-safe: a ring belongs to one
    submitting thread (create one per pipeline; ``Channel.call_many``
    serializes on the channel's internal ring with a lock).
    """

    def __init__(self, channel, depth: int = RING_DEPTH):
        self._channel = channel
        self.depth = max(1, min(int(depth), RING_DEPTH))
        # preallocated completion ring: 7-slot lists reused across
        # harvests (mux_harvest fills them in place)
        self._ring = [[None] * 7 for _ in range(RING_DEPTH)]
        self._slot_iter = itertools.count(1)
        # slot id -> [key, method_name, payload, timeout_ms, log_id,
        #             retries_left, deadline_ns]
        self._state = {}
        self._tag2slot = {}
        self._staged: List[int] = []  # slot ids awaiting flush()
        # (key, timeout) shared by everything staged, or None when
        # nothing is staged; _staged_mixed records that two different
        # pairs were staged so flush() must group slot-by-slot.  The
        # common case (one submit_all window) skips the grouping pass.
        self._staged_kt = None
        self._staged_mixed = False
        self._done: List[tuple] = []  # (slot_id, result) ready to hand out
        self._done_slots = set()      # O(1) mirror of _done's slot ids
        # ---- step-log counters (the "fails loudly" contract):
        # a silently-degraded ring shows up as boundary_crossings ≈
        # submissions or fallback_calls > 0, not just as lower qps
        self.submissions = 0          # calls staged onto the ring
        self.windows = 0              # submit_many crossings
        self.harvest_batches = 0      # non-empty harvest crossings
        self.boundary_crossings = 0   # windows + harvests (+ retries)
        self.completions = 0          # ring completions consumed
        self.fallback_calls = 0       # calls degraded to call_method
        self.retries = 0              # transport-error resubmits
        self.double_resolves = 0      # MUST stay 0 (exactly-once guard)

    # ---- submission --------------------------------------------------------
    def submit(self, method_spec, request, timeout_ms: Optional[int] = None,
               controller=None) -> int:
        """Stage one call; returns its slot id.  The call crosses into C
        on the next ``flush()`` (or immediately, per-call, when it is not
        ring-eligible — see the fallback matrix above)."""
        slot = next(self._slot_iter)
        ch = self._channel
        if controller is not None or not ch._native_fast:
            self._fallback_call(slot, method_spec, request, timeout_ms,
                                controller)
            return slot
        mux = ch._native_mux()
        if mux is None:
            self._fallback_call(slot, method_spec, request, timeout_ms, None)
            return slot
        payload = (
            request if type(request) is bytes else request.SerializeToString()
        )
        if timeout_ms is None:
            timeout_ms = ch.options.timeout_ms
        key = method_spec.__dict__.get("_native_key")
        if key is None:
            key = (
                method_spec.service_name.encode(),
                method_spec.method_name.encode(),
            )
            method_spec._native_key = key
        max_retry = max(0, ch.options.max_retry)
        tmo = timeout_ms if timeout_ms and timeout_ms > 0 else -1
        deadline_ns = (
            _monotonic_ns() + tmo * 1_000_000 if tmo > 0 else None
        )
        self._state[slot] = [
            key, method_spec.method_name, payload, tmo,
            0, max_retry, deadline_ns,
        ]
        kt = (key, tmo)
        if self._staged_kt is None:
            self._staged_kt = kt
        elif self._staged_kt != kt:
            self._staged_mixed = True
        self._staged.append(slot)
        self.submissions += 1
        if len(self._staged) >= self.depth:
            self.flush()
        return slot

    def submit_all(self, method_spec, requests,
                   timeout_ms: Optional[int] = None) -> List[int]:
        """Bulk-stage N same-method calls; returns their slot ids in
        order.  The per-call constants (native key, timeout, deadline,
        retry budget) are computed ONCE per window, so the per-call
        Python cost drops to one state row and two appends — this is
        the staging half of the ≥2x-sync budget.  Degrades to per-call
        submit() (same fallback matrix) off the native lane."""
        ch = self._channel
        if not ch._native_fast or ch._native_mux() is None:
            return [self.submit(method_spec, r, timeout_ms)
                    for r in requests]
        if timeout_ms is None:
            timeout_ms = ch.options.timeout_ms
        key = method_spec.__dict__.get("_native_key")
        if key is None:
            key = (
                method_spec.service_name.encode(),
                method_spec.method_name.encode(),
            )
            method_spec._native_key = key
        mname = method_spec.method_name
        tmo = timeout_ms if timeout_ms and timeout_ms > 0 else -1
        max_retry = max(0, ch.options.max_retry)
        deadline_ns = (
            _monotonic_ns() + tmo * 1_000_000 if tmo > 0 else None
        )
        kt = (key, tmo)
        if self._staged_kt is None:
            self._staged_kt = kt
        elif self._staged_kt != kt:
            self._staged_mixed = True
        state = self._state
        staged = self._staged
        nxt = self._slot_iter.__next__
        depth = self.depth
        slots = []
        for req in requests:
            payload = (
                req if type(req) is bytes else req.SerializeToString()
            )
            slot = nxt()
            state[slot] = [key, mname, payload, tmo, 0, max_retry,
                           deadline_ns]
            if self._staged_kt is None:  # re-arm after a mid-loop flush
                self._staged_kt = kt
            staged.append(slot)
            slots.append(slot)
            if len(staged) >= depth:
                self.flush()
        self.submissions += len(slots)
        return slots

    def _fallback_call(self, slot, method_spec, request, timeout_ms,
                       controller) -> None:
        """Per-call degradation: EXACTLY the existing path.  call_method
        applies its own native/Python gate (tenant, streams, attachments,
        compression), so semantics — including the PR 8 tenant-quota
        rule and ERPC error codes — are byte-for-byte the old path."""
        self.fallback_calls += 1
        ctrl = controller
        pooled = ctrl is None
        if pooled:
            ctrl = acquire_controller()
        if timeout_ms is not None and ctrl.timeout_ms is None:
            ctrl.timeout_ms = timeout_ms
        try:
            # a real response object, not bytes-mode: response_bytes is
            # a native-lane contract and the whole point here is that
            # the call may take the pure Python path (tenant, non-native
            # channel) — which only fills a message.  Re-serializing
            # normalizes the return type; it costs one pb round trip on
            # the (rare) fallback lane only.
            resp = method_spec.response_class()
            self._channel.call_method(method_spec, ctrl, request, resp)
            if ctrl.error_code:
                result = RingFailure(ctrl.error_code, ctrl.error_text())
            else:
                result = resp.SerializeToString()
        finally:
            if pooled:
                release_controller(ctrl)  # wiped on recycle (PR 2)
        self._resolve(slot, result)

    def flush(self) -> None:
        """Cross the boundary ONCE per (method, timeout) group: reserve
        a ring-tag block, stage the whole window via mux_submit_many.
        Calls the engine refuses to stage (shutdown / dead conn with a
        deep backlog) fail immediately with the transport error the
        per-call path maps to EFAILEDSOCKET."""
        if not self._staged:
            return
        staged, self._staged = self._staged, []
        kt, self._staged_kt = self._staged_kt, None
        mixed, self._staged_mixed = self._staged_mixed, False
        if kt is not None and not mixed:
            # uniform window (the submit_all case): skip the per-slot
            # grouping pass entirely
            groups = {kt: staged}
        else:
            groups = {}
            for slot in staged:
                st = self._state[slot]
                groups.setdefault((st[0], st[3]), []).append(slot)
        mux = self._channel._native_mux()
        for (key, timeout_ms), slots in groups.items():
            if _chaos.armed:
                spec = _chaos.check(
                    "ring.submit",
                    method=self._state[slots[0]][1],
                    direction="submit",
                )
                if spec is not None:
                    if spec.action == "delay_us":
                        _chaos.sleep_us(spec.arg)
                    elif spec.action == "drop":
                        # the window never reaches the mux: every slot
                        # completes exactly once with the transport
                        # error, no stranded waiter
                        for slot in slots:
                            self._state.pop(slot, None)
                            self._resolve(slot, RingFailure(
                                errors.EFAILEDSOCKET,
                                "chaos: ring window dropped",
                            ))
                        continue
            for base in range(0, len(slots), WINDOW_MAX):
                chunk = slots[base:base + WINDOW_MAX]
                payloads = [self._state[s][2] for s in chunk]
                tag_base = mux.reserve_ring_tags(len(chunk))
                for i, slot in enumerate(chunk):
                    self._tag2slot[tag_base + i] = slot
                self.windows += 1
                self.boundary_crossings += 1
                m = _ring_metrics()
                m.rpc_ring_windows << 1
                m.rpc_ring_crossings << 1
                n = mux.submit_window(
                    key[0], key[1], payloads, timeout_ms, 0, tag_base
                )
                for i in range(n, len(chunk)):
                    slot = chunk[i]
                    self._tag2slot.pop(tag_base + i, None)
                    self._state.pop(slot, None)
                    self._resolve(slot, RingFailure(
                        errors.EFAILEDSOCKET,
                        "native transport error rc=-32 (ring submit)",
                    ))

    # ---- completion --------------------------------------------------------
    def harvest(self, timeout_ms: int = 0) -> List[tuple]:
        """Burst-harvest ring completions into the preallocated ring
        and resolve their slots.  Returns every newly resolved
        (slot_id, result) pair — including fallback and failed-at-flush
        results queued since the last call.  result is response bytes
        or a RingFailure.

        All rings on one channel share the mux's C-side completion
        lane.  LEADER/FOLLOWER: the ring holding the mux's harvest lock
        drains the lane and routes every completion — its own resolve
        in place, a SIBLING's parks in the stash with a condition
        notify.  A ring that loses the lock waits on that condition
        instead of contending for the lane, so a completion harvested
        by a sibling costs its owner one wakeup, not a harvest timeout
        (the 860-vs-200k-qps difference under 8 concurrent rings)."""
        out = self._take_done()
        if not self._tag2slot:
            return out
        mux = self._channel._native_mux()
        deadline = _monotonic_ns() + max(0, timeout_ms) * 1_000_000
        while True:
            self._claim_stash(mux)
            if self._done:
                break  # resolved from the stash: no crossing needed
            if mux._ring_harvest_lock.acquire(blocking=False):
                try:
                    remaining_ms = max(
                        0, (deadline - _monotonic_ns()) // 1_000_000
                    )
                    self._harvest_lane(mux, int(remaining_ms))
                finally:
                    mux._ring_harvest_lock.release()
                break
            # follower: a sibling is draining the lane on our behalf;
            # sleep until it stashes something for us or the lane frees
            # up (bounded so a departing leader can't strand us)
            wait_s = (deadline - _monotonic_ns()) / 1e9
            if wait_s <= 0:
                break
            with mux._ring_lock:
                if not any(t in mux._ring_stash for t in self._tag2slot):
                    mux._ring_stash_cv.wait(min(wait_s, 0.05))
        out.extend(self._take_done())
        return out

    def _claim_stash(self, mux) -> None:
        """Consume any of our completions a sibling ring parked."""
        if not mux._ring_stash:
            return
        with mux._ring_lock:
            claimed = [
                mux._ring_stash.pop(t)
                for t in list(self._tag2slot)
                if t in mux._ring_stash
            ]
        for comp in claimed:
            self._consume(mux, comp)

    def _harvest_lane(self, mux, timeout_ms: int) -> None:
        """One boundary crossing as the lane leader: drain the C-side
        completion queue and route every tuple to its owner."""
        self.boundary_crossings += 1
        _ring_metrics().rpc_ring_crossings << 1
        n = mux.harvest_window(timeout_ms, self._ring)
        if n > 0:
            self.harvest_batches += 1
            self.completions += n
        stashed = False
        t2s = self._tag2slot
        state = self._state
        done_slots = self._done_slots
        done = self._done
        for i in range(n):
            row = self._ring[i]
            slot = t2s.get(row[0])
            if (slot is not None and row[1] == 0 and not row[4]
                    and not row[3] and not row[6]):
                # inlined common shape (success, no error/attachment/
                # compression): the body bytes are an owned object, so
                # handing row[2] out is safe even though the 7-slot
                # list itself is reused by the next harvest
                del t2s[row[0]]
                state.pop(slot, None)
                if slot in done_slots:
                    self.double_resolves += 1
                else:
                    done_slots.add(slot)
                    done.append((slot, row[2]))
                continue
            # copy out of the preallocated slot: a stashed tuple must
            # survive the slot being overwritten by the next harvest
            comp = tuple(row)
            if slot is not None:
                self._consume(mux, comp)
            else:
                with mux._ring_lock:
                    if comp[0] in mux._ring_zombie:
                        # late completion for a backstop-failed slot:
                        # already resolved; drop it (exactly-once)
                        mux._ring_zombie.discard(comp[0])
                    else:
                        mux._ring_stash[comp[0]] = comp
                        stashed = True
        if stashed:
            with mux._ring_lock:
                mux._ring_stash_cv.notify_all()

    def _consume(self, mux, comp) -> None:
        """Resolve one completion tuple against its slot — exactly once
        (tag→slot single-pop); transport errors may first resubmit."""
        tag, rc, body, att_size, ec, etext, ctype = comp
        slot = self._tag2slot.pop(tag, None)
        if slot is None:
            return
        st = self._state[slot]
        if rc not in (0, -110) and st[5] > 0:
            # transport error with retry budget: resubmit within the
            # remaining global deadline (mirrors _call_native_slow's
            # retry-on-global-deadline loop), as a single-call window
            remaining_ms = -1
            if st[6] is not None:
                remaining_ms = (st[6] - _monotonic_ns()) // 1_000_000
            if st[6] is None or remaining_ms > 0:
                st[5] -= 1
                st[4] += 1
                self.retries += 1
                self.windows += 1
                self.boundary_crossings += 1
                m = _ring_metrics()
                m.rpc_ring_windows << 1
                m.rpc_ring_crossings << 1
                self._tag2slot[tag] = slot
                k = mux.submit_window(
                    st[0][0], st[0][1], [st[2]],
                    int(remaining_ms) if remaining_ms > 0 else -1,
                    0, tag,
                )
                if k == 1:
                    return
                self._tag2slot.pop(tag, None)
            else:
                rc = -110  # deadline exhausted mid-retry
        self._state.pop(slot, None)
        self._resolve(slot, self._map_completion(
            rc, body, att_size, ec, etext, ctype
        ))

    def _map_completion(self, rc, body, att_size, ec, etext, ctype):
        """rc/ec → result, with EXACTLY the per-call path's semantics:
        the common shape short-circuits to bytes; everything else runs
        through _finish_native_response on a pooled controller so error
        mapping, attachment split, and decompression stay one copy."""
        if rc == 0 and not ec and not att_size and not ctype:
            return body
        ctrl = acquire_controller()
        try:
            self._channel._finish_native_response(
                ctrl, None, rc, body if body is not None else b"",
                att_size, ec, etext, ctype,
            )
            if ctrl.error_code:
                return RingFailure(ctrl.error_code, ctrl.error_text())
            rb = ctrl.__dict__.get("response_bytes")
            return rb if rb is not None else b""
        finally:
            release_controller(ctrl)

    def _take_done(self) -> List[tuple]:
        out, self._done = self._done, []
        self._done_slots.clear()
        return out

    def _resolve(self, slot: int, result) -> None:
        if slot in self._done_slots:
            self.double_resolves += 1  # must never happen
            return
        self._done_slots.add(slot)
        self._done.append((slot, result))

    def outstanding(self) -> int:
        """Slots submitted but not yet handed out by harvest()."""
        return len(self._tag2slot) + len(self._staged) + len(self._done)

    def drain(self, extra_ms: int = 2000) -> List[tuple]:
        """Flush, then harvest until every slot resolves.  The engine's
        timeout sweep delivers -110 at each call's deadline; the
        extra_ms backstop only guards against a wedged reactor — expired
        slots fail with ERPCTIMEDOUT and their tags go to the zombie set
        so a late completion cannot double-resolve."""
        self.flush()
        results = []
        deadline = None
        for st in self._state.values():
            d = st[6]
            if d is None:
                deadline = None
                break
            deadline = d if deadline is None else max(deadline, d)
        backstop = (
            _monotonic_ns() + (extra_ms + 3_600_000 if deadline is None
                               else extra_ms) * 1_000_000
            if deadline is None
            else deadline + extra_ms * 1_000_000
        )
        while True:
            results.extend(self.harvest(timeout_ms=50))
            if not self._tag2slot and not self._done:
                break
            if _monotonic_ns() > backstop:
                mux = self._channel._native_mux()
                for tag, slot in list(self._tag2slot.items()):
                    self._tag2slot.pop(tag, None)
                    with mux._ring_lock:
                        mux._ring_zombie.add(tag)
                    self._state.pop(slot, None)
                    self._resolve(slot, RingFailure(
                        errors.ERPCTIMEDOUT, "reached timeout"
                    ))
                results.extend(self.harvest(timeout_ms=0))
                break
        return results

    def counters(self) -> dict:
        """Python-side step-log counters; pair with the C side's
        mux.ring_stats() when proving the ring isn't degraded."""
        return {
            "submissions": self.submissions,
            "windows": self.windows,
            "harvest_batches": self.harvest_batches,
            "boundary_crossings": self.boundary_crossings,
            "completions": self.completions,
            "fallback_calls": self.fallback_calls,
            "retries": self.retries,
            "double_resolves": self.double_resolves,
        }


def call_many(channel, method_spec, requests, timeout_ms=None,
              controllers=None):
    """Vectorized call: N same-method requests, results in order —
    response bytes per success, :class:`RingFailure` per failure.  See
    ``Channel.call_many`` for the public contract."""
    n = len(requests)
    if controllers is not None and len(controllers) != n:
        raise ValueError("controllers must match requests 1:1")
    ring = channel._submission_ring()
    if controllers is None:
        slots = ring.submit_all(method_spec, requests, timeout_ms)
    else:
        slots = [
            ring.submit(method_spec, requests[i], timeout_ms,
                        controllers[i])
            for i in range(n)
        ]
    pos = {slot: i for i, slot in enumerate(slots)}
    results = [None] * n
    for slot, result in ring.drain():
        idx = pos.get(slot)
        if idx is not None:
            results[idx] = result
    for i in range(n):
        if results[i] is None:  # unreachable unless a slot was lost
            results[i] = RingFailure(
                errors.EINTERNAL, "ring slot never resolved"
            )
    return results


def call_many_grouped(legs, method_spec, timeout_ms=None):
    """Windowed shard fan-out: each leg is ``(ring, rows)`` with rows a
    list of ``(orig_index, request)`` routed to that leg's shard.  Every
    leg's group is staged and FLUSHED before any leg is harvested, so
    all shard sub-windows are in flight concurrently and the C boundary
    is crossed once per SHARD, not once per key (submit side; harvests
    batch per the normal completion lane).  Returns
    ``{orig_index: result}`` — response bytes or :class:`RingFailure`,
    the same per-slot contract as :func:`call_many`.

    Off the native lane a leg's ring degrades per call inside
    ``submit_all`` (byte-identical ERPC semantics via ``call_method``);
    the step log records those as fan-out fallback_calls, so a degraded
    shard path is proven by counts, never guessed from timing."""
    staged = []
    total_keys = 0
    fallback_before = 0
    for ring, rows in legs:
        fallback_before += ring.fallback_calls
        slots = ring.submit_all(
            method_spec, [req for _, req in rows], timeout_ms
        )
        ring.flush()
        staged.append((ring, rows, slots))
        total_keys += len(rows)
    results = {}
    fallback_after = 0
    for ring, rows, slots in staged:
        pos = {slot: i for i, slot in enumerate(slots)}
        seen = set()
        for slot, result in ring.drain():
            i = pos.get(slot)
            if i is not None:
                results[rows[i][0]] = result
                seen.add(i)
        for i, (orig, _) in enumerate(rows):
            if i not in seen:  # unreachable unless a slot was lost
                results[orig] = RingFailure(
                    errors.EINTERNAL, "ring slot never resolved"
                )
        fallback_after += ring.fallback_calls
    fanout_log.record(
        crossings=len(staged),
        keys=total_keys,
        fallback_calls=fallback_after - fallback_before,
    )
    return results
