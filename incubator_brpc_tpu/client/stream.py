"""Compatibility shim — the streaming subsystem grew into its own
package (incubator_brpc_tpu/streaming/); the Stream API is re-exported
here because streams are negotiated from the client Controller and
existing code imports them from this path."""

from incubator_brpc_tpu.streaming.stream import (  # noqa: F401
    Stream,
    StreamHandler,
    StreamOptions,
)
