"""Streaming RPC — ordered, flow-controlled, bidirectional streams.

Analog of reference stream.{h,cpp} (stream.h:90-130) and
stream_impl.h:30: a Stream is negotiated inside a normal RPC (the id
rides RpcMeta.stream_settings), then DATA frames flow on the host
connection with consumed-bytes feedback flow control
(min_buf_size/max_buf_size, stream.h:50-67): the writer blocks in
``write`` when the remote's unconsumed backlog would exceed
max_buf_size, exactly the reference's StreamWait semantics.

Usage (mirrors StreamCreate/StreamAccept/StreamWrite/StreamClose):
    client:  stream = Stream.create(ctrl, handler, opts)
             stub.Method(ctrl, req)           # negotiates the stream
             stream.write(IOBuf(b"chunk"))
    server:  stream = Stream.accept(ctrl, handler, opts)  # in handler
             done()                           # response carries settings
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import streaming as wire
from incubator_brpc_tpu.protos import rpc_meta_pb2 as pb
from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

_stream_id_seq = itertools.count(1)


class StreamHandler:
    """Analog of brpc::StreamInputHandler."""

    def on_received_messages(self, stream: "Stream", messages: List[IOBuf]):
        pass

    def on_closed(self, stream: "Stream"):
        pass

    def on_failed(self, stream: "Stream", error_code: int, error_text: str):
        pass


@dataclass
class StreamOptions:
    max_buf_size: int = 2 << 20  # writer blocks past this unconsumed backlog
    handler: Optional[StreamHandler] = None


class Stream:
    def __init__(self, options: StreamOptions, is_server: bool):
        self.stream_id = next(_stream_id_seq)
        self.options = options
        self.is_server = is_server
        self.remote_stream_id = 0
        self._sock = None
        self._established = threading.Event()
        self._closed = False
        self._failed = (0, "")
        # flow control (consumed feedback, stream.h:50-67)
        self._unconsumed = 0
        self._flow_cond = threading.Condition()
        # ordered delivery through an execution queue (stream.cpp uses
        # bthread::ExecutionQueue for exactly this)
        self._rx = ExecutionQueue(self._consume_batch)

    # ---- negotiation --------------------------------------------------------
    @classmethod
    def create(cls, controller, handler: StreamHandler, options=None) -> "Stream":
        """Client side, BEFORE issuing the RPC (StreamCreate, stream.h:90)."""
        opts = options or StreamOptions()
        opts.handler = handler or opts.handler
        stream = cls(opts, is_server=False)
        controller._request_stream = stream
        return stream

    @classmethod
    def accept(cls, controller, handler: StreamHandler, options=None) -> "Stream":
        """Server side, inside the method handler (StreamAccept, stream.h:97)."""
        opts = options or StreamOptions()
        opts.handler = handler or opts.handler
        stream = cls(opts, is_server=True)
        controller._response_stream = stream
        req_settings = controller._remote_stream_settings
        if req_settings is not None:
            stream.establish(controller._server_socket, req_settings.stream_id)
        return stream

    def fill_settings(self) -> pb.StreamSettings:
        ss = pb.StreamSettings()
        ss.stream_id = self.stream_id
        ss.need_feedback = True
        ss.max_buf_size = self.options.max_buf_size
        return ss

    def establish(self, sock, remote_stream_id: int):
        """Wire the stream onto the connection once the peer's id is
        known (client: response meta arrived; server: request meta)."""
        self._sock = sock
        self.remote_stream_id = remote_stream_id
        sock.stream_map[self.stream_id] = self
        self._established.set()

    def wait_established(self, timeout: float = 5.0) -> bool:
        return self._established.wait(timeout)

    # ---- writing (StreamWrite + StreamWait flow control) --------------------
    def write(self, data, timeout: Optional[float] = 10.0) -> int:
        if isinstance(data, (bytes, str)):
            data = IOBuf(data)
        if self._closed or self._failed[0]:
            return self._failed[0] or errors.ECLOSE
        if not self._established.wait(timeout or 10.0):
            return errors.ERPCTIMEDOUT
        size = len(data)
        with self._flow_cond:
            ok = self._flow_cond.wait_for(
                lambda: self._closed
                or self._failed[0]
                or self._unconsumed + size <= self.options.max_buf_size,
                timeout,
            )
            if not ok:
                return errors.ERPCTIMEDOUT  # reference EAGAIN after StreamWait
            if self._closed or self._failed[0]:
                return self._failed[0] or errors.ECLOSE
            self._unconsumed += size
        frame = wire.pack_frame(self.remote_stream_id, wire.FRAME_DATA, data)
        rc = self._sock.write(frame)
        return rc

    # ---- receiving ----------------------------------------------------------
    def on_frame(self, frame: wire.StreamFrame):
        if frame.frame_type == wire.FRAME_DATA:
            self._rx.execute(frame.payload)
        elif frame.frame_type == wire.FRAME_FEEDBACK:
            consumed = int.from_bytes(frame.payload.to_bytes()[:8], "big")
            with self._flow_cond:
                self._unconsumed = max(0, self._unconsumed - consumed)
                self._flow_cond.notify_all()
        elif frame.frame_type == wire.FRAME_CLOSE:
            self._mark_closed()
        elif frame.frame_type == wire.FRAME_RST:
            self._mark_failed(errors.ECLOSE, "stream reset by peer")

    def _consume_batch(self, batch):
        msgs = list(batch)
        if not msgs:
            return
        handler = self.options.handler
        if handler is not None:
            try:
                handler.on_received_messages(self, msgs)
            except Exception as e:  # noqa: BLE001
                log_error("stream handler raised: %r", e)
        # consumed-bytes feedback unblocks the remote writer
        total = sum(len(m) for m in msgs)
        if self._sock is not None and not self._sock.failed and not self._closed:
            fb = IOBuf(total.to_bytes(8, "big"))
            self._sock.write(wire.pack_frame(self.remote_stream_id, wire.FRAME_FEEDBACK, fb))

    # ---- teardown -----------------------------------------------------------
    def close(self):
        """StreamClose: notify the peer and tear down."""
        if self._closed:
            return
        if self._sock is not None and not self._sock.failed:
            self._sock.write(wire.pack_frame(self.remote_stream_id, wire.FRAME_CLOSE))
        self._mark_closed()

    def _mark_closed(self):
        if self._closed:
            return
        self._closed = True
        with self._flow_cond:
            self._flow_cond.notify_all()
        if self._sock is not None:
            self._sock.stream_map.pop(self.stream_id, None)
        handler = self.options.handler
        if handler is not None:
            # spawned, never inline: a CLOSE frame may be processed on
            # the SENDER's thread (ici inline client-port delivery), and
            # user code blocking there would wedge the sender — the
            # reference likewise runs stream callbacks on bthread
            # workers, not the IO thread (stream.cpp on_closed path)
            from incubator_brpc_tpu.runtime import scheduler

            def _notify(h=handler, s=self):
                try:
                    h.on_closed(s)
                except Exception as e:  # noqa: BLE001
                    log_error("stream on_closed raised: %r", e)

            scheduler.spawn(_notify)

    def _mark_failed(self, code: int, text: str):
        self._failed = (code, text)
        with self._flow_cond:
            self._flow_cond.notify_all()
        handler = self.options.handler
        if handler is not None:
            # spawned for the same reason as on_closed above
            from incubator_brpc_tpu.runtime import scheduler

            def _notify(h=handler, s=self):
                try:
                    h.on_failed(s, code, text)
                except Exception:  # noqa: BLE001
                    pass

            scheduler.spawn(_notify)
        self._mark_closed()

    def on_socket_failed(self, code: int, text: str):
        """Called by Socket.set_failed for attached streams."""
        self._mark_failed(code, text)

    @property
    def closed(self) -> bool:
        return self._closed
