"""Channel — the client entry point.

Analog of reference brpc::Channel (channel.{h,cpp}): ``init`` takes a
single server address or a naming URL + load balancer name
(channel.h:160-183); ``call_method`` drives the RPC through the
Controller (CallMethod, channel.cpp:407-584). ChannelOptions mirrors
channel.h:41-140.
"""

from __future__ import annotations

import threading
from time import monotonic_ns as _monotonic_ns
from dataclasses import dataclass, field, replace
from typing import Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.global_init import global_init
from incubator_brpc_tpu.metrics.latency_recorder import LatencyRecorder
from incubator_brpc_tpu.protocols import find_protocol
from incubator_brpc_tpu.protocols.compress import COMPRESS_TYPE_NONE
from incubator_brpc_tpu.transport.input_messenger import InputMessenger
from incubator_brpc_tpu.transport.socket_map import acquire_socket, get_socket_map
from incubator_brpc_tpu.utils.endpoint import EndPoint, str2endpoint
from incubator_brpc_tpu.utils.logging import log_error

@dataclass
class ChannelOptions:
    """Mirrors reference ChannelOptions (channel.h:41-140)."""

    connect_timeout_ms: int = 1000
    timeout_ms: int = 1000
    backup_request_ms: int = -1
    max_retry: int = 3
    protocol: str = "tpu_std"
    # "" = adaptive (http→pooled, else single); or single | pooled |
    # short | native (tpu_std over the C++ engine's pooled connections:
    # the whole round trip runs with the GIL released, native/engine.cpp)
    connection_type: str = ""
    connection_group: str = ""
    request_compress_type: int = COMPRESS_TYPE_NONE
    retry_policy: object = None
    ns_filter: object = None
    auth: object = None
    enable_circuit_breaker: bool = False
    # jax device owning this channel's ICI client port HBM. None (default):
    # responses move by reference with no forced placement hop. Set it and
    # inbound device segments are placed onto (and, same-chip, transmitted
    # through HBM to) that device — the full two-hop data plane.
    ici_device: object = None
    # TLS: a transport/ssl_helper.ChannelSSLOptions enables SSL on every
    # connection this channel opens (reference ChannelOptions.mutable_ssl_options,
    # channel.h; handshake in transport/socket.py Socket.connect)
    ssl_options: object = None


class Channel:
    def __init__(self, options: Optional[ChannelOptions] = None):
        # copy: init() resolves adaptive fields (connection_type) in
        # place, and mutating a caller-owned options object would leak
        # the resolution into other channels built from it
        self.options = replace(options) if options is not None else ChannelOptions()
        self.protocol = None
        self._endpoint: Optional[EndPoint] = None
        self._lb = None  # LoadBalancerWithNaming when cluster-init'ed
        self._messenger = InputMessenger()
        self._latency = None
        self._latency_lock = threading.Lock()
        self._init_done = False
        self._native_fast = False  # set by single-server init()
        self._ici_client_port = None
        self._native_mux_obj = None
        self._nf_call = None  # cached sync-call entry (ext or ctypes)
        self._native_stats_snap = (0, 0)  # (ok, latency_us_sum) harvested
        self._ssl_ctx = None  # built once from options.ssl_options
        self._ring_obj = None  # channel-cached SubmissionRing (call_many)
        self._ring_lock = threading.Lock()  # serializes call_many windows

    # ---- init (channel.h:160-183) ------------------------------------------
    def init(self, naming_url: str, lb_name: Optional[str] = None) -> int:
        """init("ip:port") for a single server, or
        init("file://path" | "list://a:1,b:2" | "ici://...", "rr") for a
        cluster behind a naming service + load balancer."""
        global_init()
        self.protocol = find_protocol(self.options.protocol)
        if self.protocol is None:
            log_error("unknown protocol %r", self.options.protocol)
            return errors.EREQUEST
        self._resolve_connection_type()
        # single-endpoint forms: host:port, unix:path, ici://slice/chip
        # (an ici:// URL names ONE chip; a cluster needs lb_name + a
        # naming service URL like file:// list:// tpu://)
        if lb_name is None and (
            "://" not in naming_url
            or naming_url.startswith("ici://")
            or naming_url.startswith("unix:")
        ):
            try:
                self._endpoint = str2endpoint(naming_url)
            except ValueError as e:
                log_error("bad address %r: %r", naming_url, e)
                return errors.EREQUEST
            self._compute_native_fast()
            self._init_done = True
            return 0
        # cluster path
        try:
            from incubator_brpc_tpu.client.lb_with_naming import (
                LoadBalancerWithNaming,
            )
        except ImportError as e:
            log_error("cluster channel support unavailable: %r", e)
            return errors.EINTERNAL

        lb = LoadBalancerWithNaming()
        rc = lb.init(naming_url, lb_name or "rr", self.options.ns_filter)
        if rc != 0:
            return rc
        self._lb = lb
        self._init_done = True
        return 0

    def init_single(self, endpoint: EndPoint) -> int:
        global_init()
        self.protocol = find_protocol(self.options.protocol)
        self._resolve_connection_type()
        self._endpoint = endpoint
        self._compute_native_fast()
        self._init_done = True
        return 0

    def _compute_native_fast(self) -> None:
        """Precompute the per-channel half of the native-path gate (the
        per-controller half stays in call_method — this runs once per
        channel, call_method once per RPC)."""
        ep = self._endpoint
        self._native_fast = (
            self.options.connection_type == "native"
            and ep is not None
            and ep.scheme in ("tcp", "uds")
            and self.options.backup_request_ms < 0
            and not self.options.request_compress_type
        )

    def _resolve_connection_type(self):
        """Adaptive connection type (reference adaptive_connection_type):
        correlation-less HTTP/1 defaults to pooled — FIFO matching is
        only safe with one outstanding request per connection."""
        ct = self.options.connection_type
        if ct == "native":
            from incubator_brpc_tpu import native

            # auth (credential packing) and custom retry policies live in
            # the Python call path — silently dropping them would be
            # worse than the speed win, so those channels degrade to
            # pooled (same one-in-flight-per-connection discipline)
            if (
                self.options.protocol != "tpu_std"
                or self.options.auth is not None
                or self.options.retry_policy is not None
                or self.options.ssl_options is not None
                or not native.available()
            ):
                log_error(
                    "connection_type=native needs tpu_std, no auth, no "
                    "custom retry_policy, no TLS, and the C++ engine "
                    "(%s); using pooled",
                    native.unavailable_reason() or "ok",
                )
                self.options.connection_type = "pooled"
            return
        if ct not in ("single", "pooled", "short", ""):
            log_error("unknown connection_type %r, using single", ct)
            self.options.connection_type = "single"
        elif not ct:
            self.options.connection_type = (
                "pooled" if self.options.protocol == "http" else "single"
            )

    # ---- the RPC entry (CallMethod, channel.cpp:407) -----------------------
    def call_method(self, method_spec, controller, request, response, done=None):
        """Drive one RPC.  The sync native fast path is FUSED into this
        method: a sync RPC over the C++ mux reactor parks the calling
        thread in C on a per-call waiter with the GIL released
        (engine.cpp nc_mux_call), so N sync callers share a connection
        and their submissions batch into single writes.  Pack, round
        trip, and meta parse all happen in C; Python touches only the
        user payload.  Every Python operation here is paid 100k+ times
        a second, which is why the common shape (transport ok, no app
        error, plain payload) completes inline with no further calls:
        retry/deadline machinery and the generic response tail live in
        _call_native_slow and only run when something actually went
        wrong (or the response carries an attachment / compression).

        Per-call recorder work is zero — the C reactor keeps sync-call
        atomics (engine.cpp nc_mux_stats) that the LatencyRecorder
        pulls lazily (_pull_native_stats); native channels are
        single-endpoint, so there is no LB feedback either.

        The native gate runs first: _native_fast is only ever True
        after a successful init, so the uninitialized check below still
        catches every broken channel.  The immutable half of
        eligibility (connection_type, endpoint scheme, engine
        availability) is precomputed at init; per-controller bits and
        the mutable options are re-checked per call."""
        if self._native_fast:
            opts = self.options
            if (
                controller._request_stream is None
                and not controller.request_compress_type
                and not opts.request_compress_type
                and opts.backup_request_ms < 0
                # tenant identity rides RpcRequestMeta.tenant, which
                # the C mux does not pack: a tenant-tagged call must
                # take the Python path or the server would admit it as
                # the default tier, silently bypassing its quota
                and not controller.__dict__.get("tenant")
            ):
                if done is not None:
                    return self._call_native_async(
                        method_spec, controller, request, response, done
                    )
                fc = self._nf_call
                if fc is None:
                    fc = self._native_fastcall()
                    if fc is None:
                        controller.set_failed(
                            errors.EINTERNAL, "native mux unavailable"
                        )
                        return
                # bytes request = already-serialized payload (pack
                # echo-style requests ONCE, outside the call loop — no
                # per-call protobuf churn; see docs/fastpath.md)
                payload = (
                    request
                    if type(request) is bytes
                    else request.SerializeToString()
                )
                att_buf = controller.__dict__.get("request_attachment")
                att = (
                    att_buf.to_bytes()
                    if att_buf is not None and len(att_buf)
                    else b""
                )
                timeout_ms = controller.timeout_ms
                if timeout_ms is None:
                    timeout_ms = opts.timeout_ms
                key = method_spec.__dict__.get("_native_key")
                if key is None:
                    key = (
                        method_spec.service_name.encode(),
                        method_spec.method_name.encode(),
                    )
                    method_spec._native_key = key
                t0 = _monotonic_ns()
                r = fc(
                    key[0], key[1], payload, att,
                    timeout_ms if timeout_ms and timeout_ms > 0 else -1,
                    controller.log_id,
                )
                # mux_call_fast returns the body bytes directly for the
                # common shape; the ctypes fallback (and every non-plain
                # outcome) returns the 6-tuple
                if type(r) is bytes:
                    controller.latency_us = (_monotonic_ns() - t0) // 1000
                    if response is not None:
                        try:
                            response.ParseFromString(r)
                        except Exception as e:  # noqa: BLE001
                            controller.set_failed(
                                errors.ERESPONSE,
                                f"parse response failed: {e}",
                            )
                    else:
                        controller.response_bytes = r
                    return
                rc, body, att_size, ec, etext, ctype = r
                if rc == 0 and not ec and not att_size and not ctype:
                    controller.latency_us = (_monotonic_ns() - t0) // 1000
                    if response is not None:
                        try:
                            response.ParseFromString(body)
                        except Exception as e:  # noqa: BLE001
                            controller.set_failed(
                                errors.ERESPONSE,
                                f"parse response failed: {e}",
                            )
                    else:
                        controller.response_bytes = body
                    return
                return self._call_native_slow(
                    controller, response, rc, body, att_size, ec, etext,
                    ctype, t0, timeout_ms, payload, att, key, fc,
                )
        if not self._init_done:
            controller.set_failed(errors.EINTERNAL, "channel not initialized")
            if done:
                done()
            return
        controller._start_call(self, method_spec, request, response, done)
        if done is None:
            controller.join()

    def _call_native_slow(
        self, controller, response, rc, body, att_size, ec, etext, ctype,
        t0, timeout_ms, payload, att, key, fc,
    ):
        """Off the inline fast path: transport-level errors retry (the
        reactor reconnects under us) on a GLOBAL deadline — attempts
        share the remaining budget, like the Python path's single
        overall timer — then the generic response tail runs."""
        max_retry = controller.max_retry
        if max_retry is None:
            max_retry = self.options.max_retry
        deadline_ns = (
            t0 + timeout_ms * 1_000_000
            if timeout_ms and timeout_ms > 0
            else None
        )
        attempt = 1
        while rc not in (0, -110) and attempt <= max(0, max_retry):
            if deadline_ns is None:
                per_call_ms = -1
            else:
                remaining_ms = (deadline_ns - _monotonic_ns()) // 1_000_000
                if remaining_ms <= 0:
                    rc = -110  # deadline exhausted mid-retry
                    break
                per_call_ms = max(1, int(remaining_ms))
            controller.retry_count = attempt
            r = fc(
                key[0], key[1], payload, att, per_call_ms, controller.log_id
            )
            if type(r) is bytes:  # mux_call_fast common-shape contract
                rc, body, att_size, ec, etext, ctype = 0, r, 0, 0, None, 0
            else:
                rc, body, att_size, ec, etext, ctype = r
            attempt += 1
        controller.latency_us = (_monotonic_ns() - t0) // 1000
        self._finish_native_response(
            controller, response, rc, body, att_size, ec, etext, ctype
        )

    def _finish_native_response(
        self, controller, response, rc, body, att_size, ec, etext, ctype
    ):
        """Shared completion tail for the sync and async native paths:
        rc→error mapping, attachment split, decompression, parse."""
        if rc == -110:
            controller.set_failed(errors.ERPCTIMEDOUT, "reached timeout")
            return
        if rc != 0:
            controller.set_failed(
                errors.EFAILEDSOCKET, f"native transport error rc={rc}"
            )
            return
        if ec:
            controller.set_failed(ec, etext or "")
            return
        if response is None and not ctype and not att_size:
            # bytes mode, plain payload: the caller gets the raw
            # response bytes and parses (or not) on its own schedule.
            # Compressed or attachment-bearing responses fall through
            # to the generic tail below — one copy of that logic.
            controller.response_bytes = body
            return
        if not att_size and not ctype:
            # plain-response fast path (the overwhelmingly common shape):
            # parse straight into the user message, nothing else to do
            try:
                response.ParseFromString(body)
            except Exception as e:  # noqa: BLE001
                controller.set_failed(
                    errors.ERESPONSE, f"parse response failed: {e}"
                )
            return
        from incubator_brpc_tpu.utils.iobuf import IOBuf

        msg_end = len(body) - att_size  # att_size validated <= body in C
        if att_size:
            controller.response_attachment = IOBuf(body[msg_end:])
        msg_bytes = body[:msg_end]
        if ctype:
            from incubator_brpc_tpu.protocols import compress as compress_mod

            buf = compress_mod.decompress(IOBuf(msg_bytes), ctype)
            if buf is None:
                controller.set_failed(
                    errors.ERESPONSE, f"unsupported compress type {ctype}"
                )
                return
            msg_bytes = buf.to_bytes()
        if response is None:
            controller.response_bytes = msg_bytes
            return
        try:
            response.ParseFromString(msg_bytes)
        except Exception as e:  # noqa: BLE001
            controller.set_failed(
                errors.ERESPONSE, f"parse response failed: {e}"
            )

    def _call_native_async(self, method_spec, controller, request, response, done):
        """Async RPC over the C++ mux reactor: submissions batch into
        single writes, completions harvest in batches — the pipelined
        path that amortizes per-RPC syscalls (done runs on the
        harvester thread, like reference done on a bthread worker).
        Closure-free: per-call state rides one context tuple dispatched
        to the stable bound method _native_async_complete, keeping the
        per-call GIL-held cost a few microseconds (the whole user call
        budget on one core is ~7us).  Transport errors retry on the
        shared global deadline, matching the sync native path."""
        mux = self._native_mux()
        if mux is None:
            controller.set_failed(errors.EINTERNAL, "native mux unavailable")
            done()
            return
        payload = (
            request if type(request) is bytes else request.SerializeToString()
        )
        att_buf = controller.__dict__.get("request_attachment")
        att = att_buf.to_bytes() if att_buf is not None and len(att_buf) else b""
        timeout_ms = (
            controller.timeout_ms
            if controller.timeout_ms is not None
            else self.options.timeout_ms
        )
        max_retry = (
            controller.max_retry
            if controller.max_retry is not None
            else self.options.max_retry
        )
        key = getattr(method_spec, "_native_key", None)
        if key is None:
            key = (
                method_spec.service_name.encode(),
                method_spec.method_name.encode(),
            )
            method_spec._native_key = key
        t0 = _monotonic_ns()
        deadline_ns = (
            t0 + timeout_ms * 1_000_000 if timeout_ms and timeout_ms > 0 else None
        )
        ctx = [
            controller, response, done, t0, deadline_ns,
            max(0, max_retry), key, payload, att, mux,
        ]
        if not self._native_async_submit(ctx, -1 if timeout_ms is None or timeout_ms <= 0 else timeout_ms):
            controller.set_failed(errors.EINTERNAL, "native mux unavailable")
            done()

    def _native_async_submit(self, ctx, per_call_ms) -> bool:
        mux = ctx[9]
        key = ctx[6]
        return mux.submit_ctx(
            key[0], key[1], ctx[7], ctx[8], per_call_ms,
            ctx[0].log_id, self._native_async_complete, ctx,
        )

    def _native_async_complete(self, ctx, rc, body, att_size, ec, etext, ctype):
        """Runs on the mux harvester thread, once per completion."""
        controller = ctx[0]
        response = ctx[1]
        done = ctx[2]
        t0 = ctx[3]
        deadline_ns = ctx[4]
        retries_left = ctx[5]
        if rc not in (0, -110) and retries_left > 0:
            # transport error: retry within the remaining global budget.
            # A computed remaining <= 0 must NOT collapse into the -1
            # "no deadline" sentinel (an expired call would resubmit
            # with an infinite timeout and hang past its deadline).
            ctx[5] = retries_left - 1
            controller.retry_count += 1
            if deadline_ns is None:
                if self._native_async_submit(ctx, -1):
                    return
            else:
                remaining = (deadline_ns - _monotonic_ns()) // 1_000_000
                if remaining > 0 and self._native_async_submit(
                    ctx, int(remaining)
                ):
                    return
                rc = -110
        controller.latency_us = (_monotonic_ns() - t0) // 1000
        self._finish_native_response(
            controller, response, rc, body if body is not None else b"",
            att_size, ec, etext, ctype,
        )
        self._on_rpc_end(controller)
        done()

    # ---- vectorized calls (submission/completion ring) ---------------------
    def call_many(self, method_spec, requests, timeout_ms=None,
                  controllers=None):
        """Vectorized RPC: N same-method requests cross the Python↔C
        boundary as a WINDOW (one mux_submit_many) and complete in
        harvest bursts — io_uring's amortization applied to the per-call
        crossing that caps the sync fast path (client/ring.py has the
        full contract).  Returns results IN ORDER: response bytes per
        success, a ring.RingFailure(error_code, error_text) per failure
        — the same ERPC codes the per-call path would set.

        ``controllers``, when given, is a parallel list; a non-None
        entry makes THAT call degrade to ``call_method`` with that
        controller (tenant-tagged calls keep the PR 8 quota rule; any
        per-call override — attachment, compression, stream — keeps its
        exact old semantics).  Non-native channels (including fan-out /
        combo subclasses, which inherit this method) degrade entirely:
        every call runs through ``call_method`` with a pooled,
        wiped-on-recycle controller — byte-for-byte the old path."""
        from incubator_brpc_tpu.client import ring as _ring

        with self._ring_lock:
            return _ring.call_many(
                self, method_spec, requests, timeout_ms, controllers
            )

    def submission_ring(self, depth: int = 128):
        """A caller-owned SubmissionRing for pipelined use — the async
        ``submit()/harvest()`` pair (stage calls as they arrive, harvest
        completions in bursts, overlap with application work).  Each
        ring belongs to one thread; ``call_many`` uses a separate
        channel-internal ring and does not contend with these."""
        from incubator_brpc_tpu.client.ring import SubmissionRing

        return SubmissionRing(self, depth)

    def _submission_ring(self):
        """The channel-cached ring backing call_many (callers hold
        _ring_lock)."""
        if self._ring_obj is None:
            from incubator_brpc_tpu.client.ring import SubmissionRing

            self._ring_obj = SubmissionRing(self)
        return self._ring_obj

    def _native_fastcall(self):
        """Resolve + cache the sync-call entry point: the CPython
        extension's mux_call pre-bound to the reactor handle when the
        extension built, else the ctypes call_blocking wrapper."""
        mux = self._native_mux()
        if mux is None:
            return None
        self._nf_call = mux.fast_call_entry()
        return self._nf_call

    def _native_mux(self):
        if self._native_mux_obj is None:
            with self._latency_lock:
                if self._native_mux_obj is None:
                    import socket as _pysock

                    from incubator_brpc_tpu import native

                    try:
                        # UDS: the engine treats a '/'-prefixed host as a
                        # unix-domain path (port ignored)
                        if self._endpoint.scheme == "uds":
                            host, port = self._endpoint.host, 0
                        else:
                            host = _pysock.gethostbyname(self._endpoint.host)
                            port = self._endpoint.port
                        # one conn per channel: the best-measured shape
                        # on the bench curve, and it maps one channel to
                        # one engine worker like the pooled path did
                        self._native_mux_obj = native.NativeMuxClient(
                            host, port, nconns=1
                        )
                    except OSError as e:
                        log_error("native mux init failed: %r", e)
        return self._native_mux_obj


    # ---- socket selection (Controller::IssueRPC hooks) ---------------------
    def _select_socket(self, controller):
        """Returns (err, sid, server_node). Single-server channels share
        the connection via SocketMap; cluster channels ask the LB."""
        if self._lb is not None:
            return self._lb.select_server(controller, self._messenger)
        if self._endpoint.is_ici():
            sid = self._ici_port().connect(self._endpoint.coords)
            if sid is None:
                return errors.EFAILEDSOCKET, 0, None
            return 0, sid, None
        err, sid = acquire_socket(
            self._endpoint,
            self._messenger,
            self._signature(),
            self.options.connection_type,
            self.options.connect_timeout_ms / 1000.0,
            controller,
            ssl_params=self._ssl_params(),
        )
        return err, sid, None

    def _ici_port(self):
        if self._ici_client_port is None:
            with self._latency_lock:  # double-checked: one port per channel
                if self._ici_client_port is None:
                    from incubator_brpc_tpu.parallel.ici import acquire_client_port

                    # default device=None: responses move by reference, no
                    # forced placement hop; options.ici_device opts into
                    # device-owned delivery (see ChannelOptions)
                    self._ici_client_port = acquire_client_port(
                        device=self.options.ici_device
                    )
        return self._ici_client_port

    def close(self):
        """Release channel resources: the client ICI port, the native
        mux client, and the LB/naming watcher chain, if any."""
        mux = self._native_mux_obj
        if mux is not None:
            self._native_mux_obj = None
            self._nf_call = None
            self._ring_obj = None  # its tags die with the mux
            mux.destroy()
        port = self._ici_client_port
        if port is not None:
            from incubator_brpc_tpu.parallel.ici import get_fabric

            self._ici_client_port = None
            get_fabric().unregister(port.coords)
        if self._lb is not None:
            lb, self._lb = self._lb, None
            self._init_done = False
            lb.close()

    def _signature(self) -> str:
        # the ssl marker keeps TLS and plaintext channels — and channels
        # with DIFFERENT TLS configs (verification, client certs) — from
        # sharing a connection (reference hashes the full
        # ChannelSSLOptions into the SocketMapKey's ChannelSignature)
        ssl_mark = ""
        if self.options.ssl_options is not None:
            import hashlib

            ssl_mark = (
                ":ssl:"
                + hashlib.md5(
                    repr(self.options.ssl_options).encode()
                ).hexdigest()[:10]
            )
        return f"{self.options.protocol}:{self.options.connection_group}{ssl_mark}"

    def _ssl_params(self):
        """(SSLContext, sni_hostname) or None; context built once."""
        opts = self.options.ssl_options
        if opts is None:
            return None
        if self._ssl_ctx is None:
            with self._latency_lock:
                if self._ssl_ctx is None:
                    from incubator_brpc_tpu.transport.ssl_helper import (
                        make_client_context,
                    )

                    self._ssl_ctx = make_client_context(opts)
        return (self._ssl_ctx, opts.sni_name)

    def _on_rpc_end(self, controller):
        """Per-RPC bookkeeping: latency recorder + LB feedback
        (reference Controller::Call::OnComplete).  Batched recording:
        the ~1.5us per-call recorder write would cap aggregate qps on
        its own; observations fold in at the 1 Hz sampler tick."""
        rec = self._latency or self._latency_recorder()
        if not controller.error_code:
            rec.update_batched(controller.latency_us)
        if self._lb is not None:
            self._lb.feedback(controller)

    def _pull_native_stats(self):
        """Lazy harvest of the C mux client's sync-call atomics into the
        LatencyRecorder (called from the recorder before reads and at
        sampler ticks — the sync fast path itself records NOTHING in
        Python).  Counts fold via update_bulk, so percentiles over
        native sync traffic read as the interval mean (bulk_folded)."""
        mux = self._native_mux_obj
        rec = self._latency
        if mux is None or rec is None:
            return
        s = mux.stats()
        last = self._native_stats_snap
        dn = s["ok"] - last[0]
        if dn > 0:
            dsum = s["latency_us_sum"] - last[1]
            self._native_stats_snap = (s["ok"], s["latency_us_sum"])
            rec.update_bulk(dsum // dn, dn)
        if s["latency_us_max"]:
            rec.note_max(s["latency_us_max"])

    def _latency_recorder(self) -> LatencyRecorder:
        if self._latency is None:
            with self._latency_lock:
                if self._latency is None:
                    rec = LatencyRecorder()
                    if self._native_fast:
                        rec.set_pull_source(self._pull_native_stats)
                    self._latency = rec
        return self._latency

    def latency_recorder(self) -> LatencyRecorder:
        return self._latency_recorder()
