"""RetryPolicy (analog of reference retry_policy.{h,cpp}).

DoRetry decides which error codes are retriable; the default mirrors
the reference's DefaultRetryPolicy: connection-level failures retry,
logical/server errors don't. Retries reuse the versioned CallId so
stale responses of dead attempts are dropped (controller.cpp:996-1004).

``backoff_ms`` extends the reference contract (newer brpc's
RetryPolicy::GetBackoffTimeMs): the Controller waits that long before
reissuing a retriable attempt.  RetryPolicyWithBackoff implements
seeded exponential backoff with deterministic jitter — the jitter for
retry k is a pure function of (seed, k), so a replayed run produces
the identical attempt-time spacing (the chaos harness asserts it).
"""

from __future__ import annotations

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.utils.hashes import GOLDEN64 as _GOLDEN
from incubator_brpc_tpu.utils.hashes import fmix64 as _mix64

#: codes worth reissuing.  EOVERCROWDED ("this server is overloaded,
#: retry elsewhere" — docs/overload.md code mapping) is retriable ONLY
#: against a different replica: reissuing it at the same saturated
#: server adds load exactly where there is none to give.  ELIMIT is
#: deliberately absent — it now means "the request expired while
#: queued" (batcher deadline shed): a drop, retrying is wasted work.
_RETRIABLE = (
    errors.EFAILEDSOCKET,
    errors.ECLOSE,
    errors.EOVERCROWDED,
    errors.ELOGOFF,
)


class RetryPolicy:
    def do_retry(self, controller) -> bool:
        code = controller.error_code
        if code not in _RETRIABLE:
            return False
        if (
            code == errors.EOVERCROWDED
            # only SERVER-returned sheds demand a different replica; a
            # locally-generated EOVERCROWDED (the client's own write
            # queue past its unsent-bytes cap) is transient — a
            # backed-off retry on the same connection drains it, and
            # failing fast there would regress every single-server
            # caller hitting momentary backpressure
            and controller.__dict__.get("_error_from_server")
            and not controller.has_unexcluded_replica()
        ):
            # no OTHER replica to try: hammering the overloaded server
            # again is worse than failing fast (the caller's own
            # backpressure is the right response)
            return False
        return True

    def backoff_ms(self, controller) -> float:
        """Delay before the next attempt; 0 = reissue immediately
        (the historical behavior, kept as the default)."""
        return 0.0


class RetryPolicyWithBackoff(RetryPolicy):
    """Exponential backoff with seeded, deterministic jitter.

    Retry k (1-based) sleeps ``min(base_ms * multiplier**(k-1),
    max_ms)`` scaled by a jitter factor in ``[1 - jitter, 1]`` drawn
    from fmix64(seed, k).  Pure function of (seed, k): call
    :meth:`expected_backoffs` to precompute the exact schedule a
    replay will follow.

    ``no_backoff_remaining_ms``: when the RPC's remaining deadline
    budget is below this, skip the sleep — burning the last slice of
    budget waiting guarantees a timeout (reference
    DefaultRetryPolicy-with-backoff has the same guard).
    """

    def __init__(
        self,
        base_ms: float = 4.0,
        max_ms: float = 1000.0,
        multiplier: float = 2.0,
        jitter: float = 0.25,
        seed: int = 0,
        no_backoff_remaining_ms: float = 0.0,
    ):
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.multiplier = float(multiplier)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.seed = int(seed)
        self.no_backoff_remaining_ms = float(no_backoff_remaining_ms)

    def backoff_for(self, k: int) -> float:
        """The exact backoff (ms) before retry ``k`` (1-based)."""
        if k < 1:
            return 0.0
        raw = min(self.base_ms * self.multiplier ** (k - 1), self.max_ms)
        if self.jitter:
            u = _mix64(self.seed + k * _GOLDEN) / 2.0**64
            raw *= 1.0 - self.jitter * u
        return raw

    def expected_backoffs(self, n: int) -> list:
        """[backoff before retry 1, ..., before retry n] — the replay
        schedule the chaos harness compares attempt spacing against."""
        return [self.backoff_for(k) for k in range(1, n + 1)]

    #: slice of deadline budget a capped backoff always leaves for the
    #: reissued attempt itself
    DEADLINE_MARGIN_MS = 10.0

    def backoff_ms(self, controller) -> float:
        delay = self.backoff_for(controller.retry_count)
        remaining = controller.remaining_ms()
        if remaining is not None:
            if (
                self.no_backoff_remaining_ms > 0
                and remaining < self.no_backoff_remaining_ms
            ):
                return 0.0
            # never sleep past the overall deadline: an uncapped
            # backoff would convert every late retriable error into a
            # guaranteed ERPCTIMEDOUT, silently voiding the retry
            # budget (the scheduled delay may therefore undershoot
            # expected_backoffs near the deadline)
            delay = min(delay, max(0.0, remaining - self.DEADLINE_MARGIN_MS))
        return delay


_default = RetryPolicy()


def default_retry_policy() -> RetryPolicy:
    return _default
