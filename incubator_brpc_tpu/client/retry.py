"""RetryPolicy (analog of reference retry_policy.{h,cpp}).

DoRetry decides which error codes are retriable; the default mirrors
the reference's DefaultRetryPolicy: connection-level failures retry,
logical/server errors don't. Retries reuse the versioned CallId so
stale responses of dead attempts are dropped (controller.cpp:996-1004).
"""

from __future__ import annotations

from incubator_brpc_tpu import errors


class RetryPolicy:
    def do_retry(self, controller) -> bool:
        return controller.error_code in (
            errors.EFAILEDSOCKET,
            errors.ECLOSE,
            errors.EOVERCROWDED,
            errors.ELOGOFF,
            errors.ELIMIT,
        )


_default = RetryPolicy()


def default_retry_policy() -> RetryPolicy:
    return _default
