"""Load balancers — lock-free-read server selection.

Analog of reference LoadBalancer (load_balancer.h:40-105) and the
policy/ implementations (global.cpp:141-149). Every implementation
keeps its server set in a DoublyBufferedData snapshot so the hot
``select_server`` path is a read with no lock — the structural property
the reference gets from butil::DoublyBufferedData
(doubly_buffered_data.h:37-51).

Implemented: rr, wrr, random, wr (weighted random), c_murmurhash
(consistent hashing with a murmur3 ketama-style ring,
consistent_hashing_load_balancer.cpp), la (locality-aware:
latency×inflight weighted, locality_aware_load_balancer.{h,cpp},
doc docs/cn/lalb.md).
"""

from __future__ import annotations

import bisect
import itertools
import threading
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu.client.naming_service import ServerNode
from incubator_brpc_tpu.utils.containers import DoublyBufferedData
from incubator_brpc_tpu.utils.hashes import fast_rand_less_than, murmur3_32


@dataclass
class SelectIn:
    """Analog of LoadBalancer::SelectIn (load_balancer.h)."""

    excluded: frozenset = frozenset()  # nodes already tried this RPC
    request_code: int = 0  # hash key for consistent hashing


class LoadBalancer:
    name = ""

    def add_server(self, node: ServerNode) -> bool:
        raise NotImplementedError

    def remove_server(self, node: ServerNode) -> bool:
        raise NotImplementedError

    def reset_servers(self, nodes: List[ServerNode]):
        snapshot = self.servers()
        for node in snapshot:
            if node not in nodes:
                self.remove_server(node)
        for node in nodes:
            if node not in snapshot:
                self.add_server(node)

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        raise NotImplementedError

    def feedback(self, node: ServerNode, latency_us: int, failed: bool):
        pass

    def servers(self) -> List[ServerNode]:
        raise NotImplementedError


class _SnapshotLB(LoadBalancer):
    """Common base: node list in a DoublyBufferedData."""

    def __init__(self):
        self._data: DoublyBufferedData = DoublyBufferedData(tuple())

    def add_server(self, node: ServerNode) -> bool:
        added = []

        def mod(cur):
            if node in cur:
                return cur
            added.append(True)
            return cur + (node,)

        self._data.modify(mod)
        return bool(added)

    def remove_server(self, node: ServerNode) -> bool:
        removed = []

        def mod(cur):
            if node not in cur:
                return cur
            removed.append(True)
            return tuple(x for x in cur if x != node)

        self._data.modify(mod)
        return bool(removed)

    def servers(self) -> List[ServerNode]:
        return list(self._data.read())

    def _candidates(self, sin: SelectIn) -> Tuple[ServerNode, ...]:
        snap = self._data.read()
        if not sin.excluded:
            return snap
        filtered = tuple(n for n in snap if n not in sin.excluded)
        return filtered or snap  # all excluded: better any than none


class RoundRobinLB(_SnapshotLB):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._counter = itertools.count()

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        cands = self._candidates(sin)
        if not cands:
            return None
        return cands[next(self._counter) % len(cands)]


class WeightedRoundRobinLB(_SnapshotLB):
    name = "wrr"

    def __init__(self):
        super().__init__()
        self._counter = itertools.count()
        # weight-expanded snapshot, rebuilt only on membership change so
        # the select hot path is a single index (DoublyBufferedData read)
        self._expanded: DoublyBufferedData = DoublyBufferedData(tuple())

    def _rebuild_expanded(self):
        nodes = self._data.read()
        expanded: List[ServerNode] = []
        for n in nodes:
            expanded.extend([n] * max(1, n.weight))
        self._expanded.modify(lambda _: tuple(expanded))

    def add_server(self, node: ServerNode) -> bool:
        added = super().add_server(node)
        if added:
            self._rebuild_expanded()
        return added

    def remove_server(self, node: ServerNode) -> bool:
        removed = super().remove_server(node)
        if removed:
            self._rebuild_expanded()
        return removed

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        expanded = self._expanded.read()
        if not expanded:
            return None
        if not sin.excluded:
            return expanded[next(self._counter) % len(expanded)]
        for _ in range(len(expanded)):
            node = expanded[next(self._counter) % len(expanded)]
            if node not in sin.excluded:
                return node
        return expanded[next(self._counter) % len(expanded)]


class RandomLB(_SnapshotLB):
    name = "random"

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        cands = self._candidates(sin)
        if not cands:
            return None
        return cands[fast_rand_less_than(len(cands))]


class WeightedRandomLB(_SnapshotLB):
    name = "wr"

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        cands = self._candidates(sin)
        if not cands:
            return None
        total = sum(max(1, n.weight) for n in cands)
        r = fast_rand_less_than(total)
        acc = 0
        for n in cands:
            acc += max(1, n.weight)
            if r < acc:
                return n
        return cands[-1]


class ConsistentHashingLB(LoadBalancer):
    """Ketama-style ring with murmur3 virtual nodes
    (consistent_hashing_load_balancer.cpp; 100 replicas/node there)."""

    name = "c_murmurhash"
    REPLICAS = 100

    def __init__(self):
        self._ring: DoublyBufferedData = DoublyBufferedData(((), ()))  # (hashes, nodes)
        self._members: Dict[ServerNode, bool] = {}
        self._lock = threading.Lock()

    def _rebuild(self):
        points: List[Tuple[int, ServerNode]] = []
        for node in self._members:
            base = str(node.endpoint).encode()
            for r in range(self.REPLICAS * max(1, node.weight)):
                points.append((murmur3_32(base + b"-%d" % r), node))
        # endpoint tie-break: two nodes hashing a virtual point to the
        # same value would otherwise order by membership-insertion order
        # — clients that learned the cluster in different orders (or a
        # restarted client) would disagree on key ownership exactly at
        # collisions.  With the tie-break the ring is a pure function of
        # the member set (golden-pinned in tests).
        points.sort(key=lambda p: (p[0], str(p[1].endpoint)))
        hashes = tuple(p[0] for p in points)
        nodes = tuple(p[1] for p in points)
        self._ring.modify(lambda _: (hashes, nodes))

    def add_server(self, node: ServerNode) -> bool:
        with self._lock:
            if node in self._members:
                return False
            self._members[node] = True
            self._rebuild()
            return True

    def remove_server(self, node: ServerNode) -> bool:
        with self._lock:
            if node not in self._members:
                return False
            del self._members[node]
            self._rebuild()
            return True

    def servers(self) -> List[ServerNode]:
        return list(self._members)

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        hashes, nodes = self._ring.read()
        if not hashes:
            return None
        h = (
            sin.request_code & 0xFFFFFFFF
            if sin.request_code
            else murmur3_32(b"%d" % fast_rand_less_than(1 << 30))
        )
        idx = bisect.bisect_left(hashes, h) % len(hashes)
        # walk the ring past excluded nodes
        for step in range(len(hashes)):
            node = nodes[(idx + step) % len(hashes)]
            if node not in sin.excluded:
                return node
        return nodes[idx]


class MeshLocalityLB(ConsistentHashingLB):
    """Consistent hashing made mesh-topology-aware (the cache tier's
    router, docs/cache.md): key ownership comes from the same
    deterministic murmur3 ketama ring as ``c_murmurhash``, but the ring
    walk is re-ranked by ICI locality and shed pressure —

      0. same-ICI-neighborhood replicas (endpoint slice ==
         ``local_coords`` slice) that are not shedding,
      1. remote (DCN) replicas not shedding,
      2. anything shedding, locals first.

    Within a class, candidates keep deterministic ring order, so two
    healthy clusters route a key identically to plain consistent
    hashing restricted to the local slice.  Spill to DCN happens only
    when every local replica is excluded (breaker-isolated/dead) or
    shedding — the ISSUE's locality contract, regression-tested at
    >=90% local under healthy load.

    Shed signals arrive via ``on_shed`` (LoadBalancerWithNaming feeds
    EOVERCROWDED completions — the admission tier's retry-elsewhere
    code); each successful feedback decays the pressure so a revived
    replica re-earns local preference without wall-clock coupling."""

    name = "mesh_locality"
    SHED_TRIP = 2  # consecutive-ish sheds before we route around
    SHED_MAX = 8
    PROBE_EVERY = 4  # every Nth spilled pick probes the shedding local

    def __init__(self):
        super().__init__()
        self.local_coords: Optional[Tuple[int, int]] = None
        self._shed: Dict[ServerNode, int] = {}
        self._shed_lock = threading.Lock()
        self.picks_local = 0
        self.picks_remote = 0
        self._probe_tick = 0

    def set_local_coords(self, coords) -> None:
        """The client's own mesh coordinates (slice, chip) — typically
        ``TpuTopologyNamingService`` fabric/mesh coordinates."""
        self.local_coords = tuple(coords) if coords is not None else None

    def _is_local(self, node: ServerNode) -> bool:
        if self.local_coords is None:
            return False
        ep = node.endpoint
        if not ep.is_ici():
            return False
        return ep.coords[0] == self.local_coords[0]

    def on_shed(self, node: ServerNode) -> None:
        with self._shed_lock:
            self._shed[node] = min(self.SHED_MAX, self._shed.get(node, 0) + 1)

    def shedding(self, node: ServerNode) -> bool:
        return self._shed.get(node, 0) >= self.SHED_TRIP

    def feedback(self, node: ServerNode, latency_us: int, failed: bool):
        if not failed:
            with self._shed_lock:
                s = self._shed.get(node, 0)
                if s:
                    self._shed[node] = s - 1

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        hashes, nodes = self._ring.read()
        if not hashes:
            return None
        h = (
            sin.request_code & 0xFFFFFFFF
            if sin.request_code
            else murmur3_32(b"%d" % fast_rand_less_than(1 << 30))
        )
        idx = bisect.bisect_left(hashes, h) % len(hashes)
        best = None
        best_rank = None
        local_shed = None  # first shedding local seen, in ring order
        seen = set()
        for step in range(len(hashes)):
            node = nodes[(idx + step) % len(hashes)]
            if node in seen:
                continue
            seen.add(node)
            if node in sin.excluded:
                continue
            local = self._is_local(node)
            shed = self.shedding(node)
            if local and shed and local_shed is None:
                local_shed = node
            rank = (2 + (not local)) if shed else (0 if local else 1)
            if rank == 0:
                best = node
                break
            if best_rank is None or rank < best_rank:
                best, best_rank = node, rank
        if best is None:
            return nodes[idx]  # all excluded: better the owner than none
        if best_rank is not None and local_shed is not None:
            # circuit-breaker revival probe: a spill pick occasionally
            # re-tries the shedding local replica so its successes can
            # decay the pressure (feedback) — without this the replica
            # never gets picked again and the spill becomes permanent
            self._probe_tick += 1
            if self._probe_tick % self.PROBE_EVERY == 0:
                best = local_shed
        if self._is_local(best):
            self.picks_local += 1
        else:
            self.picks_remote += 1
        return best

    def locality_fraction(self) -> float:
        total = self.picks_local + self.picks_remote
        return self.picks_local / total if total else 0.0


class LocalityAwareLB(_SnapshotLB):
    """Latency/inflight-weighted selection (lalb): weight_i ∝
    1 / (ema_latency_i × (inflight_i + 1)); fresh nodes get the mean
    weight so they are probed (doc docs/cn/lalb.md)."""

    name = "la"

    def __init__(self):
        super().__init__()
        self._stats: Dict[ServerNode, List[float]] = {}  # [ema_lat_us, inflight]
        self._stats_lock = threading.Lock()
        self._alpha = 0.3

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        cands = self._candidates(sin)
        if not cands:
            return None
        with self._stats_lock:
            weights = []
            for n in cands:
                st = self._stats.get(n)
                if st is None or st[0] <= 0:
                    weights.append(-1.0)  # unknown: assign mean later
                else:
                    weights.append(1.0 / (st[0] * (st[1] + 1.0)))
            known = [w for w in weights if w > 0]
            mean = sum(known) / len(known) if known else 1.0
            weights = [w if w > 0 else mean for w in weights]
            total = sum(weights)
            r = (fast_rand_less_than(1 << 30) / float(1 << 30)) * total
            acc = 0.0
            chosen = cands[-1]
            for n, w in zip(cands, weights):
                acc += w
                if r < acc:
                    chosen = n
                    break
            return chosen

    def on_dispatch(self, node: ServerNode):
        """Called once the node is definitively chosen (socket acquired);
        select_server itself must not count inflight — rejected
        candidates would leak the count and deflate their weight."""
        with self._stats_lock:
            st = self._stats.setdefault(node, [0.0, 0.0])
            st[1] += 1.0

    def on_undispatch(self, node: ServerNode):
        """Release an inflight count for a dispatch whose attempt was
        superseded (retry/backup) — feedback() only decrements once."""
        with self._stats_lock:
            st = self._stats.get(node)
            if st is not None:
                st[1] = max(0.0, st[1] - 1.0)

    def feedback(self, node: ServerNode, latency_us: int, failed: bool):
        with self._stats_lock:
            st = self._stats.setdefault(node, [0.0, 0.0])
            st[1] = max(0.0, st[1] - 1.0)
            lat = float(latency_us if not failed else max(latency_us, 100_000) * 10)
            st[0] = lat if st[0] <= 0 else st[0] * (1 - self._alpha) + lat * self._alpha


class DynPartLB(_SnapshotLB):
    """Weighted selection where each candidate's weight is supplied
    LIVE by a callable — the DynamicPartitionChannel registers one
    entry per partition SCHEME and weights it by the scheme's current
    server count, so capacity migrating between schemes shifts traffic
    proportionally (reference DynPartLoadBalancer::SelectServer,
    policy/dynpart_load_balancer.cpp:109-162, weighting sub-channels by
    schan::GetSubChannelWeight).

    Works as a plain LB too: nodes without a weight callable count as
    weight = max(1, node.weight)."""

    name = "dynpart"

    @staticmethod
    def _weight_of(node) -> int:
        fn = getattr(node, "dynpart_weight", None)
        if callable(fn):
            try:
                return max(0, int(fn()))
            except Exception:  # noqa: BLE001 — a raising probe = empty
                return 0
        return max(1, int(getattr(node, "weight", 1) or 1))

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        nodes = self._data.read()
        cands = [n for n in nodes if n not in sin.excluded] or list(nodes)
        weighted = [(n, self._weight_of(n)) for n in cands]
        total = sum(w for _, w in weighted)
        if total <= 0:
            return None
        r = fast_rand_less_than(total)
        acc = 0
        for n, w in weighted:
            acc += w
            if r < acc:
                return n
        return None


class StableShardLB(_SnapshotLB):
    """Deterministic keyed shard routing for a flat cluster used as a
    sharded KV (docs/sharded_ps.md): ``request_code % n`` over the
    ENDPOINT-SORTED member list.  Sorting (not insertion order) is
    what makes the key→server mapping reproducible across restarts and
    across clients that learned the membership in different orders —
    the property the ShardRoutedChannel gets from NS tag indices, for
    channels that have only a node list.  Excluded (already-failed)
    owners fail over to the next server in sorted order, still
    deterministically.

    Shed pressure (EOVERCROWDED completions fed through ``on_shed`` by
    LoadBalancerWithNaming, same contract as ``mesh_locality``) demotes
    an overloaded owner: its keys fail over to the next server in
    sorted order until successes decay the pressure, with every Nth
    demoted pick probing the owner so it re-earns ownership.  Without
    this the retry-elsewhere code looped straight back to the same
    shedding replica — ``% n`` is memoryless."""

    name = "shard"
    SHED_TRIP = 2  # consecutive-ish sheds before keys route around
    SHED_MAX = 8
    PROBE_EVERY = 4  # every Nth demoted pick probes the shedding owner

    def __init__(self):
        super().__init__()
        # endpoint-sorted snapshot, rebuilt on membership change so the
        # select hot path is one index (same shape as WRR's expansion)
        self._sorted: DoublyBufferedData = DoublyBufferedData(tuple())
        self._shed: Dict[ServerNode, int] = {}
        self._shed_lock = threading.Lock()
        self._probe_tick = 0

    def _rebuild_sorted(self):
        nodes = self._data.read()
        ordered = tuple(sorted(nodes, key=lambda n: str(n.endpoint)))
        self._sorted.modify(lambda _: ordered)

    def add_server(self, node: ServerNode) -> bool:
        added = super().add_server(node)
        if added:
            self._rebuild_sorted()
        return added

    def remove_server(self, node: ServerNode) -> bool:
        removed = super().remove_server(node)
        if removed:
            self._rebuild_sorted()
        return removed

    def on_shed(self, node: ServerNode) -> None:
        with self._shed_lock:
            self._shed[node] = min(self.SHED_MAX, self._shed.get(node, 0) + 1)

    def shedding(self, node: ServerNode) -> bool:
        return self._shed.get(node, 0) >= self.SHED_TRIP

    def feedback(self, node: ServerNode, latency_us: int, failed: bool):
        if not failed:
            with self._shed_lock:
                s = self._shed.get(node, 0)
                if s:
                    self._shed[node] = s - 1

    def select_server(self, sin: SelectIn) -> Optional[ServerNode]:
        ordered = self._sorted.read()
        if not ordered:
            return None
        idx = (sin.request_code or 0) % len(ordered)
        shed_owner = None  # first shedding candidate, in walk order
        fallback = None  # first non-excluded shedding candidate
        for step in range(len(ordered)):
            node = ordered[(idx + step) % len(ordered)]
            if node in sin.excluded:
                continue
            if self.shedding(node):
                if shed_owner is None:
                    shed_owner = node
                if fallback is None:
                    fallback = node
                continue
            if shed_owner is not None:
                # demoted pick: occasionally probe the shedding owner so
                # its successes can decay the pressure (feedback) — the
                # same revival contract as mesh_locality
                self._probe_tick += 1
                if self._probe_tick % self.PROBE_EVERY == 0:
                    return shed_owner
            return node
        if fallback is not None:
            return fallback  # everyone shedding: better overloaded than none
        return ordered[idx]  # all excluded: better the owner than none


_lb_registry: Dict[str, type] = {}


def register_load_balancer(cls):
    _lb_registry[cls.name] = cls
    return cls


for _cls in (
    RoundRobinLB,
    WeightedRoundRobinLB,
    RandomLB,
    WeightedRandomLB,
    ConsistentHashingLB,
    MeshLocalityLB,
    LocalityAwareLB,
    DynPartLB,
    StableShardLB,
):
    register_load_balancer(_cls)


def create_load_balancer(name: str) -> Optional[LoadBalancer]:
    cls = _lb_registry.get(name)
    return cls() if cls else None
