"""Health checking — periodic reconnect probes for failed nodes.

Analog of reference HealthCheckTask (details/health_check.cpp:146): a
node whose connection failed is probed every
``health_check_interval_s``; when a probe connects, the node is revived
and rejoins load balancing (SocketUser::CheckHealth/AfterRevived,
socket.h:64-78).
"""

from __future__ import annotations

import socket as _pysocket
import threading
from typing import Callable, Optional

from incubator_brpc_tpu.runtime.timer_thread import get_timer_thread
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.logging import log_info


class HealthCheckTask:
    def __init__(
        self,
        endpoint: EndPoint,
        on_revived: Callable[[], None],
        interval_s: float = 1.0,
        max_probes: int = 0,  # 0 = forever
    ):
        self.endpoint = endpoint
        self._on_revived = on_revived
        self._interval = interval_s
        self._max_probes = max_probes
        self._probes = 0
        self._stopped = False
        self._schedule()

    def _schedule(self):
        # the timer thread only *spawns* the probe; the blocking connect
        # runs on a runtime worker so armed RPC timers never stall
        get_timer_thread().schedule(self._spawn_probe, self._interval)

    def _spawn_probe(self):
        from incubator_brpc_tpu.runtime import scheduler

        scheduler.spawn(self._probe)

    def _probe(self):
        if self._stopped:
            return
        self._probes += 1
        if self._check():
            log_info("health check: %s revived", self.endpoint)
            self._stopped = True
            try:
                self._on_revived()
            except Exception:
                pass
            return
        if self._max_probes and self._probes >= self._max_probes:
            self._stopped = True
            return
        self._schedule()

    def _check(self) -> bool:
        ep = self.endpoint
        if ep.scheme == "ici":
            from incubator_brpc_tpu.parallel.ici import get_fabric

            return get_fabric().routable(ep.coords)
        try:
            s = _pysocket.create_connection(ep.sockaddr(), timeout=0.5)
            s.close()
            return True
        except OSError:
            return False

    def stop(self):
        self._stopped = True
