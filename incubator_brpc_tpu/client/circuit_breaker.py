"""Circuit breaker + cluster recovery.

Analog of reference CircuitBreaker (circuit_breaker.h:25-60): per-node
error-rate EMA; a node is isolated when its recent error rate crosses
the threshold, isolation duration doubles on repeat offenses (capped),
and the node rejoins after the duration via health checking.
ClusterRecoverPolicy (cluster_recover_policy.{h,cpp}) prevents
avalanche: when too many nodes are isolated, traffic is randomly let
through to isolated nodes so the cluster can recover.
"""

from __future__ import annotations

import threading
import time

from incubator_brpc_tpu.utils.hashes import fast_rand_double


class CircuitBreaker:
    def __init__(
        self,
        alpha: float = 0.2,
        error_threshold: float = 0.5,
        min_samples: int = 5,
        base_isolation_s: float = 0.1,
        max_isolation_s: float = 30.0,
    ):
        self._alpha = alpha
        self._threshold = error_threshold
        self._min_samples = min_samples
        self._base_isolation = base_isolation_s
        self._max_isolation = max_isolation_s
        self._lock = threading.Lock()
        self._ema_error = 0.0
        self._samples = 0
        self._isolated_until = 0.0
        self._isolation_count = 0

    def on_call(self, failed: bool) -> None:
        """Feedback from every finished RPC (reference OnCallEnd)."""
        with self._lock:
            self._samples += 1
            self._ema_error = (
                self._ema_error * (1 - self._alpha) + (1.0 if failed else 0.0) * self._alpha
            )
            if (
                failed
                and self._samples >= self._min_samples
                and self._ema_error > self._threshold
                and time.monotonic() >= self._isolated_until
            ):
                self._isolation_count += 1
                duration = min(
                    self._base_isolation * (2 ** (self._isolation_count - 1)),
                    self._max_isolation,
                )
                self._isolated_until = time.monotonic() + duration

    def mark_failed_hard(self):
        """Connection-level failure: isolate immediately."""
        with self._lock:
            self._isolation_count += 1
            duration = min(
                self._base_isolation * (2 ** (self._isolation_count - 1)),
                self._max_isolation,
            )
            self._isolated_until = time.monotonic() + duration
            self._ema_error = 1.0
            self._samples = max(self._samples, self._min_samples)

    def is_isolated(self) -> bool:
        return time.monotonic() < self._isolated_until

    def reset(self):
        """Health check succeeded: rejoin (reference Reset; the
        repeat-offender count decays rather than clearing)."""
        with self._lock:
            self._ema_error = 0.0
            self._samples = 0
            self._isolated_until = 0.0
            self._isolation_count = max(0, self._isolation_count - 1)


class ClusterRecoverPolicy:
    """Anti-avalanche: when isolated_ratio exceeds `threshold`, allow a
    random fraction of traffic to isolated nodes."""

    def __init__(self, threshold: float = 0.7):
        self._threshold = threshold

    def should_try_isolated(self, isolated: int, total: int) -> bool:
        if total == 0 or isolated == 0:
            return False
        ratio = isolated / total
        if ratio < self._threshold:
            return False
        return fast_rand_double() < ratio
