"""Controller — per-RPC state machine shared by client & server roles.

Analog of reference brpc::Controller (controller.{h,cpp}): carries
timeouts, retry budget, compression, attachments, error state, the
versioned correlation id, and drives IssueRPC (controller.cpp:985-1199)
plus the retry/backup arbitration of OnVersionedRPCReturned (:568).

Client lifecycle (mirrors SURVEY.md §3.2):
  CallMethod → create CallId(on_error=_id_on_error) → serialize once →
  arm deadline (+backup) timer → IssueRPC → [sync] join(cid)
  response → protocol locks wire cid (stale attempts fail) →
  _on_response → finalize → unlock_and_destroy → join wakes / done runs
  error (timeout / socket failure) → _id_on_error under the id lock →
  retry (bump version, reissue) or finalize.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.client.retry import default_retry_policy
from incubator_brpc_tpu.protocols.compress import COMPRESS_TYPE_NONE
from incubator_brpc_tpu.runtime import scheduler
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.runtime.timer_thread import get_timer_thread
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

# Controller freelist (Controller.acquire/release).  A plain list:
# append/pop are GIL-atomic, and a stale controller is always released
# pre-wiped, so acquire hands out objects indistinguishable from fresh
# ones.  Bounded so a burst can't pin memory forever.
_pool: list = []
_POOL_MAX = 4096


def acquire_controller() -> "Controller":
    """Pooled Controller for high-rate callers (see docs/fastpath.md).
    Flat implementation — this pair runs once per RPC on the fast path,
    so it skips the method-dispatch hop of Controller.acquire/release."""
    try:
        return _pool.pop()  # GIL-atomic
    except IndexError:
        return Controller()


def release_controller(controller: "Controller") -> None:
    controller.__dict__.clear()
    if len(_pool) < _POOL_MAX:
        _pool.append(controller)


class Controller:
    # ---- field defaults -----------------------------------------------------
    # All immutable defaults live on the CLASS: constructing a
    # Controller touches no instance state at all, so Controller() costs
    # ~0.1us instead of ~2.4us of attribute stores.  That matters
    # because the native sync/async fast paths create one per RPC and
    # the whole user-visible call budget on one core is ~7us
    # (reference parity: Controller is a POD-ish stack object there,
    # controller.h).  reset() is a __dict__ wipe back to these defaults.
    # Mutable fields (IOBufs, lists, lock, set) are lazily materialized
    # by the properties below on first touch; _start_call materializes
    # the lock eagerly before any cross-thread use.
    # shared state
    error_code = 0
    _error_text = ""
    request_compress_type = COMPRESS_TYPE_NONE
    response_compress_type = COMPRESS_TYPE_NONE
    log_id = 0
    remote_side: Optional[EndPoint] = None
    local_side: Optional[EndPoint] = None
    # client state
    timeout_ms: Optional[int] = None  # None = channel default
    max_retry: Optional[int] = None
    retry_count = 0
    # tenant identity for server-side admission control
    # (docs/overload.md): packed into RpcRequestMeta.tenant; the server
    # maps it to a priority tier / quota at dispatch
    tenant = ""
    # True while arbitrating an error the SERVER returned (vs one the
    # local transport generated) — the retry policy's retry-elsewhere
    # rule for EOVERCROWDED reads it
    _error_from_server = False
    backup_request_ms: Optional[int] = None
    call_id = 0  # base cid (any-version form used by timers)
    _current_cid = 0  # wire cid of the live attempt
    _channel = None
    _method_spec = None
    _request_buf: Optional[IOBuf] = None
    _response = None
    _done: Optional[Callable] = None
    _timer_id = 0
    _backup_timer_id = 0
    _retry_backoff_timer_id = 0  # pending backed-off retry (chaos/backoff)
    _start_ns = 0
    latency_us = 0
    # server's own elapsed time (RpcResponseMeta.server_time_us): the
    # leg's latency_us minus this is the wire+queue residual the
    # cluster straggler attribution splits on (observability/cluster.py)
    server_time_us = 0
    # server-side anchor for stamping server_time_us into the response
    _server_recv_ns = 0
    _retry_policy = None
    _used_backup = False
    _sending_sid = 0
    _selected_server = None  # LB bookkeeping (Feedback)
    # FIFO entries the next write must register atomically with its
    # queue position (set by pack_request of pipelined protocols)
    _pipelined_entries = None
    # (bytes, entries) to prepend once per connection (redis AUTH)
    _conn_preamble = None
    _auth_context = None  # per-request identity (h2 per-stream auth)
    _finalized = False
    _span = None
    # raw response payload when the call ran in bytes mode (native fast
    # path with response=None); None otherwise
    response_bytes = None
    # server state
    server = None
    _server_socket = None
    _server_cid = 0
    _server_meta = None
    service_name = ""
    method_name = ""
    # streaming
    _request_stream = None
    _response_stream = None
    _remote_stream_settings = None
    _session_local = None  # pooled per-RPC user data (server side)
    # progressive bodies (reference progressive_attachment.h)
    _read_progressively = False  # client opt-in, set before call
    _progressive_body = None  # client: _ProgressiveBody to read
    _progressive_attachment = None  # server: PA being written

    def __init__(self):
        pass

    def reset(self):
        self.__dict__.clear()

    # ---- pooled construction (the zero-Python-per-call fast path) ----------
    # The reference's Controller is a stack object reused implicitly per
    # call frame (controller.h); here the analog is an explicit LIFO
    # freelist.  Contract (docs/fastpath.md): release() wipes ALL
    # per-call state (reset is a __dict__ clear back to class defaults),
    # so nothing — errors, timeouts, attachments, retry counts — can
    # bleed into the next acquire.  Never release a controller whose RPC
    # is still in flight (async: release only from/after done()).
    @classmethod
    def acquire(cls) -> "Controller":
        return acquire_controller()

    def release(self):
        release_controller(self)

    # ---- lazily-materialized mutable fields ---------------------------------
    # Data descriptors shadow the instance __dict__, so the properties
    # own the storage: getters create-on-first-touch, setters write the
    # same slot.  Untouched fields cost nothing per instance.
    @staticmethod
    def _lazy(name, factory):
        def get(self):
            v = self.__dict__.get(name)
            if v is None:
                v = self.__dict__[name] = factory()
            return v

        def set_(self, v):
            self.__dict__[name] = v

        return property(get, set_)

    request_attachment = _lazy.__func__("request_attachment", IOBuf)
    response_attachment = _lazy.__func__("response_attachment", IOBuf)
    _lb_dispatches = _lazy.__func__("_lb_dispatches", list)
    _waiter_regs = _lazy.__func__("_waiter_regs", list)
    # sockets this RPC borrowed exclusively (connection_type pooled/
    # short): (kind, sid, remote, signature); released at finalize
    _owned_sockets = _lazy.__func__("_owned_sockets", list)
    _excluded = _lazy.__func__("_excluded", set)  # servers already tried
    # monotonic_ns stamp per issued attempt (chaos retry-spacing asserts)
    _attempt_times_ns = _lazy.__func__("_attempt_times_ns", list)
    # guards the dispatch/waiter lists against a backup attempt racing
    # finalize: issue_rpc runs spawned, outside the id lock, and may
    # register a waiter/dispatch after _finalize_locked swept them
    _rpc_end_lock = _lazy.__func__("_rpc_end_lock", threading.Lock)

    # ---- error surface (controller.h) --------------------------------------
    def failed(self) -> bool:
        return self.error_code != 0

    def error_text(self) -> str:
        return self._error_text or (
            errors.error_text(self.error_code) if self.error_code else ""
        )

    def set_failed(self, code: int, text: str = ""):
        self.error_code = code or errors.EINTERNAL
        self._error_text = text

    # ---- per-attempt bookkeeping (swept by _finalize_locked) ----------------
    def try_record_dispatch(self, node) -> bool:
        """Record an LB on_dispatch for the end-of-RPC sweep. False =
        the RPC already finalized; the caller must undo its dispatch."""
        with self._rpc_end_lock:
            if self._finalized:
                return False
            self._lb_dispatches.append(node)
            return True

    def take_dispatches(self):
        with self._rpc_end_lock:
            d = self._lb_dispatches
            self._lb_dispatches = []
            return d

    def _try_record_waiter(self, sid: int, wire_cid: int) -> bool:
        with self._rpc_end_lock:
            if self._finalized:
                return False
            self._waiter_regs.append((sid, wire_cid))
            return True

    def try_record_owned(self, entry) -> bool:
        """Record a pooled/short socket borrow for the finalize release.
        False = already finalized; the caller must release it itself."""
        with self._rpc_end_lock:
            if self._finalized:
                return False
            self._owned_sockets.append(entry)
            return True

    # ---- client call driving ------------------------------------------------
    def _start_call(self, channel, method_spec, request, response, done):
        from incubator_brpc_tpu.protocols import find_protocol

        # materialize the end-of-RPC lock while still single-threaded:
        # lazy creation from two racing threads would yield two locks
        self._rpc_end_lock  # noqa: B018 — touch creates it
        self._channel = channel
        self._method_spec = method_spec
        self._response = response
        self._done = done
        self._retry_policy = channel.options.retry_policy or default_retry_policy()
        if self.timeout_ms is None:
            self.timeout_ms = channel.options.timeout_ms
        if self.max_retry is None:
            self.max_retry = channel.options.max_retry
        if self.backup_request_ms is None:
            self.backup_request_ms = channel.options.backup_request_ms
        if self.request_compress_type == COMPRESS_TYPE_NONE:
            self.request_compress_type = channel.options.request_compress_type
        self._start_ns = time.monotonic_ns()
        # rpcz client span (Span::CreateClientSpan, channel.cpp:478)
        from incubator_brpc_tpu.observability.span import Span

        self._span = Span.create_client(
            method_spec.service_name, method_spec.method_name
        )
        proto = channel.protocol
        pool = _id_pool()
        self._current_cid = pool.create(data=self, on_error=Controller._id_on_error)
        from incubator_brpc_tpu.runtime.call_id import wildcard

        self.call_id = wildcard(self._current_cid)
        # serialize ONCE per RPC (channel.cpp:517)
        try:
            self._request_buf = proto.serialize_request(request, self)
        except Exception as e:  # noqa: BLE001
            self.set_failed(errors.EREQUEST, f"serialize failed: {e}")
            pool.lock(self._current_cid)
            self._finalize_locked(self._current_cid)
            return
        # arm overall deadline (channel.cpp:550-567)
        if self.timeout_ms and self.timeout_ms > 0:
            self._timer_id = get_timer_thread().schedule(
                self._handle_timeout, self.timeout_ms / 1000.0, self.call_id
            )
        if self.backup_request_ms and self.backup_request_ms > 0:
            self._backup_timer_id = get_timer_thread().schedule(
                self._handle_backup_request, self.backup_request_ms / 1000.0,
                self.call_id,
            )
        self.issue_rpc(self._current_cid)

    def join(self):
        _id_pool().join(self.call_id)

    def issue_rpc(self, wire_cid: int):
        """Select a server socket and send (IssueRPC, controller.cpp:985).
        Called without the id lock held."""
        # Stale-spawn guard, BEFORE any state is touched: a backed-off
        # retry spawn can outlive its RPC (timer pops racing finalize).
        # A mismatched cid means this attempt's world is gone — the
        # call finalized and the Controller was released (wiped cid 0)
        # or even reacquired for a new call (fresh cid); a live newer
        # attempt also invalidates this one (version bumped).  Writing
        # anything here would repopulate a pooled controller.
        if wire_cid != self._current_cid or self._channel is None:
            return
        # attempt-time stamp: one ns clock read + list append per
        # ATTEMPT (not per call on the fused native path, which never
        # enters issue_rpc) — the chaos harness asserts retry/backoff
        # spacing against these
        self._attempt_times_ns.append(time.monotonic_ns())
        channel = self._channel
        proto = channel.protocol
        err, sid, server = channel._select_socket(self)
        if err:
            # couldn't reach any server: feed the error through the id so
            # retry/finalize arbitration stays in one place
            _id_pool().error(wire_cid, err, "failed to select/connect server")
            return
        self._sending_sid = sid
        self._selected_server = server
        from incubator_brpc_tpu.transport.socket import Socket

        sock = Socket.address(sid)
        if sock is None or sock.failed:
            _id_pool().error(wire_cid, errors.EFAILEDSOCKET, "socket gone")
            return
        self.remote_side = sock.remote
        # headerless protocols (esp) validate incoming bytes against
        # the protocol this socket is actually speaking
        sock.last_protocol = proto.name
        # A backup/retry attempt racing finalize must leave ZERO
        # per-socket state behind (waiting_cids, http pipelined_info),
        # or the connection desynchronizes. Ordering: create the state,
        # then publish it for the finalize sweep; on a lost race the
        # publish fails and this attempt undoes its own state — the
        # sweep can never miss a published registration.
        if proto.issue is not None:
            # stateful protocols (h2) pack+write atomically themselves
            # and register the response waiter internally
            if not sock.is_server_side and not self._try_record_waiter(sid, wire_cid):
                return  # finalized before any state was created
            try:
                proto.issue(sock, self._request_buf, wire_cid, self._method_spec, self)
            except Exception as e:  # noqa: BLE001
                _id_pool().error(wire_cid, errors.EREQUEST, f"issue failed: {e}")
            with self._rpc_end_lock:
                swept = self._finalized
            if swept:
                # finalize may have swept before issue() registered the
                # waiter; removing again here is idempotent either way
                sock.remove_response_waiter(wire_cid)
            return
        if not sock.is_server_side:
            sock.add_response_waiter(wire_cid)
            if not self._try_record_waiter(sid, wire_cid):
                sock.remove_response_waiter(wire_cid)
                return
        try:
            packet = proto.pack_request(
                self._request_buf, wire_cid, self._method_spec, self
            )
        except Exception as e:  # noqa: BLE001
            _id_pool().error(wire_cid, errors.EREQUEST, f"pack failed: {e}")
            return
        entries, self._pipelined_entries = self._pipelined_entries, None
        preamble, self._conn_preamble = self._conn_preamble, None
        prev_span = None
        # Scope this attempt's span as the task-local parent while the
        # packet enters the transport — but only on fabric sockets:
        # that is where collective sub-spans (ici/dcn legs) are created
        # and need the client span as parent. Kernel sockets create no
        # sub-spans, so the TCP hot path skips both TLS swaps.
        swap = self._span is not None and sock.ici_port is not None
        if self._span is not None:
            # the generic "write" stamp: for a client span the queued
            # bytes are the REQUEST; sent_us follows at flush
            self._span.response_write_us = time.time_ns() // 1000
        if swap:
            from incubator_brpc_tpu.observability.span import swap_current_span

            prev_span = swap_current_span(self._span)
        try:
            rc = sock.write(
                packet, notify_cid=wire_cid, pipelined_entries=entries,
                conn_preamble=preamble, span=self._span,
            )
        finally:
            if swap:
                swap_current_span(prev_span)
        # rc!=0 already routed the error through the id pool

    # ---- error / timeout / retry arbitration -------------------------------
    def _reissue_after_backoff(self, cid):
        """Timer-thread continuation of a backed-off retry: the timer
        only SPAWNS the attempt (issue_rpc may block on connect).
        Two stale-firing guards — the timer may pop concurrently with
        finalize (unschedule misses an already-popped entry):
        _current_cid no longer matching catches a released/reused
        Controller (release wipes it to 0, a new call mints a new cid);
        _finalized catches completed-but-not-yet-released.  Read via
        __dict__ so a released controller is not re-polluted by the
        lazy-lock property.  The residual spawn-vs-finalize window is
        the same one backup requests already have: issue_rpc's
        _try_record_waiter undoes the attempt's state on a lost race."""
        if self._current_cid != cid or self.__dict__.get("_finalized"):
            return
        scheduler.spawn(self.issue_rpc, cid)

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds left in this RPC's overall deadline budget;
        None when the call has no deadline."""
        if not self.timeout_ms or self.timeout_ms <= 0 or not self._start_ns:
            return None
        elapsed_ms = (time.monotonic_ns() - self._start_ns) / 1e6
        return self.timeout_ms - elapsed_ms

    def attempt_times_ns(self) -> list:
        """monotonic_ns stamps of every attempt issued (first try,
        retries, backups) — the chaos harness reads retry spacing here."""
        return list(self.__dict__.get("_attempt_times_ns") or ())

    def _attempt_pending(self) -> bool:
        """Whether any of this RPC's issued attempts is still awaiting
        a response (its waiter remains registered on its socket — the
        responding attempt's waiter is removed at parse time, before
        the id is locked).  Used by hedge arbitration: an error from
        one replica must not decide the RPC while another attempt is
        live."""
        from incubator_brpc_tpu.transport.socket import Socket

        with self._rpc_end_lock:
            regs = list(self._waiter_regs)
        for sid, cid_reg in regs:
            sock = Socket.address(sid)
            if sock is not None and not sock.failed:
                with sock._write_lock:
                    if cid_reg in sock.waiting_cids:
                        return True
        return False

    def has_unexcluded_replica(self) -> bool:
        """Whether the channel's cluster still offers a replica this
        RPC has not already tried/excluded — the retry policy's
        "EOVERCROWDED is retriable only on a DIFFERENT server" gate.
        Single-server channels (no LB) have nowhere else to go."""
        channel = self._channel
        lb = getattr(channel, "_lb", None)
        if lb is None:
            return False
        excluded = set(self.__dict__.get("_excluded") or ())
        if self._selected_server is not None:
            excluded.add(self._selected_server)
        return any(n not in excluded for n in lb.servers())

    def _handle_timeout(self, cid):
        _id_pool().error(cid, errors.ERPCTIMEDOUT, "reached timeout")

    def _handle_backup_request(self, cid):
        _id_pool().error(cid, errors.EBACKUPREQUEST, "")

    @staticmethod
    def _id_on_error(data, cid, error_code, error_text):
        """Runs UNDER the id lock (reference bthread_id_error semantics)."""
        self: Controller = data
        pool = _id_pool()
        if error_code == errors.EBACKUPREQUEST:
            # hedged request: send a second attempt, keep first in flight
            # (channel.cpp:537-558). Same wire cid version: first response wins.
            # A pending backed-off retry is superseded — this backup IS
            # the reissue (just earlier); leaving the timer armed would
            # put a THIRD identical attempt on the wire when it pops.
            if self._retry_backoff_timer_id:
                get_timer_thread().unschedule(self._retry_backoff_timer_id)
                self._retry_backoff_timer_id = 0
            self._used_backup = True
            # hedge to a DIFFERENT replica (docs/overload.md): the slow
            # attempt's server joins the exclusion set so the LB picks
            # another one — a backup landing on the same wedged replica
            # hedges nothing.  Single-server channels have no LB and
            # reissue on the shared connection as before.
            if self._selected_server is not None:
                self._excluded.add(self._selected_server)
            pool.unlock(cid)
            scheduler.spawn(self.issue_rpc, self._current_cid)
            return
        if error_code not in (
            errors.ERPCTIMEDOUT, errors.ECANCELED
        ) and self._try_retry_locked(cid, error_code, error_text):
            return
        self.set_failed(error_code, error_text)
        self._finalize_locked(cid)

    def _try_retry_locked(self, cid, error_code, error_text) -> bool:
        """Retry arbitration under the id lock, shared by transport
        errors (_id_on_error) and server-returned retriable codes
        (_on_response — an EOVERCROWDED shed from admission arrives as
        a RESPONSE, not a socket failure, and must still reissue
        against a different replica).  True = a new attempt was
        scheduled and the id stays alive; False = the caller finalizes
        with the error."""
        if self.retry_count >= (self.max_retry or 0):
            return False
        pool = _id_pool()
        self.error_code = error_code
        self._error_text = error_text
        if not self._retry_policy.do_retry(self):
            self.error_code = 0
            self._error_text = ""
            return False
        self.error_code = 0
        self._error_text = ""
        # the origin marker is per-arbitration, not per-RPC: the NEXT
        # attempt's error must re-establish where it came from
        self.__dict__.pop("_error_from_server", None)
        self.retry_count += 1
        if self._selected_server is not None:
            self._excluded.add(self._selected_server)
        new_cid = pool.bump_version(self._current_cid)
        self._current_cid = new_cid
        pool.unlock(new_cid)
        # retry backoff (retry_policy.backoff_ms; 0 on the default
        # policy = the historical immediate reissue).  The sleep
        # rides the timer thread — never a worker — and the overall
        # deadline timer stays armed, so a backoff that outlives
        # the budget resolves as ERPCTIMEDOUT like any slow attempt.
        delay_ms = 0.0
        bk = getattr(self._retry_policy, "backoff_ms", None)
        if bk is not None:
            try:
                delay_ms = bk(self) or 0.0
            except Exception as e:  # noqa: BLE001
                log_error("retry backoff_ms raised: %r", e)
        if delay_ms > 0:
            self._retry_backoff_timer_id = get_timer_thread().schedule(
                self._reissue_after_backoff, delay_ms / 1000.0, new_cid
            )
        else:
            scheduler.spawn(self.issue_rpc, new_cid)
        return True

    # ---- response path ------------------------------------------------------
    def _on_response(self, cid, meta, payload: IOBuf):
        """Runs UNDER the id lock with the parsed response (client side)."""
        from incubator_brpc_tpu.protocols import compress as compress_mod

        rmeta = meta.response
        if rmeta.server_time_us:
            # read before any error-path return: a shed/failed leg still
            # carries the server's elapsed time for attribution
            self.server_time_us = rmeta.server_time_us
        if rmeta.error_code != 0:
            if self.__dict__.get("_used_backup") and self._attempt_pending():
                # hedged RPC with the OTHER attempt still in flight:
                # one replica's shed/error is not the RPC's outcome —
                # first SUCCESS wins.  Arbitrating now would exclude
                # _selected_server (the LAST-issued attempt's replica,
                # possibly the healthy one) and bump the cid version,
                # killing a backup that was about to succeed.  Ignore
                # this response; the overall deadline timer bounds the
                # wait, and the last attempt to answer arbitrates.
                _id_pool().unlock(cid)
                return
            # server-returned retriable codes (an EOVERCROWDED shed
            # from admission, ELOGOFF from a stopping server) re-enter
            # the SAME retry arbitration as transport errors: the
            # failed replica joins the exclusion set so the reissue
            # lands elsewhere — retrying an overloaded server against
            # itself is how overload spreads
            # mark the origin: the retry policy's "EOVERCROWDED only
            # retries on a different replica" rule applies to SERVER
            # sheds, not to the client's own transient write
            # backpressure (which arrives via _id_on_error instead)
            self._error_from_server = True
            if rmeta.error_code not in (
                errors.ERPCTIMEDOUT, errors.ECANCELED
            ) and self._try_retry_locked(
                cid, rmeta.error_code, rmeta.error_text
            ):
                return
            self.set_failed(rmeta.error_code, rmeta.error_text)
            self._finalize_locked(cid)
            return
        # stream negotiation completed: wire the client stream onto the
        # connection (reference: response meta stream_settings handling)
        if self._request_stream is not None and self._remote_stream_settings is not None:
            from incubator_brpc_tpu.transport.socket import Socket

            sock = Socket.address(self._sending_sid)
            if sock is not None and not sock.failed:
                self._request_stream.establish(
                    sock,
                    self._remote_stream_settings.stream_id,
                    self._remote_stream_settings,
                )
        try:
            att_size = meta.attachment_size
            body = payload
            if att_size:
                body = IOBuf()
                payload.cutn(body, len(payload) - att_size)
                self.response_attachment = payload
            if meta.compress_type:
                body = compress_mod.decompress(body, meta.compress_type)
                if body is None:
                    raise ValueError("unsupported compress type")
            if self._response is not None:
                self._response.ParseFromString(body.as_view())
        except Exception as e:  # noqa: BLE001
            self.set_failed(errors.ERESPONSE, f"parse response failed: {e}")
        self._finalize_locked(cid)

    def _finalize_locked(self, cid):
        """Complete the RPC: stats, timers, destroy id, run done.
        Must hold the id lock."""
        pool = _id_pool()
        with self._rpc_end_lock:
            self._finalized = True
            regs = self._waiter_regs
            self._waiter_regs = []
        if regs:
            from incubator_brpc_tpu.transport.socket import Socket

            # every attempt (retries, backups) registered its own
            # (sid, cid); removing only the last one leaks the earlier
            # registrations until their socket dies (round-1 advisor bug)
            channel = self._channel
            pack_cancel = getattr(
                getattr(channel, "protocol", None), "pack_cancel", None
            )
            for sid, cid_reg in regs:
                sock = Socket.address(sid)
                if sock is None:
                    continue
                still_pending = sock.remove_response_waiter(cid_reg)
                if (
                    still_pending
                    and pack_cancel is not None
                    and not sock.failed
                    and not sock.is_server_side
                    and getattr(sock, "ici_port", None) is None
                ):
                    # kernel sockets only: a fabric frame carries window
                    # credits and device-payload structure a bare
                    # cancel meta would corrupt (ICI losers are bounded
                    # by the fabric's own failure handling)
                    # an attempt this RPC abandoned (hedge loser, a
                    # timed-out or superseded try) is still being
                    # served: a cancel frame lets the server shed it
                    # before device work and drop the reply — hedging
                    # must never double the work (docs/overload.md).
                    # The stale-cid guard already discards whatever
                    # the loser might still send back.
                    try:
                        sock.write(
                            pack_cancel(cid_reg), ignore_eovercrowded=True
                        )
                    except Exception as e:  # noqa: BLE001 — cancel is
                        # best-effort; the RPC itself is already done
                        log_error("cancel frame send failed: %r", e)
        with self._rpc_end_lock:
            owned, self._owned_sockets = self._owned_sockets, []
        if owned:
            from incubator_brpc_tpu.transport.socket_map import release_owned_socket

            for entry in owned:
                release_owned_socket(entry)
        if self._timer_id:
            get_timer_thread().unschedule(self._timer_id)
            self._timer_id = 0
        if self._backup_timer_id:
            get_timer_thread().unschedule(self._backup_timer_id)
            self._backup_timer_id = 0
        if self._retry_backoff_timer_id:
            get_timer_thread().unschedule(self._retry_backoff_timer_id)
            self._retry_backoff_timer_id = 0
        self.latency_us = (time.monotonic_ns() - self._start_ns) // 1000
        if self._span is not None:
            self._span.remote_side = str(self.remote_side or "")
            self._span.end(self.error_code)
        channel = self._channel
        if channel is not None:
            channel._on_rpc_end(self)
        done = self._done
        pool.unlock_and_destroy(cid)
        if done is not None:
            scheduler.spawn(self._run_done, done)

    def _run_done(self, done):
        try:
            done()
        except Exception as e:  # noqa: BLE001
            log_error("rpc done callback raised: %r", e)

    def start_cancel(self):
        """Analog of Controller::StartCancel — cancel the in-flight RPC."""
        if self.call_id:
            _id_pool().error(self.call_id, errors.ECANCELED, "canceled by caller")

    # ---- server-side helpers ------------------------------------------------
    def auth_context(self):
        """The AuthContext a passing verify_credential attached to this
        request (h2: per-stream) or its connection (reference
        Controller::auth_context)."""
        if self._auth_context is not None:
            return self._auth_context
        return getattr(self._server_socket, "auth_context", None)

    def close_connection(self):
        """Server handler asks to close the connection after responding
        (controller.h:433)."""
        self._close_connection_after_response = True

    # ---- server-side user data (server.cpp:811-851) ------------------------
    def session_local_data(self):
        """Per-RPC reusable object from the server's pool (reference
        Controller::session_local_data); returns to the pool when the
        response goes out. None unless session_local_data_factory set."""
        if self._session_local is None and self.server is not None:
            self._session_local = self.server.acquire_session_local()
        return self._session_local

    def thread_local_data(self):
        """Per worker-thread object (thread_local_data_factory)."""
        return self.server.thread_local_data() if self.server else None

    def _release_session_local(self):
        data, self._session_local = self._session_local, None
        if data is not None and self.server is not None:
            self.server.return_session_local(data)

    # ---- progressive bodies (reference progressive_attachment.h,
    # controller.h response_will_be_read_progressively) ----------------------
    def response_will_be_read_progressively(self):
        """Client, before the call: don't buffer the response body —
        the RPC completes at the response headers and the body streams
        to the reader passed to read_progressive_attachment()."""
        self._read_progressively = True

    def read_progressive_attachment(self, reader) -> int:
        """Client, after the call: reader(bytes) runs per body part,
        reader(None) at end-of-body. Returns 0, or EREQUEST when the
        response wasn't progressive."""
        body = self._progressive_body
        if body is None:
            return errors.EREQUEST
        body.attach(reader)
        return 0

    def create_progressive_attachment(self, content_type=None):
        """Server handler: switch the response to a chunked stream.
        Returned ProgressiveAttachment accepts write() immediately
        (buffered until the response headers go out after done()) and
        must be close()d to terminate the stream.  ``content_type``
        overrides the chunked response's Content-Type — pass
        "text/event-stream" for SSE token streaming."""
        from incubator_brpc_tpu.protocols.http import ProgressiveAttachment

        if self._progressive_attachment is None:
            self._progressive_attachment = ProgressiveAttachment(
                content_type or "application/octet-stream"
            )
        return self._progressive_attachment
