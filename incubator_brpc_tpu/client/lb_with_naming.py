"""LoadBalancerWithNaming — glue NS → LB → sockets.

Analog of reference details/load_balancer_with_naming.{h,cpp}: watches
a NamingServiceThread, feeds add/remove into the LB, and resolves a
selected node to a shared Socket (SocketMap for TCP, fabric for ICI).
Per-node CircuitBreaker isolation, HealthCheckTask revival, and
ClusterRecoverPolicy anti-avalanche live here (reference spreads these
across socket/health_check/circuit_breaker; the composition point is
the same).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.client.circuit_breaker import CircuitBreaker, ClusterRecoverPolicy
from incubator_brpc_tpu.client.health_check import HealthCheckTask
from incubator_brpc_tpu.client.load_balancer import (
    LoadBalancer,
    SelectIn,
    create_load_balancer,
)
from incubator_brpc_tpu.client.naming_service import (
    NamingServiceThread,
    NamingServiceWatcher,
    ServerNode,
)
from incubator_brpc_tpu.transport.socket import Socket
from incubator_brpc_tpu.transport.socket_map import acquire_socket
from incubator_brpc_tpu.utils.logging import log_error


class _NodeState:
    __slots__ = ("breaker", "health_task", "healthy")

    def __init__(self):
        self.breaker = CircuitBreaker()
        self.health_task: Optional[HealthCheckTask] = None
        self.healthy = True


class LoadBalancerWithNaming(NamingServiceWatcher):
    def __init__(self):
        self._lb: Optional[LoadBalancer] = None
        self._ns_thread: Optional[NamingServiceThread] = None
        self._states: Dict[ServerNode, _NodeState] = {}
        self._lock = threading.Lock()
        self._recover = ClusterRecoverPolicy()
        self._ns_filter = None
        self._ici_port = None

    def init(self, url: str, lb_name: str, ns_filter=None) -> int:
        self._lb = create_load_balancer(lb_name)
        if self._lb is None:
            log_error("unknown load balancer %r", lb_name)
            return errors.EREQUEST
        self._ns_filter = ns_filter
        self._ns_thread = NamingServiceThread.get(url)
        if self._ns_thread is None:
            log_error("unknown naming service url %r", url)
            return errors.EREQUEST
        self._ns_thread.add_watcher(self)
        return 0

    # ---- NS watcher ---------------------------------------------------------
    def on_servers_changed(self, nodes):
        if self._ns_filter is not None:
            nodes = [n for n in nodes if self._ns_filter(n)]
        with self._lock:
            for n in nodes:
                if n not in self._states:
                    self._states[n] = _NodeState()
            for n in list(self._states):
                if n not in nodes:
                    st = self._states.pop(n)
                    if st.health_task:
                        st.health_task.stop()
        self._lb.reset_servers(list(nodes))

    # ---- selection (Controller::IssueRPC hot path) --------------------------
    def select_server(self, controller, messenger) -> Tuple[int, int, Optional[ServerNode]]:
        """Returns (err, sid, node). Skips isolated/excluded nodes, falls
        back through candidates, triggers health check on connect
        failure."""
        lb = self._lb
        all_nodes = lb.servers()
        if not all_nodes:
            return errors.ENOSERVICE, 0, None
        isolated = sum(
            1 for n in all_nodes if (st := self._states.get(n)) and st.breaker.is_isolated()
        )
        allow_isolated = self._recover.should_try_isolated(isolated, len(all_nodes))
        excluded = set(controller._excluded)
        request_code = getattr(controller, "request_code", 0) or controller.log_id
        channel = controller._channel
        signature = channel._signature() if channel is not None else ""
        conn_type = channel.options.connection_type if channel is not None else "single"
        connect_timeout_s = (
            channel.options.connect_timeout_ms / 1000.0 if channel is not None else 3.0
        )
        ssl_params = channel._ssl_params() if channel is not None else None
        for _attempt in range(len(all_nodes) + 1):
            node = lb.select_server(
                SelectIn(excluded=frozenset(excluded), request_code=request_code)
            )
            if node is None:
                break
            st = self._states.get(node)
            if (
                st is not None
                and st.breaker.is_isolated()
                and not allow_isolated
                and len(excluded) < len(all_nodes)
            ):
                excluded.add(node)
                continue
            err, sid = self._socket_for(
                node, messenger, signature, conn_type, connect_timeout_s,
                controller, ssl_params,
            )
            if err == errors.ECANCELED:
                # the RPC finalized while we were acquiring: not the
                # node's fault — no breaker mark, no further candidates
                return err, 0, None
            if err == 0:
                if hasattr(lb, "on_dispatch"):
                    lb.on_dispatch(node)
                    if not controller.try_record_dispatch(node) and hasattr(
                        lb, "on_undispatch"
                    ):
                        # RPC finalized while this backup attempt was
                        # selecting: feedback() already swept, so release
                        # the inflight count here or it leaks forever
                        lb.on_undispatch(node)
                return 0, sid, node
            self._on_connect_failed(node)
            excluded.add(node)
        return errors.EFAILEDSOCKET, 0, None

    def _socket_for(
        self,
        node: ServerNode,
        messenger,
        signature: str = "",
        conn_type: str = "single",
        connect_timeout_s: float = 3.0,
        controller=None,
        ssl_params=None,
    ) -> Tuple[int, int]:
        ep = node.endpoint
        if ep.is_ici():
            port = self._client_ici_port()
            if port is None:
                return errors.EFAILEDSOCKET, 0
            from incubator_brpc_tpu.parallel.ici import get_fabric

            if not get_fabric().routable(ep.coords):
                return errors.EFAILEDSOCKET, 0
            sid = port.connect(ep.coords)
            return (0, sid) if sid is not None else (errors.EFAILEDSOCKET, 0)
        return acquire_socket(
            ep, messenger, signature, conn_type, connect_timeout_s, controller,
            ssl_params,
        )

    def _client_ici_port(self):
        if self._ici_port is None:
            with self._lock:
                if self._ici_port is None:
                    from incubator_brpc_tpu.parallel.ici import acquire_client_port

                    self._ici_port = acquire_client_port()
        return self._ici_port

    def close(self):
        """Detach from the NS thread, stop health probes, release the
        fabric port (no shutdown path = unbounded watcher/probe leak)."""
        if self._ns_thread is not None:
            self._ns_thread.remove_watcher(self)
            self._ns_thread = None
        with self._lock:
            states = list(self._states.values())
            self._states.clear()
        for st in states:
            if st.health_task:
                st.health_task.stop()
        if self._ici_port is not None:
            from incubator_brpc_tpu.parallel.ici import get_fabric

            get_fabric().unregister(self._ici_port.coords)
            self._ici_port = None

    def _on_connect_failed(self, node: ServerNode):
        st = self._states.get(node)
        if st is None:
            return
        st.breaker.mark_failed_hard()
        if st.health_task is None or st.health_task._stopped:
            st.health_task = HealthCheckTask(
                node.endpoint, on_revived=lambda n=node: self._on_revived(n)
            )

    def _on_revived(self, node: ServerNode):
        st = self._states.get(node)
        if st is not None:
            st.breaker.reset()
            st.healthy = True

    # ---- per-RPC feedback (LB Feedback + breaker, OnComplete path) ----------
    def feedback(self, controller):
        lb = self._lb
        node = controller._selected_server
        # Every attempt (retry/backup) incremented inflight via
        # on_dispatch; lb.feedback below decrements exactly once for the
        # final node, so release every OTHER dispatch record here or the
        # leaked inflight permanently deflates those nodes' weights.
        # This sweep must run even with node None (e.g. the deadline
        # fired mid-select, before the attempt became _selected_server).
        dispatches = controller.take_dispatches()
        if dispatches and hasattr(lb, "on_undispatch"):
            final_released = False
            for d in dispatches:
                if node is not None and d == node and not final_released:
                    final_released = True  # lb.feedback covers this one
                    continue
                lb.on_undispatch(d)
        if node is None:
            return
        st = self._states.get(node)
        failed = controller.failed()
        if st is not None:
            # EOVERCROWDED is admission pressure, not node death: it
            # feeds the soft shed signal below (tier-aware LBs route
            # around and probe back), while the breaker stays armed for
            # real failures — tripping it on sheds would turn every
            # overload blip into an isolation the prober can't revive
            st.breaker.on_call(
                failed
                and controller.error_code
                not in (errors.ECANCELED, errors.EOVERCROWDED)
            )
            if failed and controller.error_code in (
                errors.EFAILEDSOCKET,
                errors.ECLOSE,
            ):
                self._on_connect_failed(node)
        if (
            failed
            and controller.error_code == errors.EOVERCROWDED
            and hasattr(lb, "on_shed")
        ):
            # admission shed (the retry-elsewhere code): tier-aware LBs
            # deprioritize the replica until successes decay the signal
            lb.on_shed(node)
        lb.feedback(node, controller.latency_us, failed)

    def servers(self):
        return self._lb.servers() if self._lb else []

    def describe(self) -> str:
        out = []
        for n in self.servers():
            st = self._states.get(n)
            iso = st.breaker.is_isolated() if st else False
            out.append(f"{n.endpoint}{' [isolated]' if iso else ''}")
        return "\n".join(out)
