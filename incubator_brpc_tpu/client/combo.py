"""Combo channels: Parallel / Selective / Partition.

Analogs of the reference's combo channels (SURVEY.md §2.6):
- ParallelChannel (parallel_channel.{h,cpp}): fan one logical RPC out
  to N sub-channels concurrently; CallMapper rewrites per-sub requests
  (parallel_channel.h:64-103), ResponseMerger folds sub-responses, and
  fail_limit bounds tolerated failures; a single shared completion
  closure counts sub-calls (parallel_channel.cpp:46-290).
- SelectiveChannel (selective_channel.h:31-52): load-balances between
  *channels* (server groups) with its own retry layer.
- PartitionChannel / DynamicPartitionChannel (partition_channel.h:
  54-110): sub-channels derived from NS tags "i/N"; the dynamic variant
  re-partitions live as the NS changes schemes.

TPU lowering note: when sub-responses are mesh-sharded tensors the
merge lowers to one collective (parallel/collectives.py); these classes
are the host-side control plane with per-sub-call failure semantics
(fail_limit, partial merges) that collectives don't have.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.client.controller import Controller
from incubator_brpc_tpu.utils.logging import log_error

# CallMapper(sub_index, total, request) -> request for that sub-channel
CallMapper = Callable[[int, int, object], object]
# ResponseMerger(response, sub_response, sub_index) -> None (folds in place)
ResponseMerger = Callable[[object, object, int], None]


def _default_merger(response, sub_response, _idx):
    if hasattr(response, "MergeFrom"):
        response.MergeFrom(sub_response)


def _note_fanout(method_spec, sub_ctrls) -> None:
    """Feed the completed fan-out's per-leg timings to the straggler
    tracker (/cluster/stragglers).  Per-leg server_time_us rides back in
    the response meta; the tracker splits each leg into server time vs
    wire+queue residual.  Best-effort: observability never fails an
    RPC."""
    try:
        legs = [
            (
                str(sc.remote_side or "") or f"sub{i}",
                sc.latency_us,
                sc.server_time_us,
                sc.failed(),
            )
            for i, sc in enumerate(sub_ctrls)
            if sc is not None
        ]
        if len(legs) < 2:
            return
        from incubator_brpc_tpu.observability import cluster

        cluster.note_fanout(
            f"{method_spec.service_name}.{method_spec.method_name}", legs
        )
    except Exception as e:  # noqa: BLE001
        log_error("fan-out straggler tracking raised: %r", e)


@dataclass
class ParallelChannelOptions:
    fail_limit: int = 0  # tolerated sub-failures; 0 = none
    timeout_ms: int = 1000


class ParallelChannel:
    """Duck-types Channel.call_method, so ServiceStub works on it."""

    def __init__(self, options: Optional[ParallelChannelOptions] = None):
        self.options = options or ParallelChannelOptions()
        self._subs: List[tuple] = []  # (channel, mapper, merger)

    def add_channel(
        self,
        channel,
        call_mapper: Optional[CallMapper] = None,
        response_merger: Optional[ResponseMerger] = None,
    ) -> int:
        self._subs.append((channel, call_mapper, response_merger or _default_merger))
        return 0

    def channel_count(self) -> int:
        return len(self._subs)

    def call_method(self, method_spec, controller, request, response, done=None):
        from incubator_brpc_tpu.observability.span import (
            Span,
            swap_current_span,
        )

        subs = list(self._subs)
        n = len(subs)
        if n == 0:
            controller.set_failed(errors.EINTERNAL, "ParallelChannel has no sub channels")
            if done:
                done()
            return
        start_ns = time.monotonic_ns()
        # rpcz fan-out span: the trace root every sub-call (and the
        # collective legs those sub-calls cross) parents under, so one
        # logical RPC reads as ONE trace in /rpcz?trace=
        fanout_span = Span.create_client(
            method_spec.service_name, method_spec.method_name
        )
        if fanout_span is not None:
            fanout_span.annotate(f"parallel fan-out over {n} sub channels")
        state = _FanoutState(n, self.options.fail_limit)

        sub_ctrls: List[Controller] = []
        sub_resps: List[object] = []
        sub_reqs: List[object] = []

        def finish():
            fails = 0
            skips = 0
            for i, sc in enumerate(sub_ctrls):
                if sc is None:
                    skips += 1
                    continue
                if sc.failed():
                    fails += 1
                else:
                    merger = subs[i][2]
                    try:
                        merger(response, sub_resps[i], i)
                    except Exception as e:  # noqa: BLE001
                        log_error("response merger raised: %r", e)
            if skips == n:
                controller.set_failed(
                    errors.EREQUEST, "CallMapper skipped every sub channel"
                )
            elif fails > self.options.fail_limit:
                first_err = next(
                    (sc for sc in sub_ctrls if sc is not None and sc.failed()), None
                )
                controller.set_failed(
                    errors.ETOOMANYFAILS,
                    f"{fails}/{n} sub calls failed"
                    + (f" (first: {first_err.error_text()})" if first_err else ""),
                )
            controller.latency_us = (time.monotonic_ns() - start_ns) // 1000
            _note_fanout(method_spec, sub_ctrls)
            if fanout_span is not None:
                fanout_span.end(controller.error_code)
            if done is not None:
                try:
                    done()
                except Exception as e:  # noqa: BLE001
                    log_error("ParallelChannel done raised: %r", e)

        # finish must be installed BEFORE any on_skip can bring the
        # remaining count to zero — an all-skip mapper otherwise fires
        # the completion with _finish still None (round-1 advisor bug).
        state.set_finish(finish)

        for i, (channel, mapper, merger) in enumerate(subs):
            sub_req = mapper(i, n, request) if mapper else request
            sub_reqs.append(sub_req)
            if sub_req is None:  # mapper may skip a sub-channel (SkipCall)
                sub_ctrls.append(None)
                sub_resps.append(None)
                state.on_skip()
                continue
            sc = Controller()
            sc.timeout_ms = (
                controller.timeout_ms
                if controller.timeout_ms is not None
                else self.options.timeout_ms
            )
            sub_ctrls.append(sc)
            sub_resps.append(method_spec.response_class())

        # issue sub-calls with the fan-out span installed as the
        # task-local parent: each sub Controller's client span (created
        # inside call_method → _start_call) joins this trace under it.
        # The whole issue loop runs inside one fabric delivery burst:
        # sub-calls crossing the ICI fabric enqueue their frames but
        # each destination port's completion queue wakes ONCE when the
        # loop ends (amortized window/credit bookkeeping — the
        # engine.cpp flush_pending_burst analog).  Sub-calls are async
        # (done callbacks), so nothing blocks inside the burst; TCP
        # sub-channels are unaffected.
        from incubator_brpc_tpu.parallel.ici import get_fabric

        prev_span = (
            swap_current_span(fanout_span)
            if fanout_span is not None
            else None
        )
        try:
            with get_fabric().delivery_burst():
                for i, (channel, mapper, merger) in enumerate(subs):
                    sc = sub_ctrls[i]
                    if sc is None:
                        continue
                    leg_done = state.make_done()
                    try:
                        channel.call_method(
                            method_spec, sc, sub_reqs[i], sub_resps[i],
                            done=leg_done,
                        )
                    except Exception as e:  # noqa: BLE001
                        # a raising sub-channel must not orphan its leg:
                        # the shared completion would otherwise never
                        # reach zero and the fan-out hangs until the
                        # wait() timeout.  leg_done is once-guarded, so
                        # a channel that raised AFTER scheduling its
                        # done cannot double-decrement either.
                        log_error("sub-channel call_method raised: %r", e)
                        if not sc.failed():
                            sc.set_failed(
                                errors.EINTERNAL, f"sub call raised: {e}"
                            )
                        leg_done()
        finally:
            if fanout_span is not None:
                swap_current_span(prev_span)
        if done is None:
            state.wait()
            # finish ran on the last completion; nothing else to do

    def call_many(self, method_spec, requests, timeout_ms=None,
                  controllers=None):
        """Windowed fan-out: N same-method requests fan to every
        sub-channel as ONE submission-ring sub-window per leg, so the
        Python↔C boundary is crossed once per LEG (shard), not once per
        (leg × request).  Per-request results come back in order:
        serialized merged response bytes per success, a
        ring.RingFailure per failure — the Channel.call_many contract.
        Merging/fail_limit semantics per request are exactly
        call_method's: each request's sub-responses fold through the
        leg's ResponseMerger and fails > fail_limit maps to
        ETOOMANYFAILS.

        Caller-provided controllers, or a sub-channel without a ring
        surface, degrade per call through ``call_method`` — byte-
        identical ERPC semantics, counted in the fan-out step log."""
        from incubator_brpc_tpu.client import ring as _ring

        subs = list(self._subs)
        n = len(requests)
        if controllers is not None and len(controllers) != n:
            raise ValueError("controllers must match requests 1:1")
        if n == 0:
            return []
        if not subs:
            return [
                _ring.RingFailure(
                    errors.EINTERNAL, "ParallelChannel has no sub channels"
                )
                for _ in requests
            ]
        if controllers is not None and any(
            c is not None for c in controllers
        ) or any(
            not (hasattr(ch, "_submission_ring") and hasattr(ch, "_ring_lock"))
            for ch, _, _ in subs
        ):
            return self._call_many_percall(
                method_spec, requests, timeout_ms, controllers
            )
        nsubs = len(subs)
        # map per-leg requests up front; a mapper returning None skips
        # that (leg, request) pair, same as call_method's SkipCall
        leg_rows = []  # parallel to subs: [((leg, j), mapped_req), ...]
        for i, (ch, mapper, merger) in enumerate(subs):
            rows = []
            for j, req in enumerate(requests):
                sub_req = mapper(i, nsubs, req) if mapper else req
                if sub_req is not None:
                    rows.append(((i, j), sub_req))
            leg_rows.append(rows)
        locked = []
        try:
            legs = []
            for i, (ch, mapper, merger) in enumerate(subs):
                if not leg_rows[i]:
                    continue
                ch._ring_lock.acquire()
                locked.append(ch._ring_lock)
                legs.append((ch._submission_ring(), leg_rows[i]))
            resolved = (
                _ring.call_many_grouped(legs, method_spec, timeout_ms)
                if legs
                else {}
            )
        finally:
            for lock in locked:
                lock.release()
        results = []
        for j in range(n):
            response = method_spec.response_class()
            fails = 0
            skips = 0
            first_err = None
            for i, (ch, mapper, merger) in enumerate(subs):
                leg = resolved.get((i, j))
                if leg is None:
                    skips += 1
                    continue
                if isinstance(leg, _ring.RingFailure):
                    fails += 1
                    if first_err is None:
                        first_err = leg
                    continue
                sub_resp = method_spec.response_class()
                try:
                    sub_resp.ParseFromString(leg)
                    merger(response, sub_resp, i)
                except Exception as e:  # noqa: BLE001
                    log_error("response merger raised: %r", e)
            if skips == nsubs:
                results.append(_ring.RingFailure(
                    errors.EREQUEST, "CallMapper skipped every sub channel"
                ))
            elif fails > self.options.fail_limit:
                results.append(_ring.RingFailure(
                    errors.ETOOMANYFAILS,
                    f"{fails}/{nsubs} sub calls failed"
                    + (
                        f" (first: {first_err.error_text})"
                        if first_err
                        else ""
                    ),
                ))
            else:
                results.append(response.SerializeToString())
        return results

    def _call_many_percall(self, method_spec, requests, timeout_ms,
                           controllers):
        """Whole-window degradation: every request runs through the
        existing call_method fan-out — byte-identical semantics."""
        from incubator_brpc_tpu.client import ring as _ring

        results = []
        for i, req in enumerate(requests):
            ctrl = controllers[i] if controllers is not None else None
            owned = ctrl is None
            if owned:
                ctrl = Controller()
            if timeout_ms is not None and ctrl.timeout_ms is None:
                ctrl.timeout_ms = timeout_ms
            resp = method_spec.response_class()
            self.call_method(method_spec, ctrl, req, resp)
            if ctrl.error_code:
                results.append(
                    _ring.RingFailure(ctrl.error_code, ctrl.error_text())
                )
            else:
                results.append(resp.SerializeToString())
        _ring.fanout_log.record(
            crossings=len(requests) * max(1, self.channel_count()),
            keys=len(requests),
            fallback_calls=len(requests),
        )
        return results


class _FanoutState:
    """Shared completion closure (analog ParallelChannelDone)."""

    def __init__(self, total: int, fail_limit: int):
        self._remaining = total
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._finish = None

    def set_finish(self, fn):
        self._finish = fn

    def on_skip(self):
        self._dec()

    def make_done(self):
        """One once-guarded completion closure per leg: a leg whose
        channel both raises (caller runs the fallback done) AND fires
        its async done later must decrement exactly once — a double
        decrement would make the real last leg miss zero and hang the
        fan-out for the full wait() timeout."""
        fired = [False]
        guard = threading.Lock()

        def _done():
            with guard:
                if fired[0]:
                    return
                fired[0] = True
            self._dec()

        return _done

    def _dec(self):
        with self._lock:
            self._remaining -= 1
            last = self._remaining == 0
        if last:
            try:
                self._finish()
            finally:
                self._event.set()

    def wait(self, timeout: float = 60.0):
        self._event.wait(timeout)


@dataclass
class SelectiveChannelOptions:
    max_retry: int = 1
    timeout_ms: int = 1000


class _GroupStats:
    """Per-sub-channel health for SelectiveChannel's LB: failure-rate
    EMA + live inflight count (a locality-aware-lite signal; reference
    runs a real LB over SubChannels, selective_channel.h:31-52)."""

    __slots__ = ("error_ema", "inflight", "lock")

    _ALPHA = 0.3
    UNHEALTHY = 0.6  # EMA above this → deprioritized

    def __init__(self):
        self.error_ema = 0.0
        self.inflight = 0
        self.lock = threading.Lock()

    def on_start(self):
        with self.lock:
            self.inflight += 1

    def on_done(self, failed: bool):
        with self.lock:
            self.inflight -= 1
            self.error_ema = (
                self._ALPHA * (1.0 if failed else 0.0)
                + (1 - self._ALPHA) * self.error_ema
            )


class SelectiveChannel:
    """LB across channels (server groups) with its own retry layer:
    selection prefers healthy groups (failure-EMA feedback) with the
    lowest inflight, and an RPC's retries never re-pick a group that
    already failed it (reference SelectiveChannel's LB + retry layer)."""

    def __init__(self, options: Optional[SelectiveChannelOptions] = None):
        self.options = options or SelectiveChannelOptions()
        self._channels: List[object] = []
        self._stats: List[_GroupStats] = []
        self._counter = itertools.count()

    def add_channel(self, channel) -> int:
        """Returns a channel handle (its index)."""
        # stats BEFORE channel: a concurrent _select indexes _stats for
        # every index it sees in _channels
        self._stats.append(_GroupStats())
        self._channels.append(channel)
        return len(self._channels) - 1

    def remove_and_destroy_channel(self, handle: int):
        if 0 <= handle < len(self._channels):
            self._channels[handle] = None

    def _select(self, excluded: set) -> Optional[int]:
        """Healthy-first, least-inflight, round-robin tiebreak."""
        live = [
            i for i, c in enumerate(self._channels)
            if c is not None and i not in excluded
        ]
        if not live:
            return None
        healthy = [i for i in live if self._stats[i].error_ema < _GroupStats.UNHEALTHY]
        pool = healthy or live  # all sick: let traffic probe them
        rr = next(self._counter)
        # tiebreak rotates by POSITION in the pool (raw indices can be
        # congruent mod len(pool) and would pin traffic to one group)
        return min(
            enumerate(pool),
            key=lambda kv: (self._stats[kv[1]].inflight, (kv[0] - rr) % len(pool)),
        )[1]

    def call_method(self, method_spec, controller, request, response, done=None):
        if not any(c is not None for c in self._channels):
            controller.set_failed(errors.EINTERNAL, "SelectiveChannel is empty")
            if done:
                done()
            return
        attempts = 1 + max(0, self.options.max_retry)
        start_ns = time.monotonic_ns()

        def run_sync():
            last_ctrl = None
            excluded: set = set()
            for _k in range(attempts):
                idx = self._select(excluded)
                if idx is None:
                    excluded.clear()  # every group tried: allow repeats
                    idx = self._select(excluded)
                    if idx is None:
                        break
                ch = self._channels[idx]
                if ch is None:  # raced remove_and_destroy_channel
                    excluded.add(idx)
                    continue
                stats = self._stats[idx]
                sc = Controller()
                sc.timeout_ms = (
                    controller.timeout_ms
                    if controller.timeout_ms is not None
                    else self.options.timeout_ms
                )
                sub_resp = method_spec.response_class()
                stats.on_start()
                try:
                    ch.call_method(method_spec, sc, request, sub_resp, None)
                finally:
                    stats.on_done(sc.failed())
                last_ctrl = sc
                if not sc.failed():
                    response.CopyFrom(sub_resp)
                    controller.latency_us = (time.monotonic_ns() - start_ns) // 1000
                    return
                excluded.add(idx)
            controller.set_failed(
                last_ctrl.error_code if last_ctrl else errors.EINTERNAL,
                f"all {attempts} group attempts failed: "
                + (last_ctrl.error_text() if last_ctrl else ""),
            )
            controller.latency_us = (time.monotonic_ns() - start_ns) // 1000

        if done is None:
            run_sync()
        else:
            from incubator_brpc_tpu.runtime import scheduler

            def run_async():
                run_sync()
                done()

            scheduler.spawn(run_async)


class PartitionParser:
    """Parse NS tags like "2/5" → (index, count) (reference
    PartitionParser, partition_channel.h)."""

    def parse(self, tag: str):
        try:
            idx, _, cnt = tag.partition("/")
            return int(idx), int(cnt)
        except ValueError:
            return None


class PartitionChannel:
    """ParallelChannel whose sub-channels are the partitions discovered
    from NS tags; DynamicPartitionChannel (dynamic=True) re-partitions
    live as the naming data changes schemes."""

    def __init__(
        self,
        options: Optional[ParallelChannelOptions] = None,
        parser: Optional[PartitionParser] = None,
        dynamic: bool = True,
    ):
        self.options = options or ParallelChannelOptions()
        self._parser = parser or PartitionParser()
        self._dynamic = dynamic
        self._lock = threading.Lock()
        self._partitions: List[object] = []  # index -> sub Channel-like
        self._ns_thread = None
        self._sub_options = None
        self._lb_name = "rr"  # init() overrides; manual feeders
        # (on_servers_changed without init) get a working default

    def init(self, naming_url: str, lb_name: str = "rr", sub_options=None) -> int:
        from incubator_brpc_tpu.client.naming_service import NamingServiceThread

        self._sub_options = sub_options
        self._lb_name = lb_name
        self._ns_thread = NamingServiceThread.get(naming_url)
        if self._ns_thread is None:
            return errors.EREQUEST
        self._ns_thread.add_watcher(self)
        return 0

    def on_servers_changed(self, nodes):
        """Group nodes by partition tag i/N and (re)build sub channels."""
        groups = {}
        max_count = 0
        for node in nodes:
            parsed = self._parser.parse(node.tag)
            if parsed is None:
                continue
            idx, cnt = parsed
            max_count = max(max_count, cnt)
            groups.setdefault(idx, []).append(node)
        with self._lock:
            if not self._dynamic and self._partitions:
                # static variant keeps its first scheme AND its channel
                # objects: a fan-out burst snapshots the partition list
                # at issue time, so rebuilding fresh channels here would
                # leave in-flight legs on orphaned channels (whose late
                # completions nobody owns) while the next call fans out
                # over cold ones — refresh membership in place instead
                # (exactly-once per shard across a membership flap)
                for i, part in enumerate(self._partitions):
                    if isinstance(part, _ManualClusterChannel):
                        part.set_nodes(groups.get(i, []))
                return
            new_parts = []
            for i in range(max_count):
                part = _ManualClusterChannel(self._lb_name, self._sub_options)
                part.set_nodes(groups.get(i, []))
                new_parts.append(part)
            self._partitions = new_parts

    def partition_count(self) -> int:
        return len(self._partitions)

    def call_method(self, method_spec, controller, request, response, done=None):
        with self._lock:
            parts = list(self._partitions)
        pc = ParallelChannel(
            ParallelChannelOptions(
                fail_limit=self.options.fail_limit,
                timeout_ms=self.options.timeout_ms,
            )
        )
        for part in parts:
            pc.add_channel(part)
        pc.call_method(method_spec, controller, request, response, done)


class DynamicPartitionChannel(PartitionChannel):
    """Partition channel where MULTIPLE partition schemes coexist while
    naming data migrates (reference DynamicPartitionChannel +
    DynPartLoadBalancer, policy/dynpart_load_balancer.cpp:44-162).

    Servers tagged 0/3,1/3,2/3 and 0/4..3/4 form TWO schemes; every
    request picks one scheme with probability proportional to its LIVE
    server count (the dynpart weighting), then fans out across that
    scheme's partitions.  Rolling a fleet from 3-partition to
    4-partition therefore shifts traffic gradually with capacity —
    no flag flip, no thundering cutover."""

    class _SchemeEntry:
        """One selectable partition scheme, fed to DynPartLB with a
        LIVE weight callable (the schan sub-channel + GetSubChannelWeight
        pairing of the reference)."""

        __slots__ = ("count", "parts", "live")

        def __init__(self, count, parts, live):
            self.count = count
            self.parts = parts
            self.live = live

        def dynpart_weight(self):
            return self.live

    def __init__(
        self,
        options: Optional[ParallelChannelOptions] = None,
        parser: Optional[PartitionParser] = None,
    ):
        from incubator_brpc_tpu.client.load_balancer import DynPartLB

        super().__init__(options=options, parser=parser, dynamic=True)
        # scheme_count -> (parts, live_server_total, complete)
        self._schemes = {}
        # selection among complete schemes runs through the DynPart LB
        self._dynpart_lb = DynPartLB()

    def on_servers_changed(self, nodes):
        groups = {}  # N -> {idx: [nodes]}
        for node in nodes:
            parsed = self._parser.parse(node.tag)
            if parsed is None:
                continue
            idx, cnt = parsed
            if cnt <= 0 or idx < 0 or idx >= cnt:
                continue
            groups.setdefault(cnt, {}).setdefault(idx, []).append(node)
        new_schemes = {}
        for cnt, idxmap in groups.items():
            parts = []
            for i in range(cnt):
                part = _ManualClusterChannel(self._lb_name, self._sub_options)
                part.set_nodes(idxmap.get(i, []))
                parts.append(part)
            live = sum(len(v) for v in idxmap.values())
            complete = all(i in idxmap for i in range(cnt))
            new_schemes[cnt] = (parts, live, complete)
        with self._lock:
            self._schemes = new_schemes
            # the LB selects among COMPLETE schemes, each weighted by
            # its live server count (weight callables read `entry.live`)
            self._dynpart_lb.reset_servers(
                [
                    self._SchemeEntry(c, parts, live)
                    for c, (parts, live, ok) in new_schemes.items()
                    if ok and live > 0
                ]
            )
            # keep the base-class view pointing at the largest complete
            # scheme so partition_count() stays meaningful
            best = max(
                (c for c, (_, _, ok) in new_schemes.items() if ok),
                default=0,
            )
            self._partitions = new_schemes.get(best, ([], 0, False))[0]

    def scheme_counts(self):
        """{partition_count: live_server_total} for complete schemes."""
        with self._lock:
            return {
                c: live
                for c, (_, live, ok) in self._schemes.items()
                if ok
            }

    def call_method(self, method_spec, controller, request, response, done=None):
        from incubator_brpc_tpu.client.load_balancer import SelectIn

        entry = self._dynpart_lb.select_server(SelectIn())
        if entry is None:
            controller.set_failed(
                errors.EFAILEDSOCKET, "no complete partition scheme"
            )
            if done:
                done()
            return
        parts = entry.parts
        pc = ParallelChannel(
            ParallelChannelOptions(
                fail_limit=self.options.fail_limit,
                timeout_ms=self.options.timeout_ms,
            )
        )
        for part in parts:
            pc.add_channel(part)
        pc.call_method(method_spec, controller, request, response, done)


class ShardRoutedChannel(PartitionChannel):
    """The shard-aware PartitionChannel of the pod-scale parameter
    server (docs/sharded_ps.md): partitions are SHARDS that own a slice
    of the keyspace/parameter rows, and the channel routes by contract:

    * **routed methods** (the default — Get/Put and anything else):
      one RPC to the key's owning shard, nothing to the others.  The
      shard index is a pure function of (seed, key, shard count) —
      murmur3 — so the same key maps to the same shard across channel
      rebuilds and process restarts.
    * **fan-out methods** (``set_fanout``): ONE fan-out across every
      shard, issued inside a single fabric delivery burst (each
      destination port's completion queue wakes once for the whole
      fan-out), with per-leg rpcz client spans joined under one
      fan-out root span.  ``prepare_leg`` stamps each leg's sub
      controller (e.g. slicing the request attachment by shard rows);
      ``merge`` folds the per-shard partial results — for tensor
      partials, one fused device op (ops/merge), the host-side analog
      of the collective merge the in-mesh lowering uses.

    Failure semantics are the combo-channel contract (PR 3): a dead
    shard fails only its leg; ``fail_limit`` bounds tolerated leg
    failures, beyond it the parent fails ``ETOOMANYFAILS`` — always
    ERPC codes, never hangs.

    Shards come from ``set_partitions`` (explicit channels),
    ``from_endpoints`` (e.g. ``ici_endpoints()`` — the mesh topology as
    the shard map), or the inherited naming-layer ``init`` (NS tags
    "i/N" define shard identity).
    """

    def __init__(
        self,
        options: Optional[ParallelChannelOptions] = None,
        parser: Optional[PartitionParser] = None,
        key_fn: Optional[Callable[[object], str]] = None,
        seed: int = 0,
    ):
        super().__init__(options=options, parser=parser, dynamic=False)
        self._key_fn = key_fn or (
            lambda req: str(getattr(req, "message", "") or "")
        )
        self._seed = int(seed)
        # method_name -> (prepare_leg, merge); see set_fanout
        self._fanout: dict = {}

    @classmethod
    def from_endpoints(
        cls,
        endpoints,
        options: Optional[ParallelChannelOptions] = None,
        channel_options=None,
        **kw,
    ) -> "ShardRoutedChannel":
        """One sub-channel per endpoint, in endpoint order — pass
        ``parallel.mesh.ici_endpoints(mesh)`` to shard across the mesh
        coordinates (chip-major within each slice: consecutive shards
        ride the ICI axis first, per the mesh convention)."""
        from incubator_brpc_tpu.client.channel import Channel

        ch = cls(options=options, **kw)
        subs = []
        for ep in endpoints:
            sub = Channel(channel_options)
            rc = sub.init(str(ep))
            if rc != 0:
                raise ValueError(f"cannot init shard channel to {ep}")
            subs.append(sub)
        ch.set_partitions(subs)
        return ch

    def set_partitions(self, channels) -> None:
        with self._lock:
            self._partitions = list(channels)

    def partitions(self) -> List[object]:
        with self._lock:
            return list(self._partitions)

    def set_fanout(self, method_name: str, prepare_leg=None, merge=None):
        """Mark `method_name` as a fan-out method.

        prepare_leg(i, n, request, parent_ctrl, sub_ctrl) -> sub request
          (or None to skip that shard); it may stamp sub_ctrl (slice the
          parent's request attachment, set request_code, ...).  Raising
          fails the parent EREQUEST before any leg is issued.
        merge(parent_ctrl, parent_resp, sub_ctrls, sub_resps) -> None
          folds successful legs (failed legs arrive as failed
          controllers; with fail_limit > 0 the merge sees a partial
          set — the degraded-mode contract).
        """
        self._fanout[method_name] = (prepare_leg, merge)

    def shard_of(self, key: str, n: Optional[int] = None) -> int:
        """Owning shard of `key` — pure in (seed, key, n), so the
        mapping survives restarts as long as the shard count and
        ordering do (endpoint order / NS tag index)."""
        from incubator_brpc_tpu.utils.hashes import murmur3_32

        if n is None:
            n = self.partition_count()
        if n <= 0:
            raise ValueError("ShardRoutedChannel has no shards")
        return murmur3_32(str(key).encode(), seed=self._seed) % n

    def call_method(self, method_spec, controller, request, response, done=None):
        with self._lock:
            parts = list(self._partitions)
        if not parts:
            controller.set_failed(
                errors.EINTERNAL, "ShardRoutedChannel has no shards"
            )
            if done:
                done()
            return
        fan = self._fanout.get(method_spec.method_name)
        if fan is not None and len(parts) > 1:
            return self._call_fanout(
                parts, fan, method_spec, controller, request, response, done
            )
        # routed: exactly one RPC, to the owning shard (single-shard
        # deployments route everything — a fan-out over one shard is
        # the same call with extra steps)
        idx = self.shard_of(self._key_fn(request), len(parts)) if len(parts) > 1 else 0
        controller.shard_index = idx
        parts[idx].call_method(method_spec, controller, request, response, done)

    def call_many(self, method_spec, requests, timeout_ms=None,
                  controllers=None):
        """Windowed shard fan-out: route each request to its owning
        shard (same murmur3 contract as call_method) and submit every
        shard's group as ONE sub-window through that shard channel's
        submission ring — a 64-key window crosses the C boundary once
        per SHARD, not once per key.  All shard sub-windows are flushed
        before any is harvested, so they are in flight concurrently.
        Results return in request order: response bytes per success, a
        ring.RingFailure per failure (the Channel.call_many contract).

        Caller-provided controllers degrade THAT call to the routed
        per-call path (its controller keeps every per-call override);
        shard channels without a ring surface degrade their group per
        call — byte-identical ERPC semantics either way, recorded as
        fan-out fallback_calls in the step log."""
        from incubator_brpc_tpu.client import ring as _ring

        n = len(requests)
        if controllers is not None and len(controllers) != n:
            raise ValueError("controllers must match requests 1:1")
        if n == 0:
            return []
        with self._lock:
            parts = list(self._partitions)
        if not parts:
            return [
                _ring.RingFailure(
                    errors.EINTERNAL, "ShardRoutedChannel has no shards"
                )
                for _ in requests
            ]
        results = [None] * n
        percall = []   # (orig idx, request, controller)
        grouped = {}   # shard idx -> [(orig idx, request), ...]
        nparts = len(parts)
        for i, req in enumerate(requests):
            ctrl = controllers[i] if controllers is not None else None
            if ctrl is not None:
                percall.append((i, req, ctrl))
                continue
            idx = (
                self.shard_of(self._key_fn(req), nparts)
                if nparts > 1
                else 0
            )
            grouped.setdefault(idx, []).append((i, req))
        ring_legs = []   # (sub channel, rows) with a ring surface
        plain_rows = []  # (sub channel, rows) without one
        for idx in sorted(grouped):
            sub = parts[idx]
            rows = grouped[idx]
            if hasattr(sub, "_submission_ring") and hasattr(sub, "_ring_lock"):
                ring_legs.append((sub, rows))
            else:
                plain_rows.append((sub, rows))
        if ring_legs:
            # locks taken in shard-index order (deterministic, so two
            # concurrent fan-outs over overlapping shards cannot
            # deadlock), held until every leg drained: the sub-windows
            # share the channels' call_many rings
            locked = []
            try:
                legs = []
                for sub, rows in ring_legs:
                    sub._ring_lock.acquire()
                    locked.append(sub._ring_lock)
                    legs.append((sub._submission_ring(), rows))
                for orig, res in _ring.call_many_grouped(
                    legs, method_spec, timeout_ms
                ).items():
                    results[orig] = res
            finally:
                for lock in locked:
                    lock.release()
        fallback_calls = 0
        for sub, rows in plain_rows:
            fallback_calls += len(rows)
            for orig, req in rows:
                ctrl = Controller()
                if timeout_ms is not None:
                    ctrl.timeout_ms = timeout_ms
                resp = method_spec.response_class()
                sub.call_method(method_spec, ctrl, req, resp)
                results[orig] = (
                    _ring.RingFailure(ctrl.error_code, ctrl.error_text())
                    if ctrl.error_code
                    else resp.SerializeToString()
                )
        for orig, req, ctrl in percall:
            fallback_calls += 1
            resp = method_spec.response_class()
            self.call_method(method_spec, ctrl, req, resp)
            results[orig] = (
                _ring.RingFailure(ctrl.error_code, ctrl.error_text())
                if ctrl.error_code
                else resp.SerializeToString()
            )
        if plain_rows or percall:
            _ring.fanout_log.record(
                crossings=fallback_calls,
                keys=fallback_calls,
                fallback_calls=fallback_calls,
            )
        return results

    def _call_fanout(
        self, parts, fan, method_spec, controller, request, response, done
    ):
        from incubator_brpc_tpu.observability.span import (
            Span,
            swap_current_span,
        )

        prepare_leg, merge = fan
        n = len(parts)
        start_ns = time.monotonic_ns()
        fanout_span = Span.create_client(
            method_spec.service_name, method_spec.method_name
        )
        if fanout_span is not None:
            fanout_span.annotate(f"shard fan-out over {n} shards")
        state = _FanoutState(n, self.options.fail_limit)
        sub_ctrls: List[Optional[Controller]] = []
        sub_resps: List[object] = []
        sub_reqs: List[object] = []

        def finish():
            fails = sum(
                1 for sc in sub_ctrls if sc is not None and sc.failed()
            )
            skips = sum(1 for sc in sub_ctrls if sc is None)
            if skips == n:
                controller.set_failed(
                    errors.EREQUEST, "prepare_leg skipped every shard"
                )
            elif fails > self.options.fail_limit:
                first_err = next(
                    (sc for sc in sub_ctrls if sc is not None and sc.failed()),
                    None,
                )
                controller.set_failed(
                    errors.ETOOMANYFAILS,
                    f"{fails}/{n} shard legs failed"
                    + (
                        f" (first: {first_err.error_text()})"
                        if first_err
                        else ""
                    ),
                )
            else:
                try:
                    if merge is not None:
                        merge(controller, response, sub_ctrls, sub_resps)
                    else:
                        for i, sc in enumerate(sub_ctrls):
                            if sc is not None and not sc.failed():
                                _default_merger(response, sub_resps[i], i)
                except Exception as e:  # noqa: BLE001
                    log_error("shard merge raised: %r", e)
                    controller.set_failed(
                        errors.EINTERNAL, f"shard merge failed: {e}"
                    )
            controller.latency_us = (time.monotonic_ns() - start_ns) // 1000
            _note_fanout(method_spec, sub_ctrls)
            if fanout_span is not None:
                fanout_span.end(controller.error_code)
            if done is not None:
                try:
                    done()
                except Exception as e:  # noqa: BLE001
                    log_error("ShardRoutedChannel done raised: %r", e)

        state.set_finish(finish)
        for i in range(n):
            sc = Controller()
            sc.timeout_ms = (
                controller.timeout_ms
                if controller.timeout_ms is not None
                else self.options.timeout_ms
            )
            try:
                sub_req = (
                    prepare_leg(i, n, request, controller, sc)
                    if prepare_leg is not None
                    else request
                )
            except Exception as e:  # noqa: BLE001
                controller.set_failed(
                    errors.EREQUEST, f"prepare_leg failed: {e}"
                )
                if fanout_span is not None:
                    fanout_span.end(controller.error_code)
                if done:
                    done()
                return
            sub_reqs.append(sub_req)
            if sub_req is None:
                sub_ctrls.append(None)
                sub_resps.append(None)
                continue
            sub_ctrls.append(sc)
            sub_resps.append(method_spec.response_class())
        # one burst, one trace: every leg issues inside a single fabric
        # delivery burst (per-port CQ wakes once for the whole fan-out)
        # with the fan-out span as task-local parent, so per-leg client
        # spans — and the collective legs under them — join one trace
        from incubator_brpc_tpu.parallel.ici import (
            get_fabric,
            ici_pallas_stacked_segments,
        )

        prev_span = (
            swap_current_span(fanout_span) if fanout_span is not None else None
        )
        fabric = get_fabric()
        # on the Pallas data plane, same-shape device payloads of a
        # fan-out burst coalesce into stacked kernel dispatches at the
        # fabric layer — count the coalesced segments so the trace
        # proves the collective lowering fired (or didn't)
        stacked_before = (
            int(ici_pallas_stacked_segments.get_value())
            if fabric.chunk_mode == "pallas" and fanout_span is not None
            else None
        )
        try:
            with fabric.delivery_burst():
                for i in range(n):
                    sc = sub_ctrls[i]
                    if sc is None:
                        state.on_skip()
                        continue
                    leg_done = state.make_done()
                    try:
                        parts[i].call_method(
                            method_spec, sc, sub_reqs[i], sub_resps[i],
                            done=leg_done,
                        )
                    except Exception as e:  # noqa: BLE001
                        # exactly-once per shard even when a leg's
                        # channel raises (e.g. membership flapped and
                        # the partition lost its servers mid-burst):
                        # fail THIS leg and complete it — never orphan
                        # the shared completion, never re-issue.
                        log_error("shard leg call_method raised: %r", e)
                        if not sc.failed():
                            sc.set_failed(
                                errors.EINTERNAL, f"shard leg raised: {e}"
                            )
                        leg_done()
        finally:
            if stacked_before is not None:
                stacked = (
                    int(ici_pallas_stacked_segments.get_value())
                    - stacked_before
                )
                if stacked:
                    fanout_span.annotate(
                        f"pallas stacked fan-out: {stacked} segments "
                        f"coalesced"
                    )
            if fanout_span is not None:
                swap_current_span(prev_span)
        if done is None:
            state.wait()


class DynamicShardChannel:
    """Two `ShardRoutedChannel`s (the OLD N-shard and the NEW M-shard
    scheme) behind one Channel duck-type, routed per-call by the live
    re-sharding migration's phase/epoch (resharding/migration.py,
    docs/resharding.md) — the sharded-store analog of
    DynamicPartitionChannel's scheme coexistence:

    * the **authoritative** scheme is OLD until the migration's epoch
      bump (CUTOVER published through naming), NEW after it.  Every
      call snapshots (authoritative, other) ONCE at entry, so an
      in-flight fan-out finishes on the scheme it started on even if
      the epoch bumps under it — no mixed-scheme fan-out, no
      stale-route EINTERNALs.
    * **fan-out methods** (e.g. Forward) go to the authoritative
      scheme only: every shard of one scheme holds a complete row
      partition, so one scheme is always sufficient and dual fan-out
      would double device work.
    * **writes** (``write_methods``) dual-apply while the migration is
      between DUAL_WRITE and CUTOVER: the authoritative leg decides
      the caller-visible result; the other scheme's leg is best-effort
      (counted, never failing the parent) so keys written mid-COPY are
      already in place on their new owner at cutover.
    * **reads** try the authoritative scheme and, while a migration is
      in flight, fall back to the other scheme on failure — a source
      shard that died mid-COPY serves reads from the dual-written/
      copied replica on the other scheme (counted in
      ``reads_fell_back``).
    """

    WRITE_METHODS = frozenset({"Put", "Set", "Delete"})

    def __init__(self, old_channel, new_channel, view, write_methods=None):
        self._old = old_channel
        self._new = new_channel
        self._view = view
        self._write = (
            frozenset(write_methods)
            if write_methods is not None
            else self.WRITE_METHODS
        )
        # step-log counters (the zero-downtime proof reads these)
        self.reads_fell_back = 0
        self.dual_writes = 0
        self.dual_write_misses = 0  # best-effort leg failed (counted only)
        self._stat_lock = threading.Lock()

    # -- scheme snapshot ----------------------------------------------------
    def channels(self):
        """(authoritative, other) at THIS instant — call once per RPC."""
        if self._view.cut_over():
            return self._new, self._old
        return self._old, self._new

    def epoch(self) -> int:
        return self._view.epoch

    def shard_of(self, key: str) -> int:
        auth, _ = self.channels()
        return auth.shard_of(key)

    def partition_count(self) -> int:
        auth, _ = self.channels()
        return auth.partition_count()

    def set_fanout(self, method_name: str, prepare_leg=None, merge=None):
        """Fan-out config applies to BOTH schemes (each leg count n is
        passed to prepare_leg, so the same slicer serves N and M)."""
        self._old.set_fanout(method_name, prepare_leg, merge)
        self._new.set_fanout(method_name, prepare_leg, merge)

    # -- the routed/dual/fallback call plane --------------------------------
    def call_method(self, method_spec, controller, request, response, done=None):
        primary, other = self.channels()
        m = method_spec.method_name
        if m in getattr(primary, "_fanout", {}):
            # one scheme, snapshot at issue: in-flight fan-outs finish
            # on the scheme they started on across a cutover
            return primary.call_method(
                method_spec, controller, request, response, done
            )
        migrating = self._view.migrating()
        if m in self._write and migrating and self._view.dual_writing():
            return self._call_dual_write(
                primary, other, method_spec, controller, request, response,
                done,
            )
        if migrating:
            return self._call_with_fallback(
                primary, other, method_spec, controller, request, response,
                done,
            )
        return primary.call_method(
            method_spec, controller, request, response, done
        )

    @staticmethod
    def _sub_controller(controller) -> Controller:
        sc = Controller()
        sc.timeout_ms = controller.timeout_ms
        return sc

    @staticmethod
    def _adopt(controller, response, sc, sub_resp):
        """Fold a successful sub-attempt into the parent call."""
        if hasattr(response, "CopyFrom"):
            response.CopyFrom(sub_resp)
        if not sc.response_attachment.empty():
            controller.response_attachment = sc.response_attachment
        controller.latency_us = sc.latency_us
        controller.shard_index = getattr(sc, "shard_index", None)

    def _call_dual_write(
        self, primary, other, method_spec, controller, request, response, done
    ):
        # the request attachment is consumed by the first send: snapshot
        # it up front so the best-effort leg carries its own copy
        attach = (
            controller.request_attachment.to_bytes()
            if not controller.request_attachment.empty()
            else None
        )

        def run_sync():
            primary.call_method(method_spec, controller, request, response)
            sc = self._sub_controller(controller)
            if attach is not None:
                sc.request_attachment.append(attach)
            sub_resp = method_spec.response_class()
            try:
                other.call_method(method_spec, sc, request, sub_resp)
            except Exception as e:  # noqa: BLE001
                log_error("dual-write secondary leg raised: %r", e)
                sc.set_failed(errors.EINTERNAL, str(e))
            with self._stat_lock:
                self.dual_writes += 1
                if sc.failed():
                    self.dual_write_misses += 1

        if done is None:
            run_sync()
        else:
            from incubator_brpc_tpu.runtime import scheduler

            def run_async():
                run_sync()
                done()

            scheduler.spawn(run_async)

    def _call_with_fallback(
        self, primary, other, method_spec, controller, request, response, done
    ):
        attach = (
            controller.request_attachment.to_bytes()
            if not controller.request_attachment.empty()
            else None
        )

        def run_sync():
            sc = self._sub_controller(controller)
            if attach is not None:
                sc.request_attachment.append(attach)
            sub_resp = method_spec.response_class()
            try:
                primary.call_method(method_spec, sc, request, sub_resp)
            except Exception as e:  # noqa: BLE001
                log_error("primary scheme read raised: %r", e)
                sc.set_failed(errors.EINTERNAL, str(e))
            if not sc.failed():
                self._adopt(controller, response, sc, sub_resp)
                return
            sc2 = self._sub_controller(controller)
            if attach is not None:
                sc2.request_attachment.append(attach)
            sub_resp2 = method_spec.response_class()
            try:
                other.call_method(method_spec, sc2, request, sub_resp2)
            except Exception as e:  # noqa: BLE001
                log_error("fallback scheme read raised: %r", e)
                sc2.set_failed(errors.EINTERNAL, str(e))
            if not sc2.failed():
                self._adopt(controller, response, sc2, sub_resp2)
                with self._stat_lock:
                    self.reads_fell_back += 1
                return
            # both schemes failed: surface the AUTHORITATIVE error
            controller.set_failed(
                sc.error_code,
                f"both schemes failed (authoritative: {sc.error_text()}; "
                f"fallback: {sc2.error_text()})",
            )

        if done is None:
            run_sync()
        else:
            from incubator_brpc_tpu.runtime import scheduler

            def run_async():
                run_sync()
                done()

            scheduler.spawn(run_async)


class ManualClusterChannel:
    """A Channel over a manually-fed node set (one partition): no
    naming thread — ``set_nodes`` IS the membership feed.  The
    replication tier's building block: per-group read channels (hedged,
    mesh-locality) and leader channels are ManualClusterChannels whose
    node sets the ReplicatedShardChannel refreshes off the group's
    ``members_version``."""

    def __init__(self, lb_name: str, options=None):
        from incubator_brpc_tpu.client.channel import Channel, ChannelOptions
        from incubator_brpc_tpu.client.lb_with_naming import LoadBalancerWithNaming
        from incubator_brpc_tpu.client.load_balancer import create_load_balancer

        self._channel = Channel(options)
        self._channel.protocol = None
        lb = LoadBalancerWithNaming()
        lb._lb = create_load_balancer(lb_name)
        self._lbwn = lb
        # bind manually: no NS thread; set_nodes feeds membership
        from incubator_brpc_tpu.global_init import global_init
        from incubator_brpc_tpu.protocols import find_protocol

        global_init()
        self._channel.protocol = find_protocol(self._channel.options.protocol)
        self._channel._lb = lb
        self._channel._init_done = True

    def set_nodes(self, nodes):
        self._lbwn.on_servers_changed(list(nodes))

    def call_method(self, method_spec, controller, request, response, done=None):
        self._channel.call_method(method_spec, controller, request, response, done)


#: pre-PR-18 private name — kept for in-tree callers
_ManualClusterChannel = ManualClusterChannel


def session_channel(prefill, replicas, coords=None):
    """Factory for the serving tier's combo plane: a
    ``serving/router.SessionChannel`` routing a session's prefill to
    the prefill tier and its decode legs across ``replicas`` with
    live migration (docs/serving.md).  Lives behind a factory so
    importing combo.py stays jax-free; the class is also importable
    lazily as ``combo.SessionChannel``."""
    from incubator_brpc_tpu.serving.router import SessionChannel

    return SessionChannel(prefill, replicas, coords=coords)


def __getattr__(name):
    if name == "SessionChannel":
        from incubator_brpc_tpu.serving.router import SessionChannel

        return SessionChannel
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
