"""Client stack (analog of reference Channel/Controller + policy/)."""

from incubator_brpc_tpu.client.controller import Controller  # noqa: F401
from incubator_brpc_tpu.client.channel import Channel, ChannelOptions  # noqa: F401
