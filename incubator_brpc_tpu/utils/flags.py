"""Runtime flags — the gflags analog.

The reference configures everything through ~146 gflags;
BRPC_VALIDATE_GFLAG marks flags hot-reloadable and the /flags builtin
service edits them over HTTP at runtime (reloadable_flags.h:28-60,
builtin/flags_service.h:28). Same model here: define_flag registers a
typed flag; a validator makes it reloadable; /flags lists and sets.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


@dataclass
class Flag:
    name: str
    value: Any
    default: Any
    help: str = ""
    validator: Optional[Callable[[Any], bool]] = None  # non-None => reloadable

    @property
    def reloadable(self) -> bool:
        return self.validator is not None


_flags: Dict[str, Flag] = {}
_lock = threading.Lock()


def define_flag(name: str, default, help: str = "", validator=None) -> Flag:
    with _lock:
        if name in _flags:
            return _flags[name]
        f = Flag(name, default, default, help, validator)
        _flags[name] = f
        return f


def get_flag(name: str, default=None):
    f = _flags.get(name)
    return f.value if f else default


def set_flag(name: str, value, force: bool = False) -> bool:
    """Runtime update; only reloadable flags accept it (the /flags
    service path). Values are coerced to the default's type.
    ``force=True`` is the PROGRAMMATIC override for non-reloadable
    flags (startup configuration in operator code) — the HTTP /flags
    path never passes it, so security-sensitive flags stay
    operator-only like the reference's non-validated gflags."""
    f = _flags.get(name)
    if f is None or (not f.reloadable and not force):
        return False
    try:
        if isinstance(f.default, bool):
            value = str(value).lower() in ("1", "true", "yes", "on")
        elif isinstance(f.default, int):
            value = int(value)
        elif isinstance(f.default, float):
            value = float(value)
        else:
            value = str(value)
    except (TypeError, ValueError):
        return False
    if f.validator is not None and not f.validator(value):
        return False
    f.value = value
    return True


def list_flags() -> Dict[str, Flag]:
    return dict(_flags)


# framework flags (mirroring commonly-tuned reference gflags)
define_flag(
    "max_body_size", 2 << 30, "max message body bytes", validator=lambda v: v > 0
)
define_flag(
    "health_check_interval_s", 1.0, "failed-node probe interval",
    validator=lambda v: v > 0,
)
define_flag(
    "circuit_breaker_error_rate", 0.5, "EMA error rate that isolates a node",
    validator=lambda v: 0 < v <= 1,
)
define_flag("rpcz_enabled", True, "collect rpcz spans", validator=lambda v: True)
# -event_dispatcher_num analog (event_dispatcher.cpp:30-45).  NOT
# reloadable: the epoll-loop pool is sized once at first socket
# registration — resizing live would strand fds on dead loops.
# Operators set it via set_flag(..., force=True) before any socket.
define_flag(
    "event_dispatcher_num", 1,
    "number of epoll event-dispatcher loops (fd-hashed)",
)
define_flag(
    "enable_dir_service",
    False,
    "serve the /dir filesystem browser (reference -enable_dir_service; "
    "default off: it reads any path with the server's permissions). "
    "NOT hot-reloadable: enabling filesystem read must be operator "
    "code (set_flag(..., force=True)), never a /flags?setvalue request",
)
define_flag(
    "rpcz_db_path",
    "",
    "persist rpcz spans to this sqlite file (reference: SpanDB/leveldb); "
    "empty = in-memory ring only",
    validator=lambda v: True,
)
define_flag(
    "socket_max_unwritten_bytes", 64 << 20, "EOVERCROWDED threshold",
    validator=lambda v: v > 0,
)
