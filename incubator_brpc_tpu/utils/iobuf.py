"""IOBuf — zero-copy, non-contiguous, refcounted segmented buffer.

TPU-native rebuild of butil::IOBuf (reference: butil/iobuf.h:61-111,
iobuf.cpp). The universal payload type of the framework: every wire
message, attachment, and stream chunk is an IOBuf.

Design (kept from the reference):
- A buffer is a sequence of *block refs*; each ref is a (block, offset,
  length) window into a shared, refcounted block. Slicing (``cutn``,
  ``pop_front``) moves refs, never bytes.
- Blocks come from a thread-local block cache (reference iobuf.cpp
  per-thread block list); CPython object refcounting plays the role of
  the reference's manual block refcounts.
- ``cut_into_socket`` / ``append_from_socket`` do vectored IO
  (reference cut_into_file_descriptor / append_from_file_descriptor).

TPU-first extension (the point of the rebuild): a ref may be a
*DeviceRef* holding an HBM-resident ``jax.Array`` instead of host bytes
(the north-star "IOBuf payloads map zero-copy into HBM-resident XLA
buffers"). Device refs flow through the framework untouched; the ICI
transport hands the array to XLA without ever materializing host bytes,
while TCP/DCN transports materialize lazily on first byte access.
"""

from __future__ import annotations

import ssl as _ssl
import threading
from collections import deque
from typing import Iterable, List, Optional, Tuple

DEFAULT_BLOCK_SIZE = 8192  # reference IOBUF_BLOCK_SIZE = 8KB (iobuf.cpp)
MAX_BLOCKS_PER_CACHE = 64
_SSL_LOCK_GUARD = threading.Lock()  # creation guard for per-socket locks


class Block:
    """A refcounted byte block.

    CPython refcounting stands in for the reference's manual block
    refcounts; when the last IOBuf ref drops, ``__del__`` recycles the
    backing bytearray into a thread-local cache (the storage, not the
    Block object, so recycling keeps working across GC generations).
    """

    __slots__ = ("data", "size", "cap")

    def __init__(self, cap: int = DEFAULT_BLOCK_SIZE, data: Optional[bytearray] = None):
        self.data = data if data is not None else bytearray(cap)
        self.size = 0  # bytes filled; [size, cap) is writable tail space
        self.cap = cap

    @property
    def left_space(self) -> int:
        return self.cap - self.size

    def __del__(self):
        try:
            if self.cap == DEFAULT_BLOCK_SIZE:
                cache = _tl_cache
                if len(cache.storages) < MAX_BLOCKS_PER_CACHE:
                    cache.returned += 1
                    cache.storages.append(self.data)
        except Exception:
            pass  # interpreter shutdown


class _TLBlockCache(threading.local):
    def __init__(self):
        self.storages: List[bytearray] = []
        self.got = 0
        self.returned = 0


_tl_cache = _TLBlockCache()


def acquire_block(min_cap: int = DEFAULT_BLOCK_SIZE) -> Block:
    cache = _tl_cache
    if min_cap <= DEFAULT_BLOCK_SIZE and cache.storages:
        cache.got += 1
        return Block(DEFAULT_BLOCK_SIZE, data=cache.storages.pop())
    return Block(max(min_cap, DEFAULT_BLOCK_SIZE))


class BlockRef:
    """A (block, offset, length) window. Analog of butil::IOBuf::BlockRef."""

    __slots__ = ("block", "offset", "length")

    def __init__(self, block: Block, offset: int, length: int):
        self.block = block
        self.offset = offset
        self.length = length

    def view(self) -> memoryview:
        return memoryview(self.block.data)[self.offset : self.offset + self.length]


class UserRef:
    """Zero-copy ref over user-owned bytes/memoryview (append_user_data)."""

    __slots__ = ("mv", "offset", "length")

    def __init__(self, data, offset: int = 0, length: Optional[int] = None):
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        self.mv = mv
        self.offset = offset
        self.length = len(mv) - offset if length is None else length

    def view(self) -> memoryview:
        return self.mv[self.offset : self.offset + self.length]


class DeviceRef:
    """An HBM-resident payload segment: a jax.Array standing in for bytes.

    The ICI transport ships the array via XLA device-to-device transfer;
    a host transport (TCP) materializes bytes lazily. ``offset/length``
    window into the array's byte representation so cutn/pop_front keep
    zero-copy semantics at the ref level even for device payloads.
    """

    # __weakref__: the ICI fabric pins a weakref.finalize on placed
    # refs so the HBM profiler's in-flight charge releases with the ref
    __slots__ = ("array", "offset", "length", "_host", "csum", "__weakref__")

    def __init__(self, array, offset: int = 0, length: Optional[int] = None):
        self.array = array
        nbytes = int(array.nbytes)
        self.offset = offset
        self.length = nbytes - offset if length is None else length
        self._host = None
        # device-resident transmit checksum, set by the ICI fabric's
        # copy+verify delivery (ops/transfer.transmit_array); never
        # fetched on the hot path
        self.csum = None

    def _materialize(self) -> memoryview:
        if self._host is None:
            import numpy as np

            from incubator_brpc_tpu.analysis.device_witness import (
                allowed_transfer,
            )

            # the one sanctioned host-materialization choke point for
            # device segments: every wire serializer funnels through
            # here (manifested as iobuf.host-view)
            with allowed_transfer("iobuf.host-view"):
                self._host = memoryview(np.asarray(self.array)).cast("B")
        return self._host

    def view(self) -> memoryview:
        return self._materialize()[self.offset : self.offset + self.length]

    def whole_array(self):
        """The underlying array iff this ref covers it fully (zero-copy path)."""
        if self.offset == 0 and self.length == int(self.array.nbytes):
            return self.array
        return None


class IOBuf:
    """Segmented zero-copy buffer (analog butil::IOBuf, iobuf.h:61)."""

    __slots__ = ("_refs", "_size")

    def __init__(self, data=None):
        self._refs: deque = deque()
        self._size = 0
        if data is not None:
            self.append(data)

    # ---- size & inspection ------------------------------------------------
    def __len__(self) -> int:
        return self._size

    @property
    def size(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

    def backing_block_count(self) -> int:
        return len(self._refs)

    def has_device_payload(self) -> bool:
        return any(isinstance(r, DeviceRef) for r in self._refs)

    def device_segments(self) -> List["DeviceRef"]:
        """All device refs (possibly windowed), in order."""
        return [r for r in self._refs if isinstance(r, DeviceRef)]

    def iter_refs(self) -> Tuple:
        """Snapshot of the live ref sequence (BlockRef/UserRef/DeviceRef)
        in order.  Device-aware protocol parsers walk host bytes AROUND
        device segments with this instead of ``copy_to`` — the latter
        would materialize every DeviceRef just to frame the reply.  The
        refs stay owned by this buffer; callers must not mutate them."""
        return tuple(self._refs)

    def device_arrays(self) -> List[object]:
        """Whole jax.Arrays carried by this buffer, in order (ICI fast path).

        Raises ValueError if any device segment has been split by a
        cut/pop — callers must then fall back to device_segments() or
        byte materialization rather than silently losing payload.
        """
        out = []
        for r in self._refs:
            if isinstance(r, DeviceRef):
                a = r.whole_array()
                if a is None:
                    raise ValueError(
                        "IOBuf carries a partially-cut device segment; "
                        "use device_segments() or to_bytes()"
                    )
                out.append(a)
        return out

    # ---- append -----------------------------------------------------------
    def append(self, data) -> None:
        if isinstance(data, IOBuf):
            # Block sharing, no byte copy (IOBuf::append(const IOBuf&)).
            # Ref *objects* are cloned: each IOBuf uniquely owns its refs
            # because cutn/pop_front mutate them in place.
            self._refs.extend(_slice_ref(r, 0, r.length) for r in data._refs)
            self._size += data._size
            return
        if isinstance(data, str):
            data = data.encode()
        # large immutable payloads append BY REFERENCE: copying a 64MB
        # attachment into 1MB blocks costs ~50ms and shatters it into
        # refs the wire chunker then re-joins (bytes are immutable, so
        # the ref stays valid; mutable buffers still copy below)
        if isinstance(data, bytes) and len(data) >= 64 * 1024:
            self.append_user_data(data)
            return
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        n = len(mv)
        if n == 0:
            return
        pos = 0
        # copy into tail block / fresh blocks (IOBuf::append(void const*, size_t))
        while pos < n:
            blk = self._writable_tail(n - pos)
            take = min(blk.left_space, n - pos)
            blk.data[blk.size : blk.size + take] = mv[pos : pos + take]
            last = self._refs[-1] if self._refs else None
            if (
                isinstance(last, BlockRef)
                and last.block is blk
                and last.offset + last.length == blk.size
            ):
                last.length += take
            else:
                self._refs.append(BlockRef(blk, blk.size, take))
            blk.size += take
            pos += take
            self._size += take

    def append_user_data(self, data) -> None:
        """Zero-copy append of caller-owned memory (IOBuf::append_user_data)."""
        ref = UserRef(data)
        if ref.length:
            self._refs.append(ref)
            self._size += ref.length

    def append_device(self, array) -> None:
        """Zero-copy append of an HBM-resident jax.Array (TPU extension)."""
        ref = DeviceRef(array)
        if ref.length:
            self._refs.append(ref)
            self._size += ref.length

    def push_back(self, byte: int) -> None:
        self.append(bytes((byte,)))

    def _writable_tail(self, hint: int) -> Block:
        if self._refs:
            last = self._refs[-1]
            if (
                isinstance(last, BlockRef)
                and last.offset + last.length == last.block.size
                and last.block.left_space > 0
            ):
                return last.block
        return acquire_block(min(max(hint, DEFAULT_BLOCK_SIZE), 1 << 20))

    # ---- cut / pop (zero-copy slicing) ------------------------------------
    def cutn(self, out: Optional["IOBuf"], n: int) -> int:
        """Move first n bytes into `out` (or drop if None). Returns moved count.

        Ref-moving only — no byte copies (IOBuf::cutn, iobuf.cpp).
        """
        n = max(0, min(n, self._size))
        left = n
        while left > 0:
            ref = self._refs[0]
            if ref.length <= left:
                self._refs.popleft()
                if out is not None:
                    out._refs.append(ref)
                    out._size += ref.length
                left -= ref.length
            else:
                if out is not None:
                    head = _slice_ref(ref, 0, left)
                    out._refs.append(head)
                    out._size += left
                ref.offset += left
                ref.length -= left
                left = 0
        self._size -= n
        return n

    def pop_front(self, n: int) -> int:
        return self.cutn(None, n)

    def pop_back(self, n: int) -> int:
        n = max(0, min(n, self._size))
        left = n
        while left > 0:
            ref = self._refs[-1]
            if ref.length <= left:
                self._refs.pop()
                left -= ref.length
            else:
                ref.length -= left
                left = 0
        self._size -= n
        return n

    def clear(self) -> None:
        self._refs.clear()
        self._size = 0

    def swap(self, other: "IOBuf") -> None:
        self._refs, other._refs = other._refs, self._refs
        self._size, other._size = other._size, self._size

    # ---- materialization --------------------------------------------------
    def copy_to(self, n: int = -1, pos: int = 0) -> bytes:
        """Copy up to n bytes starting at pos into a new bytes object."""
        if n < 0:
            n = self._size
        out = bytearray()
        remaining_skip = pos
        remaining = n
        for ref in self._refs:
            if remaining <= 0:
                break
            v = ref.view()
            if remaining_skip >= len(v):
                remaining_skip -= len(v)
                continue
            if remaining_skip:
                v = v[remaining_skip:]
                remaining_skip = 0
            take = min(len(v), remaining)
            out += v[:take]
            remaining -= take
        return bytes(out)

    def to_bytes(self) -> bytes:
        if len(self._refs) == 1:
            return bytes(self._refs[0].view())  # single copy, no bytearray
        return self.copy_to()

    def as_view(self):
        """Contiguous zero-copy view when the buffer is one segment,
        else a single-copy bytes. Hot-path input for pb ParseFromString."""
        if len(self._refs) == 1:
            return self._refs[0].view()
        return self.copy_to()

    def fetch(self, n: int) -> Optional[bytes]:
        """First n bytes without consuming, or None if fewer available."""
        if self._size < n:
            return None
        if self._refs and self._refs[0].length >= n:
            return bytes(self._refs[0].view()[:n])
        return self.copy_to(n)

    def cut_bytes(self, n: int) -> bytes:
        """Consume and return exactly min(n, len) front bytes as bytes —
        the one-copy fast path for small wire fields (headers, meta);
        equivalent to cutn into a scratch IOBuf + to_bytes without the
        intermediate ref bookkeeping."""
        n = min(n, self._size)
        if not n:
            return b""
        ref = self._refs[0]
        if ref.length > n:  # fully inside the first segment: slice in place
            out = bytes(ref.view()[:n])
            ref.offset += n
            ref.length -= n
            self._size -= n
            return out
        if ref.length == n:
            out = bytes(ref.view())
            self._refs.popleft()
            self._size -= n
            return out
        out = self.copy_to(n)
        self.pop_front(n)
        return out

    def views(self) -> List[memoryview]:
        return [r.view() for r in self._refs]

    # ---- vectored socket IO (cut_into_file_descriptor analog) -------------
    @staticmethod
    def _ssl_io_lock(sock) -> threading.Lock:
        """Per-socket lock serializing SSL_read/SSL_write: OpenSSL's
        ``SSL*`` is not thread-safe for concurrent read/write from
        different threads (the epoll dispatcher recv_into races the
        inline-writer/KeepWrite send on pipelined traffic) and CPython's
        ``_ssl`` adds no per-object lock.  Transport TLS sockets are
        non-blocking, so holds are momentary."""
        lock = getattr(sock, "_tpu_ssl_io_lock", None)
        if lock is None:
            with _SSL_LOCK_GUARD:
                lock = getattr(sock, "_tpu_ssl_io_lock", None)
                if lock is None:
                    lock = threading.Lock()
                    sock._tpu_ssl_io_lock = lock
        return lock

    def cut_into_socket(self, sock, max_bytes: int = 1 << 20) -> int:
        """Vectored non-blocking write; consumes written bytes. Returns count
        or raises BlockingIOError when the socket would block immediately.
        TLS sockets (no scatter/gather; want-read/want-write signal EAGAIN)
        take the send() path — the SSLSocket equivalent of the reference's
        SSL_write branch in Socket::DoWrite."""
        if isinstance(sock, _ssl.SSLSocket):
            # coalesce refs into one buffer → one TLS record + syscall
            # per call instead of one per fragment (the ssl module sets
            # SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER, so a rebuilt buffer
            # across WANT_* retries is fine). Cap well under the 1MB
            # plaintext budget: records are ~16KB anyway.
            budget = min(max_bytes, 256 << 10)
            first = next(iter(self._refs), None)
            if first is None:
                return 0
            v = first.view()[:budget]
            if len(v) < budget and len(self._refs) > 1:
                parts = [v]
                total = len(v)
                for ref in list(self._refs)[1:]:
                    w = ref.view()[: budget - total]
                    parts.append(w)
                    total += len(w)
                    if total >= budget:
                        break
                v = b"".join(parts)
            try:
                with self._ssl_io_lock(sock):
                    written = sock.send(v)
            except (_ssl.SSLWantReadError, _ssl.SSLWantWriteError) as e:
                raise BlockingIOError(str(e)) from e
            self.pop_front(written)
            return written
        iov = []
        total = 0
        for ref in self._refs:
            v = ref.view()
            if total + len(v) > max_bytes:
                v = v[: max_bytes - total]
            if len(v):
                iov.append(v)
                total += len(v)
            if total >= max_bytes or len(iov) >= 64:
                break
        if not iov:
            return 0
        written = sock.sendmsg(iov)
        self.pop_front(written)
        return written

    def append_from_socket(self, sock, max_bytes: int = DEFAULT_BLOCK_SIZE) -> int:
        """Non-blocking read into tail block space. Returns bytes read
        (0 = EOF), raises BlockingIOError on EAGAIN (including the TLS
        want-read/want-write signals — SSLError subclasses OSError, so
        without the translation they would read as hard failures)."""
        blk = self._writable_tail(max_bytes)
        space = min(blk.left_space, max_bytes)
        try:
            if isinstance(sock, _ssl.SSLSocket):
                with self._ssl_io_lock(sock):
                    nread = sock.recv_into(
                        memoryview(blk.data)[blk.size : blk.size + space]
                    )
            else:
                nread = sock.recv_into(
                    memoryview(blk.data)[blk.size : blk.size + space]
                )
        except (_ssl.SSLWantReadError, _ssl.SSLWantWriteError) as e:
            raise BlockingIOError(str(e)) from e
        if nread > 0:
            last = self._refs[-1] if self._refs else None
            if (
                isinstance(last, BlockRef)
                and last.block is blk
                and last.offset + last.length == blk.size
            ):
                last.length += nread
            else:
                self._refs.append(BlockRef(blk, blk.size, nread))
            blk.size += nread
            self._size += nread
        return nread

    # ---- dunder -----------------------------------------------------------
    def __eq__(self, other) -> bool:
        if isinstance(other, (bytes, bytearray)):
            return self._size == len(other) and self.to_bytes() == bytes(other)
        if isinstance(other, IOBuf):
            return self._size == other._size and self.to_bytes() == other.to_bytes()
        return NotImplemented

    def __repr__(self) -> str:
        head = self.copy_to(min(32, self._size))
        return f"IOBuf(size={self._size}, head={head!r})"


def _slice_ref(ref, offset: int, length: int):
    if isinstance(ref, BlockRef):
        return BlockRef(ref.block, ref.offset + offset, length)
    if isinstance(ref, UserRef):
        r = UserRef(ref.mv, ref.offset + offset, length)
        return r
    if isinstance(ref, DeviceRef):
        r = DeviceRef(ref.array, ref.offset + offset, length)
        r._host = ref._host
        return r
    raise TypeError(ref)


class IOBufCutter:
    """Fast sequential parser over an IOBuf (analog butil::IOBufCutter).

    Used by protocol parse callbacks to peek fixed headers and cut
    payloads without flattening the buffer.
    """

    def __init__(self, buf: IOBuf):
        self._buf = buf

    def remaining(self) -> int:
        return self._buf.size

    def peek(self, n: int) -> Optional[bytes]:
        return self._buf.fetch(n)

    def cut_bytes(self, n: int) -> Optional[bytes]:
        if self._buf.size < n:
            return None
        out = IOBuf()
        self._buf.cutn(out, n)
        return out.to_bytes()

    def cut_buf(self, n: int) -> Optional[IOBuf]:
        if self._buf.size < n:
            return None
        out = IOBuf()
        self._buf.cutn(out, n)
        return out
