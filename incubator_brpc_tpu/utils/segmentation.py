"""One chunk-segmentation policy for every bulk data path.

The ICI fabric (same-chip Pallas transmit, parallel/ici.py), the DCN
bridge wire encoder (parallel/dcn.py) and the kernel-socket write loop
(transport/socket.py) all move large payloads in bounded chunks; before
this module each carried its own ad-hoc constant and slicer.  The
reference's RDMA endpoint segments its send queue the same single way
for every transport (rdma_endpoint.h:83-137 sq window entries), which
is what makes its credit accounting composable — so the chunk PLANNER
lives here, and the transports only decide what to do per chunk.

Three knobs, one per layer:

- ``WIRE_CHUNK_BYTES``   — host-byte wire chunks (DCN bridge streaming;
  also the kernel-socket per-iteration write cap).  ~4MB: large enough
  to amortize per-chunk syscall/staging cost, small enough that the
  send window (a handful of chunks) bounds memory and a mid-stream
  fault loses little.
- ``DEVICE_CHUNK_BYTES`` — device-payload chunks for the chunked
  copy+checksum transmit (ops/transfer.py): the unit the pipelined ICI
  send double-buffers.  ~8MB: a 64MB frame becomes 8 chunks, enough
  overlap stages to hide per-chunk launch/staging latency without
  shrinking each Pallas grid below its efficient size.
- ``MIN_CHUNKS`` — frames smaller than this many chunks skip chunking
  entirely (whole-frame path): pipelining needs at least two stages in
  flight to overlap anything.

The Pallas DMA transmit (``chunk_mode="pallas"``) adds one on-chip
knob: its double-buffered VMEM staging slots are sized here too
(``PALLAS_STAGE_BYTES``/``fit_stage_rows``), so the kernel's DMA stage
plan is a pure function of the SAME row/block decomposition the fused
and pipelined modes chunk by — one planner, three transports.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

WIRE_CHUNK_BYTES = 4 << 20
DEVICE_CHUNK_BYTES = 8 << 20
MIN_CHUNKS = 2

# Pallas DMA transmit staging (ops/transfer.py device_copy_with_
# checksum_dma): each VMEM staging slot holds up to this many bytes and
# PALLAS_DB_DEPTH slots double-buffer each direction (in + out), so the
# kernel's resident VMEM footprint is ≤ 2 * depth * PALLAS_STAGE_BYTES
# — comfortably inside the ~16MB VMEM the pipelined grids already
# assume, while keeping individual DMAs ≥~2MB (large enough that the
# HBM controller runs at line rate instead of descriptor rate).
PALLAS_STAGE_BYTES = 2 << 20
PALLAS_DB_DEPTH = 2


def fit_stage_rows(rows: int, row_bytes: int, align_rows: int,
                   budget_bytes: int = PALLAS_STAGE_BYTES) -> int:
    """Rows per DMA stage for the Pallas double-buffered transmit.

    The stage is a multiple of ``align_rows`` (the checksum kernel's
    block rows — compute granularity can never straddle a stage) that
    DIVIDES ``rows`` (every stage identical, so the kernel's DMA loop
    has static sizes) and fits ``budget_bytes``.  Falls back to one
    block per stage when nothing larger fits — correctness never
    depends on the budget, only DMA efficiency does."""
    if align_rows <= 0 or rows % align_rows:
        raise ValueError(
            f"rows={rows} not a multiple of align_rows={align_rows}"
        )
    nblocks = rows // align_rows
    k = max(1, budget_bytes // max(1, align_rows * row_bytes))
    k = min(k, nblocks)
    while nblocks % k:
        k -= 1
    return k * align_rows


def plan_chunks(total: int, chunk_bytes: int = WIRE_CHUNK_BYTES) -> List[Tuple[int, int]]:
    """(offset, length) chunk windows covering ``total`` bytes in order.
    The tail chunk may be as small as 1 byte; every other chunk is
    exactly ``chunk_bytes``.  Empty payloads plan zero chunks."""
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    return [
        (off, min(chunk_bytes, total - off))
        for off in range(0, total, chunk_bytes)
    ]


def plan_row_chunks(
    rows: int, row_bytes: int, chunk_bytes: int, align_rows: int = 1
) -> List[Tuple[int, int]]:
    """(row_offset, row_count) chunks for a 2D device payload.

    Chunk boundaries are aligned to ``align_rows`` (the Pallas grid's
    block rows) so a chunked copy+checksum decomposes into the SAME
    block sequence as the whole-frame kernel — the property that makes
    the chained chunk checksum bit-identical to the whole-frame one
    (ops/transfer.device_copy_with_checksum_chunked).  ``rows`` must be
    a multiple of ``align_rows`` (the caller derives align_rows as a
    divisor of rows)."""
    if align_rows <= 0 or rows % align_rows:
        raise ValueError(f"rows={rows} not a multiple of align_rows={align_rows}")
    rows_per = max(1, chunk_bytes // max(1, row_bytes))
    rows_per = max(align_rows, (rows_per // align_rows) * align_rows)
    return [
        (off, min(rows_per, rows - off))
        for off in range(0, rows, rows_per)
    ]


def chunk_buffer(buf, chunk_bytes: int = WIRE_CHUNK_BYTES) -> Iterator[memoryview]:
    """Slice one contiguous buffer into ≤chunk_bytes memoryviews
    (zero-copy)."""
    mv = memoryview(buf)
    for i in range(0, len(mv), chunk_bytes):
        yield mv[i : i + chunk_bytes]


def chunk_views(
    views: Iterable[memoryview], chunk_bytes: int = WIRE_CHUNK_BYTES
) -> Iterator:
    """Emit ~chunk_bytes wire chunks from a list of memoryviews.

    Large views (user/device byte windows) slice zero-copy; runs of
    small views (8KB block refs from IOBuf.append) coalesce via join —
    copying only sub-chunk refs keeps big-payload staging copy-free
    while avoiding one sendall (and, under TLS, one record) per tiny
    ref.  Chunk sizes are approximate: a pending small-ref batch
    flushes early rather than ever swallowing the head of a large
    view."""
    batch, size = [], 0
    for mv in views:
        if len(mv) >= chunk_bytes and batch:
            yield batch[0] if len(batch) == 1 else b"".join(batch)
            batch, size = [], 0
        while len(mv):
            take = mv[: chunk_bytes - size]
            batch.append(take)
            size += len(take)
            mv = mv[len(take):]
            if size >= chunk_bytes:
                yield batch[0] if len(batch) == 1 else b"".join(batch)
                batch, size = [], 0
    if batch:
        yield batch[0] if len(batch) == 1 else b"".join(batch)
