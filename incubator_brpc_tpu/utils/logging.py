"""Framework logging (analog of butil/logging.{h,cc}).

Chromium-style leveled logging with pluggable sink (reference LogSink,
logging.h). Thin over stdlib logging so user processes can integrate,
but with the reference's API shape: LOG(INFO) << ... becomes
log_info(...); CHECK macros become check()/check_eq().
"""

from __future__ import annotations

import logging as _pylog
import sys

_logger = _pylog.getLogger("incubator_brpc_tpu")
if not _logger.handlers:
    _h = _pylog.StreamHandler(sys.stderr)
    _h.setFormatter(
        _pylog.Formatter("%(levelname).1s%(asctime)s %(filename)s:%(lineno)d] %(message)s")
    )
    _logger.addHandler(_h)
    _logger.setLevel(_pylog.WARNING)
    _logger.propagate = False

_sink = None  # custom LogSink; returning True swallows the record


def set_log_sink(sink):
    """Install a custom sink: callable(level:str, msg:str) -> bool.
    Analog of logging::SetLogSink (reference logging.h)."""
    global _sink
    old, _sink = _sink, sink
    return old


def set_min_log_level(level: int) -> None:
    _logger.setLevel(level)


def _emit(level_name: str, level: int, msg: str, *args):
    if args:
        msg = msg % args
    if _sink is not None and _sink(level_name, msg):
        return
    _logger.log(level, msg, stacklevel=3)


def log_verbose(msg, *args):
    _emit("VERBOSE", _pylog.DEBUG, msg, *args)


def log_info(msg, *args):
    _emit("INFO", _pylog.INFO, msg, *args)


def log_warning(msg, *args):
    _emit("WARNING", _pylog.WARNING, msg, *args)


def log_error(msg, *args):
    _emit("ERROR", _pylog.ERROR, msg, *args)


def log_fatal(msg, *args):
    _emit("FATAL", _pylog.CRITICAL, msg, *args)
    raise RuntimeError(msg % args if args else msg)


def check(cond, msg="CHECK failed"):
    if not cond:
        log_fatal(msg)


def check_eq(a, b, msg=""):
    if a != b:
        log_fatal(f"CHECK_EQ failed: {a!r} != {b!r} {msg}")
