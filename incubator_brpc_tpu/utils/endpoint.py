"""EndPoint — address value type, extended to TPU coordinates.

Analog of butil::EndPoint (reference endpoint.h:86): the reference's
extended EndPoint carries ip:port, unix-domain paths, and IPv6; the TPU
rebuild additionally carries ICI coordinates (``ici://slice/chip``) so
the naming layer can resolve TPU slice coordinates (north star:
"brpc's naming-service layer resolves TPU slice coordinates").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class EndPoint:
    host: str = ""
    port: int = 0
    scheme: str = "tcp"  # tcp | uds | ici
    # For ici endpoints: (slice_id, chip_id); chip may be a device ordinal.
    coords: Optional[Tuple[int, int]] = None

    @staticmethod
    def tcp(host: str, port: int) -> "EndPoint":
        return EndPoint(host=host, port=port, scheme="tcp")

    @staticmethod
    def uds(path: str) -> "EndPoint":
        return EndPoint(host=path, scheme="uds")

    @staticmethod
    def ici(slice_id: int, chip_id: int) -> "EndPoint":
        return EndPoint(scheme="ici", coords=(slice_id, chip_id))

    def is_ici(self) -> bool:
        return self.scheme == "ici"

    def sockaddr(self):
        if self.scheme == "tcp":
            return (self.host, self.port)
        if self.scheme == "uds":
            return self.host
        raise ValueError(f"no sockaddr for {self}")

    def __str__(self) -> str:
        return endpoint2str(self)

    def __repr__(self) -> str:
        return f"EndPoint({endpoint2str(self)!r})"


def endpoint2str(ep: EndPoint) -> str:
    """Analog of butil::endpoint2str."""
    if ep.scheme == "uds":
        return f"unix:{ep.host}"
    if ep.scheme == "ici":
        s, c = ep.coords
        return f"ici://slice{s}/chip{c}"
    return f"{ep.host}:{ep.port}"


def str2endpoint(s: str) -> EndPoint:
    """Analog of butil::str2endpoint; accepts host:port, unix:path,
    ici://sliceN/chipM."""
    if s.startswith("unix:"):
        return EndPoint.uds(s[len("unix:") :])
    if s.startswith("ici://"):
        rest = s[len("ici://") :]
        parts = rest.strip("/").split("/")
        if len(parts) != 2 or not parts[0].startswith("slice") or not parts[1].startswith("chip"):
            raise ValueError(f"bad ici endpoint: {s}")
        return EndPoint.ici(int(parts[0][5:]), int(parts[1][4:]))
    host, _, port = s.rpartition(":")
    if not host:
        raise ValueError(f"bad endpoint: {s}")
    return EndPoint.tcp(host, int(port))
