"""ResourcePool / ObjectPool — slab pools addressable by versioned ids.

Analog of butil::ResourcePool (reference resource_pool.h:27) and
butil::ObjectPool (object_pool.h). Sockets, CallId slots, and stream
contexts live here; the versioned 64-bit id makes stale handles fail
address() instead of dereferencing recycled memory (ABA safety).

Id layout follows the reference's SocketId convention (socket.h:335):
``id = (version << 32) | slot``. A slot's version is bumped on every
return_resource, so an id minted before recycling no longer resolves.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")

INVALID_ID = (1 << 64) - 1


class _Slot:
    __slots__ = ("obj", "version")

    def __init__(self):
        self.obj = None
        self.version = 0


class ResourcePool(Generic[T]):
    def __init__(self, factory: Callable[[], T]):
        self._factory = factory
        self._slots: List[_Slot] = []
        self._free: List[int] = []
        self._lock = threading.Lock()

    def get_resource(self) -> tuple[int, T]:
        """Allocate (id, object). Object may be recycled; caller resets it."""
        with self._lock:
            if self._free:
                idx = self._free.pop()
                slot = self._slots[idx]
            else:
                idx = len(self._slots)
                slot = _Slot()
                slot.obj = self._factory()
                self._slots.append(slot)
            return (slot.version << 32) | idx, slot.obj

    def address(self, rid: int) -> Optional[T]:
        """Resolve id → object; None if the slot was recycled (version drift)."""
        idx = rid & 0xFFFFFFFF
        ver = rid >> 32
        slots = self._slots
        if idx >= len(slots):
            return None
        slot = slots[idx]
        if slot.version != ver:
            return None
        return slot.obj

    def return_resource(self, rid: int) -> bool:
        idx = rid & 0xFFFFFFFF
        ver = rid >> 32
        with self._lock:
            if idx >= len(self._slots):
                return False
            slot = self._slots[idx]
            if slot.version != ver:
                return False
            slot.version += 1
            self._free.append(idx)
            return True

    def size(self) -> int:
        return len(self._slots)

    def free_count(self) -> int:
        return len(self._free)


class ObjectPool(Generic[T]):
    """Pool of reusable objects without id addressing (butil::ObjectPool)."""

    def __init__(self, factory: Callable[[], T], max_free: int = 1024):
        self._factory = factory
        self._free: List[T] = []
        self._lock = threading.Lock()
        self._max_free = max_free

    def get_object(self) -> T:
        with self._lock:
            if self._free:
                return self._free.pop()
        return self._factory()

    def return_object(self, obj: T) -> None:
        with self._lock:
            if len(self._free) < self._max_free:
                self._free.append(obj)
