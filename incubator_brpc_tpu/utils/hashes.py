"""Hashes & randoms (analog of butil crc32c/murmurhash3/fast_rand).

crc32c (Castagnoli) matches the reference's butil::crc32c used for
framing checksums; murmur3_32 matches butil::MurmurHash32 used by
consistent-hashing load balancers. A C++ native implementation (see
native/) is used when present; these pure-Python versions are the
always-available fallback and the source of truth for test vectors.
"""

from __future__ import annotations

import random
import struct

# ---- crc32c (Castagnoli, poly 0x1EDC6F41 reflected = 0x82F63B78) ----------
_CRC32C_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC32C_TABLE.append(crc)


_build_table()

_native = None


def _load_native():
    global _native
    if _native is None:
        try:
            from incubator_brpc_tpu.native import lib as _nlib

            _native = _nlib
        except Exception:
            _native = False
    return _native


def crc32c(data: bytes, crc: int = 0) -> int:
    n = _load_native()
    if n:
        return n.crc32c(data, crc)
    crc ^= 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---- murmur3 32-bit (butil::MurmurHash32) ---------------------------------
def murmur3_32(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    nblocks = len(data) // 4
    for i in range(nblocks):
        k = struct.unpack_from("<I", data, i * 4)[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[nblocks * 4 :]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


# ---- fast_rand (butil/fast_rand.h) ----------------------------------------
_rng = random.Random()


def fast_rand() -> int:
    return _rng.getrandbits(64)


def fast_rand_less_than(n: int) -> int:
    return _rng.randrange(n) if n > 0 else 0


def fast_rand_double() -> float:
    return _rng.random()


# ---- fmix64 (counter-mode deterministic hashing) ---------------------------
# Used wherever a decision must be a PURE function of (seed, counter):
# chaos fault schedules (chaos/plan.py) and seeded retry-backoff jitter
# (client/retry.py) — replays reproduce the identical sequence.
_MASK64 = (1 << 64) - 1

# golden-ratio counter stride fed to fmix64 (engine.cpp fault_check
# mirrors it); replay-critical — defined ONCE for all Python users
GOLDEN64 = 0x9E3779B97F4A7C15


def fmix64(x: int) -> int:
    """MurmurHash3's fmix64 finalizer: a high-quality 64-bit mix."""
    x &= _MASK64
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _MASK64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _MASK64
    x ^= x >> 33
    return x
