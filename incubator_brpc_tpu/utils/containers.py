"""Read-mostly containers (analog of butil/containers/).

- DoublyBufferedData: lock-free-for-readers read-mostly data, the
  structure every load balancer's hot SelectServer path reads
  (reference doubly_buffered_data.h:37-51). The CPython rebuild uses
  RCU-style snapshot swapping: readers grab an immutable snapshot
  reference (a single attribute load, atomic under the GIL); writers
  build the next snapshot off to the side and publish it with one store.
  Same reader guarantee (never blocked, never sees a torn value).
- FlatMap: open-addressing map in the reference (flat_map.h:109); dict
  is already an open-addressing hash map in CPython, so FlatMap is a
  thin API-compat shim.
- BoundedQueue: SPSC bounded ring (containers/bounded_queue.h).
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class DoublyBufferedData(Generic[T]):
    def __init__(self, initial: T):
        self._snapshot: T = initial
        self._write_lock = threading.Lock()

    def read(self) -> T:
        """Hot path: single atomic attribute load, never blocks."""
        return self._snapshot

    def modify(self, fn: Callable[[T], T]) -> None:
        """Build next snapshot from the current one and publish atomically.

        `fn` receives the current snapshot and must return the new one
        (it may copy-and-mutate). Serialised across writers.
        """
        with self._write_lock:
            self._snapshot = fn(self._snapshot)

    def modify_inplace(self, copy: Callable[[T], T], mutate: Callable[[T], None]) -> None:
        with self._write_lock:
            nxt = copy(self._snapshot)
            mutate(nxt)
            self._snapshot = nxt


class FlatMap(dict):
    """API-compat shim over dict (reference butil::FlatMap, flat_map.h:109)."""

    def seek(self, key):
        return self.get(key)

    def insert(self, key, value):
        self[key] = value
        return value

    def erase(self, key) -> int:
        return 1 if self.pop(key, _MISSING) is not _MISSING else 0


_MISSING = object()


class BoundedQueue(Generic[T]):
    """Bounded ring buffer (SPSC in the reference; here lock-guarded)."""

    def __init__(self, capacity: int):
        self._buf: list = [None] * capacity
        self._cap = capacity
        self._head = 0
        self._count = 0
        self._lock = threading.Lock()

    def push(self, item: T) -> bool:
        with self._lock:
            if self._count == self._cap:
                return False
            self._buf[(self._head + self._count) % self._cap] = item
            self._count += 1
            return True

    def pop(self) -> Optional[T]:
        with self._lock:
            if not self._count:
                return None
            item = self._buf[self._head]
            self._buf[self._head] = None
            self._head = (self._head + 1) % self._cap
            self._count -= 1
            return item

    def __len__(self) -> int:
        return self._count

    def full(self) -> bool:
        return self._count == self._cap
