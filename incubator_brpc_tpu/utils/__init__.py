"""Base utility layer (analog of brpc's butil, reference src/butil/)."""

from incubator_brpc_tpu.utils.iobuf import IOBuf, IOBufCutter  # noqa: F401
from incubator_brpc_tpu.utils.endpoint import EndPoint  # noqa: F401
from incubator_brpc_tpu.utils.resource_pool import ResourcePool, ObjectPool  # noqa: F401
from incubator_brpc_tpu.utils.containers import (  # noqa: F401
    DoublyBufferedData,
    FlatMap,
    BoundedQueue,
)
