"""Time utilities (analog of butil/time.h).

The reference reads the TSC (cpuwide_time_ns) for ~ns-cost timestamps on
the RPC hot path; CPython's time.monotonic_ns/perf_counter_ns are the
equivalent cheap monotonic clocks here.
"""

from __future__ import annotations

import time


def monotonic_ns() -> int:
    return time.monotonic_ns()


def monotonic_us() -> int:
    return time.monotonic_ns() // 1000


def monotonic_ms() -> int:
    return time.monotonic_ns() // 1_000_000


def gettimeofday_us() -> int:
    return time.time_ns() // 1000


cpuwide_time_ns = monotonic_ns
cpuwide_time_us = monotonic_us


class Timer:
    """Scoped stopwatch (butil::Timer)."""

    def __init__(self):
        self._start = 0
        self._stop = 0

    def start(self):
        self._start = time.perf_counter_ns()
        self._stop = self._start

    def stop(self):
        self._stop = time.perf_counter_ns()

    def n_elapsed(self) -> int:
        return self._stop - self._start

    def u_elapsed(self) -> int:
        return self.n_elapsed() // 1000

    def m_elapsed(self) -> int:
        return self.n_elapsed() // 1_000_000
