"""Framework error codes (analog of reference src/brpc/errno.proto).

Values mirror the reference's numbering so dashboards/docs translate
1:1: client-side 1001-1012, server-side 2001-2004.
"""

ENOSERVICE = 1001  # service not found
ENOMETHOD = 1002  # method not found
EREQUEST = 1003  # bad request
ERPCAUTH = 1004  # authentication failed
ETOOMANYFAILS = 1005  # too many sub-channel failures (ParallelChannel)
EPCHANFINISH = 1006  # ParallelChannel finished
EBACKUPREQUEST = 1007  # backup request fired (internal trigger)
ERPCTIMEDOUT = 1008  # RPC deadline exceeded
EFAILEDSOCKET = 1009  # connection broken during RPC
EHTTP = 1010  # HTTP-level error
# EOVERCROWDED = "THIS SERVER is overloaded — retry elsewhere": raised
# by socket write backpressure AND by every server-side overload shed
# (admission concurrency gate, tier shares, tenant quotas, batch queue
# caps; server/admission.py SHED_CODES).  The retry policy reissues it
# only against a DIFFERENT replica (client/retry.py).
EOVERCROWDED = 1011
ERDMA = 1012  # ICI/accelerator transport error (reference: ERTMP*)

EINTERNAL = 2001  # server internal error
ERESPONSE = 2002  # bad response
ELOGOFF = 2003  # server stopping, rejecting requests
# ELIMIT = "THIS REQUEST is no longer worth serving — drop": its
# deadline expired while queued (batcher deadline-guard shed).  NOT
# retriable: the budget is gone everywhere, not just here.  Overload
# sheds use EOVERCROWDED instead (see docs/overload.md code mapping).
ELIMIT = 2004

ECANCELED = 2005  # call canceled (StartCancel)
ECLOSE = 2006  # connection closed by peer
# ESTALEEPOCH = "THIS WRITE is fenced — its lease epoch is stale": a
# replicated Put/Delete carried an epoch older than the replica
# group's current leader lease (replication/group.py).  NOT retriable
# under the same lease: the old leader must step down; the client-side
# channel re-resolves the leader and reissues under the new epoch
# (docs/replication.md fencing invariant).
ESTALEEPOCH = 2007

_NAMES = {
    v: k
    for k, v in list(globals().items())
    if k.startswith("E") and isinstance(v, int)
}


def error_text(code: int) -> str:
    import os

    if code in _NAMES:
        return _NAMES[code]
    try:
        return os.strerror(code)
    except (ValueError, OverflowError):
        return f"E{code}"
