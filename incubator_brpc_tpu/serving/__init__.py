"""Disaggregated LLM serving: prefill/decode split with HBM-resident
KV state and live session migration (ROADMAP item 4, docs/serving.md).

Three planes:

* ``serving/prefill.py`` — ``PrefillService``: batched (optionally
  mesh-sharded) prompt prefill; ships per-session KV stacks HBM→HBM
  into the cache tier under ``kv:<session>@<epoch>#<layer>`` keys.
* ``serving/decode.py`` — ``DecodeService``: admits a session by
  pulling its KV epoch in one fused DMGET and joining the continuous-
  batched ``DecodeLoop`` mid-stream; streamed-RPC + SSE token fronts;
  EOVERCROWDED shed at ``max_sessions``.
* ``serving/router.py`` — ``SessionChannel``: routes prefill → prefill
  tier, decode → a locality-picked replica; migrates live sessions on
  overload/death/request, re-pulling the SAME cached KV (prefill runs
  exactly once per session, proven by step log).

Plus ``serving/session.py`` (the kv naming grammar + per-session
state/registry, jax-free) and ``serving/metrics.py`` (the
``rpc_serving_*`` exposed variables).

Import-light: nothing here pulls jax — the engines import it lazily
inside device paths, and the builtin/metrics surfaces only touch the
jax-free modules.
"""

from incubator_brpc_tpu.serving.session import (  # noqa: F401
    SessionRecord,
    format_kv_key,
    kv_layer_keys,
    open_session,
    parse_kv_key,
    sessions_snapshot,
)

__all__ = [
    "SessionRecord",
    "format_kv_key",
    "kv_layer_keys",
    "open_session",
    "parse_kv_key",
    "sessions_snapshot",
]
