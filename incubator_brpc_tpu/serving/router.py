"""Session router — prefill→decode orchestration with live migration
(docs/serving.md).

``SessionChannel`` is the client-side combo plane for disaggregated
serving: it routes a session's PREFILL to the prefill tier (once,
ever), its DECODE to a replica picked mesh_locality-style (same-slice
replicas first), and — on decode-replica overload (EOVERCROWDED shed),
death (loop stop / breaker-shaped failure) or an operator ``migrate``
— re-homes the session WITHOUT recomputing prefill:

* **graceful handoff** (``migrate()``): the source replica checkpoints
  — drains the row at a step boundary and publishes the live state as
  a complete NEW KV epoch before retiring the old one — and the target
  admits from the new epoch with ``start_token == ckpt_tokens``
  (nothing re-derived, nothing re-emitted).  The handoff is gated by
  the ``session.migrate`` chaos site: a drop aborts the handoff and
  the session STAYS ON THE SOURCE, epoch un-bumped.
* **crash migration** (automatic): the target re-pulls the LAST
  COMPLETE KV epoch and fast-forwards — tokens past the checkpoint are
  re-derived on device but suppressed below ``start_token``, so the
  client stream resumes at exactly the next index.

Exactly-once is enforced at the point of record: every admission bumps
the session's OWNERSHIP epoch and ``SessionRecord.accept_token``
rejects emissions from a stale epoch or at a non-next index.  The
step-log tests read ``prefill_executions == 1`` and contiguous token
indices straight off the record.

rpcz: one client span ("Serving"/"Session") roots the whole session;
the prefill leg, every ``kv.ship`` (prefill AND checkpoint) and each
``decode.hop.<replica>`` join it as collective sub-spans — /rpcz shows
a migrated session as one trace with its hops laid end to end.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.observability.span import Span, swap_current_span
from incubator_brpc_tpu.serving import metrics as _metrics
from incubator_brpc_tpu.serving import session as _session
from incubator_brpc_tpu.serving.decode import AdmitError, DecodeService
from incubator_brpc_tpu.serving.prefill import KvShipError, PrefillService
from incubator_brpc_tpu.utils.logging import log_error


class SessionError(RuntimeError):
    """A session failed for good; ``code`` is the ERPC error class the
    caller records (EOVERCROWDED = every replica shed, ELOGOFF = tier
    dead, EINTERNAL = KV ship/pull failure)."""

    def __init__(self, code: int, text: str):
        super().__init__(text)
        self.code = code


class SessionResult:
    __slots__ = ("session", "tokens", "migrations", "prefill_executions",
                 "record")

    def __init__(self, record: _session.SessionRecord):
        self.session = record.session
        self.tokens = list(record.tokens)
        self.migrations = record.migrations
        self.prefill_executions = record.prefill_executions
        self.record = record


class _LegCtl:
    """Driver↔migrate coordination for ONE decode leg: ``pending`` is
    installed BEFORE the source row is cancelled, so when the driver
    wakes on retire it knows a graceful handoff is in flight and waits
    for its checkpoint outcome."""

    __slots__ = ("pending", "handoff", "ckpt", "done", "ok")

    def __init__(self):
        self.pending: Optional[str] = None  # migration reason, or None
        self.handoff = threading.Event()
        self.ckpt = None  # checkpoint dict | AdmitError
        self.done = threading.Event()
        self.ok = False


class SessionChannel:
    """One router over a prefill service and N decode replicas (the
    in-process topology the tests and bench stand up; remote tiers
    swap ``DecodeService`` for its stub behind the same entry points).

    ``coords=(slice, chip)`` orders replica picks mesh_locality-style:
    same-slice replicas are tried first, the admission shed
    (EOVERCROWDED) walks to the next — the same locality preference
    ``client/load_balancer.MeshLocalityLB`` applies to cache shards.
    """

    def __init__(
        self,
        prefill: PrefillService,
        replicas: Sequence[DecodeService],
        coords=None,
        max_hops_per_leg: Optional[int] = None,
    ):
        if not replicas:
            raise ValueError("SessionChannel needs at least one replica")
        self.prefill = prefill
        self.replicas: List[DecodeService] = list(replicas)
        self.coords = coords
        self.max_hops_per_leg = max_hops_per_leg or (4 * len(self.replicas))
        self._lock = threading.Lock()
        self._legs = {}  # session -> (_LegCtl, source DecodeService)
        self.migrations_requested = 0
        self.migrations_aborted = 0

    # ---- replica pick (mesh_locality flavored) ------------------------------
    def _ordered(self, exclude: Optional[DecodeService]) -> List[DecodeService]:
        def rank(r: DecodeService):
            local = (
                self.coords is not None
                and r.coords is not None
                and r.coords[0] == self.coords[0]
            )
            return (0 if local else 1, r.live_sessions())

        return sorted(
            (r for r in self.replicas if not r.dead and r is not exclude),
            key=rank,
        )

    # ---- the blocking driver ------------------------------------------------
    def generate(
        self,
        session: str,
        prompt: str,
        max_tokens: int,
        on_token: Optional[Callable[[int, str], None]] = None,
    ) -> SessionResult:
        """Run one session end to end: prefill ONCE, then decode with
        as many replica hops as overload/death/migration demand.
        Returns the completed SessionResult; raises SessionError when
        the tier cannot finish it (KV unshippable, every replica
        dead/shed)."""
        rec = _session.open_session(session, prompt, max_tokens)
        root = Span.create_client("Serving", "Session")
        prev = swap_current_span(root)
        code = 0
        try:
            self._prefill(rec)
            _metrics.serving_sessions << 1
            self._drive(rec, on_token)
            return SessionResult(rec)
        except SessionError as e:
            code = e.code
            rec.state = _session.FAILED
            rec.error = str(e)
            raise
        finally:
            with self._lock:
                self._legs.pop(session, None)
            swap_current_span(prev)
            if root is not None:
                root.annotate(
                    f"session={session} tokens={len(rec.tokens)} "
                    f"migrations={rec.migrations}"
                )
                root.end(code)

    def _prefill(self, rec: _session.SessionRecord) -> None:
        leg = Span.create_collective("Serving", "prefill")
        try:
            out = self.prefill.prefill_sessions(
                [(rec.session, rec.prompt)], epoch=0
            )[rec.session]
        except KvShipError as e:
            # the no-silent-recompute contract: the ship failure is THE
            # session failure, surfaced as one ERPC-class error
            if leg is not None:
                leg.end(errors.EINTERNAL)
            raise SessionError(
                errors.EINTERNAL, f"prefill KV ship failed: {e}"
            ) from e
        rec.state = _session.PREFILLED
        rec.kv_epoch = out["epoch"]
        rec.n_layers = out["n_layers"]
        rec.kv_bytes = out["kv_bytes"]
        rec.prefill_executions = out["prefill_executions"]
        if leg is not None:
            leg.annotate(f"kv_bytes={out['kv_bytes']}")
            leg.end()

    def _admit(
        self,
        rec: _session.SessionRecord,
        replica: DecodeService,
        on_token,
        start_token: int,
    ) -> _LegCtl:
        """One decode leg: bump the ownership epoch, admit on
        ``replica`` pulling the session's live KV epoch.  Raises
        AdmitError (EOVERCROWDED/ELOGOFF/EINTERNAL) without bumping
        state when the replica refuses."""
        ctl = _LegCtl()
        epoch = rec.epoch + 1  # committed by bump_epoch below on success

        def emit(idx, tok):
            if rec.accept_token(idx, tok, epoch):
                if on_token is not None:
                    on_token(idx, tok)

        def on_finish(ok):
            ctl.ok = ok
            ctl.done.set()

        replica.admit_session(
            session=rec.session,
            kv_epoch=rec.kv_epoch,
            n_layers=rec.n_layers,
            max_tokens=rec.max_tokens,
            start_token=start_token,
            ckpt_tokens=rec.ckpt_tokens,
            emit=emit,
            on_finish=on_finish,
        )
        assert rec.bump_epoch(replica.name) == epoch
        rec.state = _session.DECODING
        with self._lock:
            self._legs[rec.session] = (ctl, replica)
        return ctl

    def _drive(self, rec: _session.SessionRecord, on_token) -> None:
        source: Optional[DecodeService] = None
        hops = 0
        last_refusal = "no live replica"
        first_leg = True
        while True:
            candidates = self._ordered(exclude=source)
            if source is not None and not source.dead:
                candidates.append(source)  # last resort: stay home
            ctl = None
            for replica in candidates:
                if hops >= self.max_hops_per_leg:
                    break
                hops += 1
                leg = Span.create_collective(
                    "Serving", f"decode.hop.{replica.name}"
                )
                try:
                    ctl = self._admit(
                        rec, replica, on_token, start_token=len(rec.tokens)
                    )
                except AdmitError as e:
                    last_refusal = f"{replica.name}: {e}"
                    if leg is not None:
                        leg.end(e.code)
                    continue
                if not first_leg:
                    rec.migrations += 1
                    _metrics.serving_migrations << 1
                    _metrics.serving_prefill_reuse << 1
                first_leg = False
                ctl.done.wait()
                if leg is not None:
                    leg.annotate(
                        f"tokens={len(rec.tokens)}/{rec.max_tokens} "
                        f"ok={ctl.ok}"
                    )
                    leg.end(0 if ctl.ok else errors.ECANCELED)
                source = replica
                break
            if ctl is None:
                raise SessionError(
                    errors.EOVERCROWDED
                    if hops < self.max_hops_per_leg
                    else errors.ETOOMANYFAILS,
                    f"session {rec.session!r}: no replica admitted "
                    f"after {hops} hops (last: {last_refusal})",
                )
            # leg retired — finished, migrating, or crashed?
            if ctl.ok and len(rec.tokens) >= rec.max_tokens:
                rec.state = _session.DONE
                return
            if ctl.pending is not None:
                # graceful handoff: wait for the checkpoint outcome the
                # migrate() caller is publishing
                rec.state = _session.MIGRATING
                ctl.handoff.wait(timeout=60.0)
                if isinstance(ctl.ckpt, dict):
                    rec.kv_epoch = ctl.ckpt["kv_epoch"]
                    rec.ckpt_tokens = ctl.ckpt["ckpt_tokens"]
                    rec.kv_bytes = ctl.ckpt["kv_bytes"]
                    rec.log_migration(
                        {
                            "kind": "graceful",
                            "reason": ctl.pending,
                            "from": source.name,
                            "kv_epoch": rec.kv_epoch,
                            "ckpt_tokens": rec.ckpt_tokens,
                        }
                    )
                else:
                    # checkpoint ship failed: the OLD epoch is intact
                    # (complete-or-absent), fall back to crash-style
                    # re-pull + fast-forward from it
                    rec.log_migration(
                        {
                            "kind": "graceful-fallback",
                            "reason": ctl.pending,
                            "from": source.name,
                            "error": str(ctl.ckpt),
                            "kv_epoch": rec.kv_epoch,
                        }
                    )
            else:
                rec.state = _session.MIGRATING
                rec.log_migration(
                    {
                        "kind": "crash",
                        "from": source.name,
                        "kv_epoch": rec.kv_epoch,
                        "resume_token": len(rec.tokens),
                    }
                )

    # ---- operator/overload-triggered migration ------------------------------
    def migrate(self, session: str, reason: str = "operator") -> bool:
        """Gracefully hand the session off its current replica.  False
        when the handoff was aborted (``session.migrate`` chaos drop,
        or no live leg) — the session stays on the source, ownership
        epoch un-bumped, stream uninterrupted."""
        with self._lock:
            self.migrations_requested += 1
            leg = self._legs.get(session)
        if leg is None:
            return False
        ctl, source = leg
        rec = _session.get_session(session)
        if rec is None:
            return False
        if _chaos.armed:
            spec = _chaos.check("session.migrate", method=session)
            if spec is not None:
                if spec.action == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif spec.action == "drop":
                    with self._lock:
                        self.migrations_aborted += 1
                    rec.log_migration(
                        {
                            "kind": "aborted",
                            "reason": reason,
                            "from": source.name,
                            "chaos": "session.migrate drop",
                        }
                    )
                    return False
        ctl.pending = reason
        ctl.handoff.clear()
        try:
            ctl.ckpt = source.checkpoint_session(session, rec.kv_epoch + 1)
        except AdmitError as e:
            ctl.ckpt = e
            log_error(
                f"session {session!r} checkpoint on {source.name} failed "
                f"({e}); crash-migrating from epoch {rec.kv_epoch}"
            )
        finally:
            ctl.handoff.set()
        return True

    def describe(self) -> dict:
        with self._lock:
            live = {s: src.name for s, (_c, src) in self._legs.items()}
        return {
            "replicas": [r.describe() for r in self.replicas],
            "live_legs": live,
            "migrations_requested": self.migrations_requested,
            "migrations_aborted": self.migrations_aborted,
        }
