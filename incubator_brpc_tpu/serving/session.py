"""Session state + the ``kv:<session>@<epoch>`` naming-tag grammar.

The serving tier's third naming-tag grammar, alongside resharding's
``i/N@E`` partition tags (resharding/migration.py) and replication's
``group@epoch:holder`` lease tags (replication/lease.py).  A session's
KV state lives in the HBM cache tier under one key per layer:

    kv:<session>@<epoch>#<layer>

``epoch`` is the session's OWNERSHIP epoch: it bumps on every decode
admission (initial admit and each migration), so a stale owner's late
writes/tokens are identifiable and a checkpoint handoff publishes a
complete new-epoch key set before the old one is retired —
crash-resumable exactly like resharding's epoch-tagged COPY.  Each
parser returns None for the other grammars, so mixed naming planes
degrade safely (a partition watcher ignores kv tags and vice versa).

``SessionRecord`` is the per-session state machine the router drives:

    PREFILLING → PREFILLED → DECODING ⇄ MIGRATING → DONE | FAILED

with the step-log fields the exactly-once proofs read
(``prefill_executions``, ``migrations``, ``tokens`` by index,
``migration_log``).  The process-global registry feeds the
``/serving`` builtin and the ``serving:`` /status section.

Import-light and jax-free by construction (the builtin and the
metrics lint import this in a bare interpreter).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# session lifecycle states (a plain tuple, not enum — the builtin
# renders them as strings)
PREFILLING = "PREFILLING"
PREFILLED = "PREFILLED"
DECODING = "DECODING"
MIGRATING = "MIGRATING"
DONE = "DONE"
FAILED = "FAILED"


# ---------------------------------------------------------------------------
# the kv:<session>@<epoch>[#<layer>] grammar
# ---------------------------------------------------------------------------

def format_kv_key(session: str, epoch: int, layer: Optional[int] = None) -> bytes:
    """Cache key for one session's KV state at one ownership epoch;
    with ``layer`` the per-layer key the fused DMGET pull enumerates."""
    base = f"kv:{session}@{int(epoch)}"
    if layer is not None:
        base += f"#{int(layer)}"
    return base.encode()


def parse_kv_key(tag) -> Optional[Tuple[str, int, Optional[int]]]:
    """``"kv:<session>@<epoch>[#<layer>]"`` → (session, epoch, layer);
    None for anything else — including the OTHER naming grammars
    (``i/N@E`` partition tags, ``group@epoch:holder`` lease tags), so
    a kv watcher scanning a shared naming plane never misroutes."""
    if isinstance(tag, (bytes, bytearray)):
        try:
            tag = bytes(tag).decode()
        except UnicodeDecodeError:
            return None
    if not isinstance(tag, str) or not tag.startswith("kv:"):
        return None
    body = tag[3:]
    sess, sep, rest = body.rpartition("@")
    if not sep or not sess:
        return None
    layer: Optional[int] = None
    ep_s, lsep, layer_s = rest.partition("#")
    try:
        epoch = int(ep_s)
        if lsep:
            layer = int(layer_s)
    except ValueError:
        return None
    if epoch < 0 or (layer is not None and layer < 0):
        return None
    return sess, epoch, layer


def kv_layer_keys(session: str, epoch: int, n_layers: int) -> List[bytes]:
    """The complete per-layer key set one epoch publishes — what the
    decode admission's fused DMGET pulls in ONE batched lookup."""
    return [format_kv_key(session, epoch, layer) for layer in range(n_layers)]


# ---------------------------------------------------------------------------
# per-session record + process-global registry
# ---------------------------------------------------------------------------

class SessionRecord:
    """One session's serving state; the router is the only writer, so
    a single lock per record suffices.  Token bookkeeping is BY INDEX:
    ``tokens[i]`` is the i-th emitted token, and accepting an emission
    requires ``idx == len(tokens)`` — contiguity and exactly-once are
    enforced at the point of record, not proven after the fact."""

    def __init__(self, session: str, prompt: str, max_tokens: int):
        self.session = session
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.state = PREFILLING
        self.epoch = 0  # ownership epoch; bumps per decode admission
        self.replica = ""  # current decode owner
        self.kv_epoch = 0  # epoch whose key set is live in the cache
        self.n_layers = 0
        self.kv_bytes = 0
        self.prefill_executions = 0
        self.migrations = 0
        self.ckpt_tokens = 0  # tokens folded into the live kv_epoch state
        self.tokens: List[str] = []
        self.migration_log: List[dict] = []
        self.error = ""
        self.created_s = time.time()
        self._lock = threading.Lock()

    def accept_token(self, idx: int, token: str, epoch: int) -> bool:
        """Record token ``idx`` iff it is the NEXT index and comes from
        the CURRENT ownership epoch.  A stale owner (aborted source
        still draining) or a duplicate re-emission is rejected here —
        the exactly-once gate."""
        with self._lock:
            if epoch != self.epoch:
                return False
            if idx != len(self.tokens):
                return False
            self.tokens.append(token)
            return True

    def bump_epoch(self, replica: str) -> int:
        with self._lock:
            self.epoch += 1
            self.replica = replica
            return self.epoch

    def log_migration(self, entry: dict) -> None:
        with self._lock:
            self.migration_log.append(entry)

    def describe(self) -> dict:
        with self._lock:
            return {
                "session": self.session,
                "state": self.state,
                "epoch": self.epoch,
                "replica": self.replica,
                "kv_epoch": self.kv_epoch,
                "n_layers": self.n_layers,
                "kv_bytes": self.kv_bytes,
                "prefill_executions": self.prefill_executions,
                "migrations": self.migrations,
                "tokens": len(self.tokens),
                "max_tokens": self.max_tokens,
                "ckpt_tokens": self.ckpt_tokens,
                "migration_log": list(self.migration_log),
                "error": self.error,
                "age_s": round(time.time() - self.created_s, 3),
            }


_registry: Dict[str, SessionRecord] = {}
_registry_lock = threading.Lock()


def open_session(session: str, prompt: str, max_tokens: int) -> SessionRecord:
    """Register a fresh record (replacing a finished prior session of
    the same id — ids are caller-scoped, re-use is legal)."""
    rec = SessionRecord(session, prompt, max_tokens)
    with _registry_lock:
        _registry[session] = rec
    return rec


def get_session(session: str) -> Optional[SessionRecord]:
    with _registry_lock:
        return _registry.get(session)


def sessions_snapshot() -> Dict[str, dict]:
    """Every registered session's describe() — the ``/serving``
    builtin's payload."""
    with _registry_lock:
        recs = list(_registry.values())
    return {rec.session: rec.describe() for rec in recs}


def clear_registry() -> None:
    """Test isolation hook (process-global state)."""
    with _registry_lock:
        _registry.clear()
