"""Decode plane — KV-pulling session admission over the continuous-
batched DecodeLoop (docs/serving.md).

``DecodeService`` admits a session by pulling its KV stack from the
cache tier in ONE fused DMGET (``get_many`` over the epoch's per-layer
keys), injecting layer 0 as the row's device-resident state
(``DecodeLoop.admit(state=...)``), and joining the PR 6 continuous-
batched loop mid-stream.  Tokens stream to the client over the PR 6
streaming subsystem: a negotiated streamed-RPC front (one
``<idx> <token>`` frame per step) and an SSE front — plus the unary
fallback the bench guard pins at zero on the streamed paths.

Exactly-once across replica hops is BY INDEX: every admission carries
``(ckpt_tokens, start_token)`` — the state it pulls has
``ckpt_tokens`` tokens folded in, and emission is suppressed until
``start_token`` (the crash-migration fast-forward re-derives the
suppressed tokens on device without re-emitting them; a graceful
checkpoint handoff has ``start_token == ckpt_tokens`` and fast-
forwards nothing).

A checkpoint (``checkpoint_session``) drains the row at a step
boundary and publishes the session's CURRENT state as a complete new
KV epoch (layer 0 = live state, upper layers re-adopted by identity —
no copies, no host crossing) before retiring the old epoch's keys:
the crash-resumable handoff discipline — at every instant some
complete epoch is pullable.

Overload is the admission tier's retry-elsewhere contract: a full (or
operator-shed) replica refuses the admission with EOVERCROWDED
(counted through ``server/admission.py note_shed``) and the session
router hops to another replica — the same code path a migration
takes.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.server import admission as _admission
from incubator_brpc_tpu.server.service import Service, ServiceStub, rpc_method
from incubator_brpc_tpu.serving.session import kv_layer_keys
from incubator_brpc_tpu.streaming.generate import DecodeLoop
from incubator_brpc_tpu.streaming.stream import Stream, StreamHandler, StreamOptions


class AdmitError(RuntimeError):
    """Admission refused; ``code`` is the ERPC error the client gets
    (EOVERCROWDED = retry elsewhere, EINTERNAL = KV not pullable,
    ELOGOFF = replica dead)."""

    def __init__(self, code: int, text: str):
        super().__init__(text)
        self.code = code


def _as_state(value, dim: int):
    """A pulled layer value → (dim,) float32 device state.  Identity
    for in-process store hits; uint8 wire values (CacheChannel rows)
    BITCAST on device — the pull path never crosses to host."""
    import jax.numpy as jnp
    from jax import lax

    if isinstance(value, (bytes, bytearray)):  # host-mode store only
        import numpy as np

        return jnp.asarray(np.frombuffer(bytes(value), dtype=np.float32))
    if value.dtype == jnp.uint8:
        return lax.bitcast_convert_type(
            value.reshape(dim, 4), jnp.float32
        ).reshape(dim)
    return value


class _SessionEntry:
    __slots__ = ("session", "row", "layers", "kv_epoch", "ckpt_base",
                 "produced", "retired")

    def __init__(self, session: str, kv_epoch: int, ckpt_base: int, layers):
        self.session = session
        self.row = None
        self.layers = layers  # pulled device arrays (re-shipped at ckpt)
        self.kv_epoch = kv_epoch
        self.ckpt_base = ckpt_base  # tokens folded into the pulled state
        self.produced = 0  # tokens derived by THIS replica's row
        self.retired = threading.Event()


class DecodeService(Service):
    """One decode replica: RPC surface + in-process engine (the router
    drives either through the same entry points).

    EchoRequest.message = JSON ``{"session", "kv_epoch", "n_layers",
    "max_tokens", "start_token", "ckpt_tokens"}`` for ``Admit`` /
    ``AdmitSSE``; ``{"session", "new_epoch"}`` for ``Checkpoint``.
    """

    SERVICE_NAME = "DecodeService"

    def __init__(
        self,
        store,
        loop: Optional[DecodeLoop] = None,
        name: str = "decode-0",
        dim: int = 16,
        max_sessions: int = 32,
        outbox_max_tokens: int = 1024,
        stream_options: Optional[StreamOptions] = None,
        coords=None,
    ):
        self.store = store
        self.loop = loop or DecodeLoop(dim=dim)
        self.name = name
        self.dim = self.loop.dim
        self.max_sessions = max_sessions
        self.outbox_max_tokens = outbox_max_tokens
        self._stream_options = stream_options
        self.coords = coords  # (slice, chip) for locality-ordered picks
        self.overloaded = False  # operator/admission-pressure shed knob
        self.dead = False
        self._lock = threading.Lock()
        self._entries: Dict[str, _SessionEntry] = {}
        # -- step log (the exactly-once and fused-pull proofs) --
        self.admitted_sessions = 0
        self.shed_sessions = 0
        self.kv_pulls = 0
        self.fused_pulls = 0  # pulls that rode the fused DMGET gather
        self.checkpoints = 0
        self.streamed_rows = 0
        self.unary_rows = 0
        self.sse_rows = 0

    def close(self) -> None:
        self.loop.stop()

    def kill(self) -> None:
        """Replica death (the breaker-trip test shape): every live row
        retires failed, future admissions refuse with ELOGOFF."""
        self.dead = True
        self.loop.stop()

    def live_sessions(self) -> int:
        with self._lock:
            return len(self._entries)

    # ---- KV pull ------------------------------------------------------------
    def _pull_kv(self, session: str, kv_epoch: int, n_layers: int):
        """One fused DMGET over the epoch's layer keys → the pulled
        device arrays.  AdmitError(EINTERNAL) when the epoch's key set
        is not complete in the cache (nothing to resume from)."""
        keys = kv_layer_keys(session, kv_epoch, n_layers)
        res = self.store.get_many(keys)
        if isinstance(res, tuple):  # HBMCacheStore: (values, stacked)
            values, stacked = res
            fused = stacked is not None
        else:  # CacheChannel MGetResult
            values = [res.row(i) for i in range(len(keys))]
            fused = res.stacked is not None
        if any(v is None for v in values):
            missing = [
                k.decode("latin1")
                for k, v in zip(keys, values)
                if v is None
            ]
            raise AdmitError(
                errors.EINTERNAL,
                f"kv epoch incomplete in cache: missing {missing}",
            )
        with self._lock:
            self.kv_pulls += 1
            if fused:
                self.fused_pulls += 1
        return [_as_state(v, self.dim) for v in values]

    # ---- admission ----------------------------------------------------------
    def admit_session(
        self,
        session: str,
        kv_epoch: int,
        n_layers: int,
        max_tokens: int,
        start_token: int = 0,
        ckpt_tokens: int = 0,
        emit: Optional[Callable] = None,
        on_finish: Optional[Callable] = None,
    ):
        """Pull the session's KV and join the decode loop.

        ``emit(idx, token)`` fires exactly once per absolute token
        index ≥ ``start_token`` (fast-forward indices are re-derived
        but suppressed); ``on_finish(ok)`` fires once at retire.
        Raises AdmitError — EOVERCROWDED means retry on another
        replica (the admission tier's contract)."""
        if self.dead:
            raise AdmitError(errors.ELOGOFF, f"replica {self.name} is dead")
        if start_token < ckpt_tokens:
            raise AdmitError(
                errors.EREQUEST,
                f"start_token {start_token} < ckpt_tokens {ckpt_tokens}: "
                "would re-emit already-delivered indices",
            )
        with self._lock:
            if self.overloaded or len(self._entries) >= self.max_sessions:
                self.shed_sessions += 1
                shed = True
            else:
                shed = False
        if shed:
            # the unified admission bookkeeping: this shed is visible
            # on /admission and rpc_admission_shed like any tier shed
            _admission.note_shed("DecodeService.Admit", None, "session_cap")
            raise AdmitError(
                errors.EOVERCROWDED,
                f"replica {self.name} overcrowded: retry elsewhere",
            )
        layers = self._pull_kv(session, kv_epoch, n_layers)
        entry = _SessionEntry(session, kv_epoch, ckpt_tokens, layers)
        suppress = start_token - ckpt_tokens

        def loop_emit(tok, row, entry=entry):
            idx = entry.ckpt_base + entry.produced
            entry.produced += 1
            if entry.produced <= suppress:
                return  # fast-forward: re-derived, never re-emitted
            if emit is not None:
                emit(idx, tok)

        def loop_finish(row, ok, entry=entry):
            with self._lock:
                cur = self._entries.get(session)
                if cur is entry:
                    del self._entries[session]
            entry.retired.set()
            if on_finish is not None:
                on_finish(ok)

        with self._lock:
            self._entries[session] = entry
            self.admitted_sessions += 1
        # remaining device steps: one per not-yet-derived token
        entry.row = self.loop.admit(
            session,
            max_tokens - ckpt_tokens,
            loop_emit,
            loop_finish,
            state=layers[0],
        )
        return entry

    # ---- migration drain ----------------------------------------------------
    def checkpoint_session(self, session: str, new_epoch: int) -> dict:
        """Drain the session's row at a step boundary and publish its
        live state as KV epoch ``new_epoch`` (complete set first, THEN
        retire the old epoch's keys — at every instant a complete
        epoch is pullable).  Returns ``{"ckpt_tokens", "kv_epoch",
        "kv_bytes"}``.  AdmitError(EINTERNAL) when the session is not
        here or the checkpoint ship fails (the caller falls back to
        crash-migration from the last complete epoch)."""
        from incubator_brpc_tpu.serving.prefill import (
            KvShipError,
            ship_kv_layers,
        )

        with self._lock:
            entry = self._entries.get(session)
        if entry is None or entry.row is None:
            raise AdmitError(
                errors.EINTERNAL, f"no live session {session!r} on {self.name}"
            )
        entry.row.cancel("migrating: checkpoint handoff")
        if not entry.retired.wait(timeout=30.0):
            raise AdmitError(
                errors.EINTERNAL, f"session {session!r} failed to drain"
            )
        # the drained row's state has ckpt_base + produced tokens
        # folded in; it becomes the new epoch's layer 0, the pulled
        # upper layers re-adopt by identity (zero-copy, zero pulls)
        ckpt_tokens = entry.ckpt_base + entry.produced
        layers = [entry.row.state] + list(entry.layers[1:])
        n_layers = len(entry.layers)
        new_keys = kv_layer_keys(session, new_epoch, n_layers)
        try:
            nbytes = ship_kv_layers(self.store, new_keys, layers)
        except KvShipError as e:
            raise AdmitError(errors.EINTERNAL, str(e)) from e
        for key in kv_layer_keys(session, entry.kv_epoch, n_layers):
            try:
                self.store.delete(key)
            except Exception:  # noqa: BLE001 — stale-epoch garbage is
                # harmless; admissions name their epoch explicitly
                pass
        with self._lock:
            self.checkpoints += 1
        return {
            "ckpt_tokens": ckpt_tokens,
            "kv_epoch": new_epoch,
            "kv_bytes": nbytes,
        }

    def shed_session(self, session: str) -> bool:
        """Admission-pressure eviction of a LIVE session: the row
        retires failed and the client/router hears EOVERCROWDED-shaped
        cancellation — the router's crash-migration path re-homes it
        from the last complete KV epoch."""
        with self._lock:
            entry = self._entries.get(session)
        if entry is None or entry.row is None:
            return False
        entry.row.cancel("shed: replica overcrowded")
        return True

    def describe(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "dead": self.dead,
                "overloaded": self.overloaded,
                "live_sessions": len(self._entries),
                "admitted": self.admitted_sessions,
                "shed": self.shed_sessions,
                "kv_pulls": self.kv_pulls,
                "fused_pulls": self.fused_pulls,
                "checkpoints": self.checkpoints,
                "loop": self.loop.describe(),
            }

    # ---- RPC surface --------------------------------------------------------
    @staticmethod
    def _parse_admit(request):
        req = json.loads(request.message)
        return {
            "session": str(req["session"]),
            "kv_epoch": int(req.get("kv_epoch", 0)),
            "n_layers": int(req.get("n_layers", 1)),
            "max_tokens": int(req.get("max_tokens", 16)),
            "start_token": int(req.get("start_token", 0)),
            "ckpt_tokens": int(req.get("ckpt_tokens", 0)),
        }

    @rpc_method(EchoRequest, EchoResponse)
    def Admit(self, controller, request, response, done):
        try:
            spec = self._parse_admit(request)
        except (ValueError, KeyError, TypeError) as e:
            controller.set_failed(errors.EREQUEST, f"bad admit request: {e}")
            done()
            return
        if controller._remote_stream_settings is None:
            # unary fallback: the whole remaining generation, one
            # response of "<idx> <token>" lines
            self.unary_rows += 1
            lines: List[str] = []

            def emit(idx, tok):
                lines.append(f"{idx} {tok}")

            def finish(ok, controller=controller, response=response):
                if not ok:
                    controller.set_failed(errors.ECANCELED, "decode aborted")
                else:
                    response.message = "\n".join(lines)
                    response.code = len(lines)
                done()

            try:
                self.admit_session(emit=emit, on_finish=finish, **spec)
            except AdmitError as e:
                controller.set_failed(e.code, str(e))
                done()
            return
        outbox = _TokenStream(self.outbox_max_tokens)
        # admission errors must fail the RPC itself, so refuse BEFORE
        # accepting the stream
        try:
            entry = self.admit_session(
                emit=outbox.emit, on_finish=outbox.finish, **spec
            )
        except AdmitError as e:
            controller.set_failed(e.code, str(e))
            done()
            return
        self.streamed_rows += 1
        opts = self._stream_options or StreamOptions()
        stream = Stream.accept(controller, outbox, opts)
        outbox.stream = stream
        outbox.row = entry.row
        response.message = "streaming"
        response.code = spec["max_tokens"]
        done()  # response (stream settings) precedes the first frame
        outbox.release()

    @rpc_method(EchoRequest, EchoResponse)
    def AdmitSSE(self, controller, request, response, done):
        """SSE front: ``data: <idx> <token>`` per step on a chunked
        text/event-stream response, ``data: [DONE]`` then close."""
        try:
            spec = self._parse_admit(request)
        except (ValueError, KeyError, TypeError) as e:
            controller.set_failed(errors.EREQUEST, f"bad admit request: {e}")
            done()
            return
        self.sse_rows += 1
        pa = controller.create_progressive_attachment(
            content_type="text/event-stream"
        )
        backlog_cap = max(64, self.outbox_max_tokens) * 64

        def emit(idx, tok, pa=pa):
            if pa.backlog_bytes() > backlog_cap:
                raise RuntimeError("sse client too slow: backlog over cap")
            if pa.write(f"data: {idx} {tok}\n\n") != 0:
                raise RuntimeError("sse client gone")

        def finish(ok, pa=pa):
            if ok:
                pa.write("data: [DONE]\n\n")
            pa.close()

        try:
            self.admit_session(emit=emit, on_finish=finish, **spec)
        except AdmitError as e:
            controller.set_failed(e.code, str(e))
        done()

    @rpc_method(EchoRequest, EchoResponse)
    def Checkpoint(self, controller, request, response, done):
        try:
            req = json.loads(request.message)
            session = str(req["session"])
            new_epoch = int(req["new_epoch"])
        except (ValueError, KeyError, TypeError) as e:
            controller.set_failed(errors.EREQUEST, f"bad checkpoint: {e}")
            done()
            return
        try:
            out = self.checkpoint_session(session, new_epoch)
        except AdmitError as e:
            controller.set_failed(e.code, str(e))
            done()
            return
        response.message = json.dumps(out)
        done()


class _TokenStream(StreamHandler):
    """Streamed-Admit glue: the same bounded-outbox discipline as
    ``streaming/generate._StreamSession`` (order-preserving queue, flow
    -control blocking off the decode thread), carrying ``<idx> <tok>``
    frames.  Emissions before the stream is accepted buffer in the
    queue and drain at ``release()``."""

    def __init__(self, max_tokens_queued: int):
        from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue

        self._max_queued = max_tokens_queued
        self._q = ExecutionQueue(self._drain)
        self._lock = threading.Lock()
        self._depth = 0
        self._dead = False
        self._ready = threading.Event()
        self.stream: Optional[Stream] = None
        self.row = None

    def release(self) -> None:
        self._ready.set()
        self._q.execute(("nop", None))

    def emit(self, idx: int, token: str) -> None:
        with self._lock:
            if self._dead:
                if self.row is not None:
                    self.row.cancel("stream gone")
                return
            self._depth += 1
            if self._depth > self._max_queued:
                self._dead = True
                if self.row is not None:
                    self.row.cancel("slow consumer: outbox overflow")
                return
        self._q.execute(("tok", f"{idx} {token}"))

    def finish(self, ok: bool) -> None:
        self._q.execute(("fin", ok))

    def _drain(self, batch) -> None:
        self._ready.wait(timeout=30.0)
        for kind, val in batch:
            stream = self.stream
            if kind == "nop":
                continue
            if kind == "tok":
                with self._lock:
                    self._depth -= 1
                    if self._dead:
                        continue
                rc = stream.write(val) if stream is not None else errors.ECLOSE
                if rc != 0:
                    with self._lock:
                        self._dead = True
                    if self.row is not None:
                        self.row.cancel(f"stream write failed: {rc}")
            else:
                ok = val
                with self._lock:
                    dead, self._dead = self._dead, True
                if stream is not None and not dead:
                    if ok:
                        stream.close()
                    else:
                        reason = (
                            getattr(self.row, "cancel_reason", "")
                            or "decode aborted"
                        )
                        code = (
                            errors.EOVERCROWDED
                            if "overcrowded" in reason
                            else errors.ECANCELED
                        )
                        stream.reset(code, reason)

    def on_closed(self, stream: Stream) -> None:
        with self._lock:
            self._dead = True
        if self.row is not None:
            self.row.cancel("client closed stream")

    def on_failed(self, stream: Stream, code: int, text: str) -> None:
        with self._lock:
            self._dead = True
        if self.row is not None:
            self.row.cancel(f"stream failed: {text}")


def decode_stub(channel) -> ServiceStub:
    return ServiceStub(channel, DecodeService)
