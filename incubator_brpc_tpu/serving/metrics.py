"""Disaggregated-serving metrics (``rpc_serving_*``; registered at
import — module listed in analysis.invariants.METRIC_MODULES so the
metrics lint render-checks them; docs/serving.md).

Counts, never timing — the proofs the serving tier makes are
arithmetic:

- ``rpc_serving_sessions``       sessions opened through the router
  (one per ``SessionChannel.generate``; a session that migrates N
  times still counts ONCE here).
- ``rpc_serving_migrations``     completed decode-replica hops: the
  target replica re-pulled the SAME cached KV and resumed emission.
- ``rpc_serving_kv_bytes``       KV bytes shipped HBM→HBM into the
  cache tier (prefill ships + migration checkpoints; adds read
  ``.nbytes`` metadata only — never the arrays).
- ``rpc_serving_prefill_reuse``  decode admissions that pulled
  EXISTING KV instead of recomputing prefill — every admission beyond
  a session's first.  ``prefill_reuse ≥ migrations`` on a healthy
  tier; a reuse count stuck at 0 under migration load means prefill
  is silently re-executing.

Import-light and jax-free by construction (the lint imports this
module in a bare interpreter).
"""

from __future__ import annotations

from incubator_brpc_tpu.metrics.reducer import Adder

serving_sessions = Adder(0).expose("rpc_serving_sessions")
serving_migrations = Adder(0).expose("rpc_serving_migrations")
serving_kv_bytes = Adder(0).expose("rpc_serving_kv_bytes")
serving_prefill_reuse = Adder(0).expose("rpc_serving_prefill_reuse")


def snapshot() -> dict:
    """Current counter values (the /status ``serving:`` line and the
    ``/serving`` builtin read this)."""
    return {
        "sessions": serving_sessions.get_value(),
        "migrations": serving_migrations.get_value(),
        "kv_bytes": serving_kv_bytes.get_value(),
        "prefill_reuse": serving_prefill_reuse.get_value(),
    }
