"""Prefill plane — batched sharded prompt prefill producing
HBM-resident KV state (docs/serving.md).

``PrefillService`` runs prompt prefill as ONE padded batched device
execution (the PR 5 bucket discipline; a mesh upgrades the layer GEMMs
to ``batching/sharded.py`` ShardedFusedKernel executions with one
collective merge each) and ships the resulting per-session KV stack
HBM→HBM into the cache tier under ``kv:<session>@<epoch>#<layer>``
keys (serving/session.py grammar).  Three load-bearing properties:

* **Zero host crossings.**  Layer arrays go kernel → ``store.set``;
  the HBM store adopts raw device arrays by identity and the
  CacheChannel ships them as DeviceRef segments — witness-armed tests
  prove the whole prefill→cache→decode path pulls nothing to host.
* **Layer 0 IS the decode state.**  The KV stack's first layer is the
  prompt-derived recurrence state ``DecodeLoop.admit`` would compute,
  so a decode pod admitting with pulled KV continues the EXACT token
  sequence the monolithic ``GenerateService`` would emit — the
  disagg-vs-monolith equivalence tests ride this.
* **A KV epoch is complete or absent.**  Layers ship in order and a
  failed ship (the ``kv.ship`` chaos site, budget overflow, a cache
  error) deletes the epoch's already-shipped keys before surfacing
  ONE ERPC error to the client — never a silent recompute, and never
  a partial key set a decode admission could half-pull.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.batching.fused import FusedKernel
from incubator_brpc_tpu.batching.policy import BatchPolicy
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.observability.profiling import hbm_account, kernel_section
from incubator_brpc_tpu.observability.span import Span
from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.server.service import Service, ServiceStub, rpc_method
from incubator_brpc_tpu.serving import metrics as _metrics
from incubator_brpc_tpu.serving.session import kv_layer_keys

# Prefill-window contract: fuse up to 32 concurrent prompts per padded
# execution (same buckets as the decode loop's GenPolicy).
PrefillPolicy = BatchPolicy(
    max_batch_size=32,
    max_wait_us=0,
    padding_buckets=(1, 2, 4, 8, 16, 32),
)

# the shipped KV stacks charge the HBM ledger under their own tag
# until the cache store adopts them (the store re-charges under
# cache.values) — /hotspots/hbm shows what prefill pins in flight
_KV_ACCT = hbm_account("serving.prefill_kv")


class KvShipError(RuntimeError):
    """A KV SET into the cache tier failed (chaos drop, budget, cache
    error).  Callers surface it as ONE ERPC failure — never a silent
    local recompute."""


def prompt_seed_state(prompt: str, dim: int) -> np.ndarray:
    """EXACTLY ``DecodeLoop.admit``'s prompt-derived init — layer 0 of
    the KV stack must be bit-identical so decode-with-pulled-KV
    continues the monolithic token sequence."""
    seed = int.from_bytes(
        hashlib.blake2s(prompt.encode(), digest_size=8).digest(), "big"
    )
    rng = np.random.default_rng(seed)
    return rng.standard_normal(dim).astype(np.float32)


def ship_kv_layers(store, keys: Sequence[bytes], layers: Sequence) -> int:
    """Ship one complete epoch key set into the cache tier, in layer
    order, each SET gated by the ``kv.ship`` chaos site.  Returns the
    bytes shipped.  On ANY failure the already-shipped keys of this
    epoch are deleted first (complete-or-absent), then KvShipError
    raises — the caller maps it to an ERPC error."""
    span = Span.create_collective("Serving", "kv.ship")
    shipped: List[bytes] = []
    nbytes = 0
    try:
        for key, arr in zip(keys, layers):
            if _chaos.armed:
                spec = _chaos.check("kv.ship", method=key.decode("latin1"))
                if spec is not None:
                    if spec.action == "delay_us":
                        _chaos.sleep_us(spec.arg)
                    elif spec.action == "drop":
                        raise KvShipError(
                            f"kv.ship dropped for {key.decode('latin1')}"
                        )
            try:
                ok = store.set(key, arr)
            except Exception as e:  # noqa: BLE001 — cache-tier error
                raise KvShipError(f"kv set failed for {key!r}: {e}") from e
            if ok is False:  # HBM store: value over budget
                raise KvShipError(f"kv value over cache budget: {key!r}")
            shipped.append(key)
            nbytes += int(arr.nbytes)
        if span is not None:
            span.annotate(f"shipped {len(shipped)} layers {nbytes}B")
        _metrics.serving_kv_bytes << nbytes
        return nbytes
    except KvShipError:
        for key in shipped:
            try:
                store.delete(key)
            except Exception:  # noqa: BLE001 — best-effort unship; a
                # leftover key from a dead epoch is garbage, not a
                # correctness hazard (admissions pull complete sets)
                pass
        raise
    finally:
        if span is not None:
            span.end()


class PrefillService(Service):
    """The prefill pod's RPC surface + in-process engine.

    ``store`` is the cache tier: an ``HBMCacheStore`` (co-resident
    pod; raw-array identity adoption) or a ``CacheChannel`` (remote
    tier; DeviceRef zero-copy over ICI) — anything with
    ``set/delete``.  ``mesh`` upgrades the layer GEMMs to sharded
    executions (``ShardedFusedKernel``); without one the fused
    single-chip kernel runs the same math.

    EchoRequest.message = JSON ``{"session", "prompt"}``;
    EchoResponse.message = JSON ``{"session", "epoch", "n_layers",
    "dim", "kv_bytes", "prefill_executions"}``.
    """

    SERVICE_NAME = "PrefillService"

    def __init__(
        self,
        store,
        dim: int = 16,
        n_layers: int = 4,
        mesh=None,
        policy: Optional[BatchPolicy] = None,
    ):
        if n_layers < 1:
            raise ValueError("n_layers must be >= 1")
        self.store = store
        self.dim = dim
        self.n_layers = n_layers
        self.policy = policy or PrefillPolicy
        self._lock = threading.Lock()
        # deterministic toy "model": same W as the decode loop (seeded
        # 1234) so layer hops and decode steps share one recurrence
        rng = np.random.default_rng(1234)
        self._w = (rng.standard_normal((dim, dim)) / np.sqrt(dim)).astype(
            np.float32
        )
        self._w_dev = None
        self._sharded = None
        if mesh is not None:
            from incubator_brpc_tpu.batching.sharded import ShardedFusedKernel

            self._sharded = ShardedFusedKernel(
                mesh, label="PrefillService.Prefill"
            )
            self._w_dev = self._sharded.shard_param(self._w)
        self._kernel = FusedKernel(
            self._layers_fn(n_layers),
            label="prefill.layers",
            batch_buckets=self.policy.padding_buckets or None,
        )
        # -- step log (tests + /serving assertions; counts, not time) --
        self.batches = 0  # padded prefill executions
        self.sessions_prefilled = 0
        self.prefill_executions: Dict[str, int] = {}  # per session id
        self.ship_failures = 0

    # ---- the batched layer stack -------------------------------------------
    @staticmethod
    def _layers_fn(n_layers: int):
        def layers(w, s):
            import jax.numpy as jnp

            out = [s]
            cur = s
            for _ in range(n_layers - 1):
                cur = jnp.tanh(cur @ w)
                out.append(cur)
            return jnp.stack(out)  # (n_layers, bucket, dim)

        return layers

    def _ensure_w(self):
        if self._w_dev is None:
            import jax

            self._w_dev = jax.device_put(self._w)
        return self._w_dev

    def prewarm(self) -> None:
        """Trace the prefill kernel at every bucket so no jit compile
        lands inside a serving (or measured) window."""
        import jax.numpy as jnp

        if self._sharded is not None:
            return  # sharded GEMMs trace per bucket on first use
        w = self._ensure_w()
        for b in self.policy.padding_buckets or (self.policy.max_batch_size,):
            self._kernel(w, jnp.zeros((b, self.dim), jnp.float32))

    def _layer_stack(self, seeds: np.ndarray):
        """(B, dim) host seeds → (n_layers, bucket, dim) device stack,
        ONE padded fused execution (or n_layers-1 sharded GEMM+merge
        executions on a mesh)."""
        import jax
        import jax.numpy as jnp

        n = seeds.shape[0]
        pad_to = self.policy.bucket_for(n)
        if pad_to > n:
            seeds = np.concatenate(
                [seeds, np.zeros((pad_to - n, self.dim), np.float32)]
            )
        with kernel_section("prefill.layers"):
            if self._sharded is not None:
                cur = jax.device_put(seeds)
                out = [cur]
                for _ in range(self.n_layers - 1):
                    cur = jnp.tanh(self._sharded(self._w_dev, cur))
                    out.append(cur)
                return jnp.stack(out)
            return self._kernel(self._ensure_w(), jnp.asarray(seeds))

    # ---- the engine ---------------------------------------------------------
    def prefill_sessions(
        self, requests: Sequence[Tuple[str, str]], epoch: int = 0
    ) -> Dict[str, dict]:
        """Prefill a window of (session, prompt) pairs as ONE batched
        execution, ship each session's KV stack, return per-session
        ``{"epoch", "n_layers", "dim", "kv_bytes", "prefill_executions"}``.
        Raises KvShipError on a failed ship (after unshipping the
        failed session's partial epoch) — the RPC surface maps it to
        EINTERNAL, and the router NEVER retries it silently."""
        if not requests:
            return {}
        seeds = np.stack(
            [prompt_seed_state(prompt, self.dim) for _, prompt in requests]
        )
        stack = self._layer_stack(seeds)
        charge = _KV_ACCT.adopt(stack)
        try:
            with self._lock:
                self.batches += 1
            out: Dict[str, dict] = {}
            for i, (session, _prompt) in enumerate(requests):
                keys = kv_layer_keys(session, epoch, self.n_layers)
                layers = [stack[layer, i] for layer in range(self.n_layers)]
                try:
                    nbytes = ship_kv_layers(self.store, keys, layers)
                except KvShipError:
                    with self._lock:
                        self.ship_failures += 1
                    raise
                with self._lock:
                    self.sessions_prefilled += 1
                    count = self.prefill_executions.get(session, 0) + 1
                    self.prefill_executions[session] = count
                out[session] = {
                    "session": session,
                    "epoch": epoch,
                    "n_layers": self.n_layers,
                    "dim": self.dim,
                    "kv_bytes": nbytes,
                    "prefill_executions": count,
                }
            return out
        finally:
            _KV_ACCT.release(charge)

    # ---- RPC surface --------------------------------------------------------
    @rpc_method(EchoRequest, EchoResponse)
    def Prefill(self, controller, request, response, done):
        try:
            req = json.loads(request.message)
            session = str(req["session"])
            prompt = str(req["prompt"])
        except (ValueError, KeyError, TypeError) as e:
            controller.set_failed(errors.EREQUEST, f"bad prefill request: {e}")
            done()
            return
        try:
            result = self.prefill_sessions(
                [(session, prompt)], epoch=int(req.get("epoch", 0))
            )
        except KvShipError as e:
            # the ERPC-not-silent-recompute contract: the client hears
            # about the failed ship and decides (docs/serving.md)
            controller.set_failed(errors.EINTERNAL, str(e))
            done()
            return
        response.message = json.dumps(result[session])
        done()


def prefill_stub(channel) -> ServiceStub:
    return ServiceStub(channel, PrefillService)
