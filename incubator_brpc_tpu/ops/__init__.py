"""Device-side ops (Pallas/jnp) for the TPU data plane: bulk transfer,
checksums, response merging. These are the hot ops of the framework —
the analog of the reference's writev/crc32c/memcpy inner loops, mapped
onto HBM/VMEM DMA and the VPU instead of the kernel's socket stack."""
