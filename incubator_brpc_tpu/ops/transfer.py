"""Bulk payload movement on device — the ICI engine's copy path.

The reference's bulk data path is writev/RDMA WRITE of IOBuf blocks
(socket.cpp:1643, rdma/rdma_endpoint.cpp); on TPU the equivalent hot op
is HBM→HBM movement staged through VMEM. ``device_copy`` is a Pallas
kernel with a pipelined grid (the pipeline emitter double-buffers the
HBM→VMEM→HBM DMAs automatically — the guide's double-buffering pattern
without hand-rolled semaphores); it is what the ICI endpoint uses to
"transmit" a payload buffer within a chip, and the unit the ring
streaming path repeats per hop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _copy_kernel(in_ref, out_ref):
    out_ref[:] = in_ref[:]


@functools.partial(jax.jit, static_argnames=("chunk_rows",))
def device_copy(x: jax.Array, chunk_rows: int = 256) -> jax.Array:
    """HBM→HBM copy through VMEM with a pipelined (auto double-buffered)
    grid. x must be 2D with last dim a multiple of 128."""
    m, n = x.shape
    rows = min(chunk_rows, m)
    while m % rows:
        rows //= 2
    rows = max(rows, 1)
    grid = (m // rows,)
    return pl.pallas_call(
        _copy_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((rows, n), lambda i: (i, 0), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((rows, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )(x)


def _copy_csum_kernel(in_ref, out_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    blk = in_ref[:]
    out_ref[:] = blk
    # running checksum per lane-column, folded on host side; f32 sum is
    # the VPU-friendly stand-in for the reference's crc32c framing check
    acc_ref[:] += jnp.sum(blk.astype(jnp.float32), axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("chunk_rows", "interpret"))
def device_copy_with_checksum(
    x: jax.Array, chunk_rows: int = 256, interpret: bool = False
):
    """Fused transmit-and-verify: copies the payload and produces a
    per-lane checksum in one pass over HBM (one read instead of two).
    ``interpret=True`` runs the SAME kernel through the Pallas
    interpreter — the off-TPU compile gates exercise the real op's
    semantics instead of a lookalike (pallas_guide: interpret mode)."""
    m, n = x.shape
    rows = min(chunk_rows, m)
    while m % rows:
        rows //= 2
    rows = max(rows, 1)
    grid = (m // rows,)
    # one spec construction for both paths: only memory_space differs
    # (the interpreter has no VMEM)
    ms = {} if interpret else {"memory_space": pltpu.VMEM}
    kw = {"interpret": True} if interpret else {}
    in_specs = [pl.BlockSpec((rows, n), lambda i: (i, 0), **ms)]
    out_specs = (
        pl.BlockSpec((rows, n), lambda i: (i, 0), **ms),
        pl.BlockSpec((1, n), lambda i: (0, 0), **ms),
    )
    out, acc = pl.pallas_call(
        _copy_csum_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        **kw,
    )(x)
    return out, jnp.sum(acc)


@jax.jit
def _xla_copy(x: jax.Array) -> jax.Array:
    # jit output cannot alias the (undonated) input, so XLA emits a real
    # HBM traversal — the fallback "transmission" for shapes/dtypes the
    # Pallas kernel doesn't tile.
    return jnp.copy(x)


def _on_tpu(arr) -> bool:
    try:
        return all(d.platform == "tpu" for d in arr.devices())
    except Exception:  # noqa: BLE001 — non-jax array-likes
        return False


def transmit_array(arr):
    """One ICI "transmission" of an HBM payload: the op the fabric runs
    per device segment on same-chip delivery (the analog of the wire hop
    RDMA WRITE performs; rdma/rdma_endpoint.cpp CutFromIOBufList).

    Runs the fused Pallas copy+checksum when the array tiles onto the
    VPU lanes, an XLA copy otherwise (and always off-TPU, where the
    Mosaic kernel can't run). Returns ``(new_array, checksum_or_None)``;
    nothing here syncs to host — the checksum stays device-resident.
    """
    use_pallas = _on_tpu(arr) and jnp.issubdtype(arr.dtype, jnp.number)
    if use_pallas:
        if arr.ndim == 2 and arr.shape[1] % _LANE == 0 and arr.shape[0] > 0:
            return device_copy_with_checksum(arr)
        total = arr.size
        if total > 0 and total % _LANE == 0:
            return _transmit_reshaped(arr)
    return _xla_copy(arr), None


@jax.jit
def _transmit_reshaped(x: jax.Array):
    total = x.size
    lanes = next(m for m in (4096, 2048, 1024, 512, 256, 128) if total % m == 0)
    out, csum = device_copy_with_checksum(x.reshape(total // lanes, lanes))
    return out.reshape(x.shape), csum
